"""Sort execs (global sort; device lexicographic sort on orderable keys).

[REF: sql-plugin/../GpuSortExec.scala :: GpuSortExec, SortUtils.scala] —
the reference calls cuDF's multi-key radix/merge sort; here the device
sort is one stable ``lax.sort`` over the orderable key limbs from
ops/ordering.py (direction and null placement baked into the encoding),
with the whole partition coalesced first (RequireSingleBatch goal, as the
reference's total-order sort requires).  Out-of-core (spill-merge) sort is
a later phase (SURVEY §2.1 #16).
"""

from __future__ import annotations

from typing import Iterator, List, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar import host as H
from spark_rapids_tpu.columnar.column import DeviceBatch, compact
from spark_rapids_tpu.exec.base import CpuExec, TpuExec
from spark_rapids_tpu.exec.basic import concat_device_batches
from spark_rapids_tpu.ops import ordering as ORD
from spark_rapids_tpu.plan.logical import SortOrder


class CpuSortExec(CpuExec):
    """Numpy-oracle global sort (gathers all partitions)."""

    def __init__(self, orders: Sequence[SortOrder], child: CpuExec):
        super().__init__(child.schema, child)
        self.orders = list(orders)

    def node_string(self):
        return f"Sort [{', '.join(str(o.expr) for o in self.orders)}]"

    def num_partitions(self) -> int:
        return 1

    def execute(self, partition: int) -> Iterator[H.HostBatch]:
        child = self.children[0]
        batches = [b for p in range(child.num_partitions())
                   for b in child.execute(p)]
        if not batches:
            return
        merged = _concat_host(self.schema, batches)
        limbs: List[np.ndarray] = []
        for o in self.orders:
            c = o.expr.eval_cpu(merged)
            limbs.extend(ORD.np_order_keys(
                c.data, c.validity, c.dtype, o.ascending, o.nulls_first))
        n = merged.num_rows
        limbs.append(np.arange(n, dtype=np.int64).view(np.uint64))  # stable
        perm = np.lexsort(list(reversed(limbs)))
        cols = [H.HostCol(c.dtype, c.data[perm],
                          None if c.validity is None else c.validity[perm])
                for c in merged.columns]
        yield H.HostBatch(self.schema, cols)


def _concat_host(schema, batches: List[H.HostBatch]) -> H.HostBatch:
    if len(batches) == 1:
        return batches[0]
    cols = []
    for i, f in enumerate(schema.fields):
        any_val = any(b.columns[i].validity is not None for b in batches)
        data = np.concatenate([b.columns[i].data for b in batches])
        validity = None
        if any_val:
            validity = np.concatenate([
                b.columns[i].validity if b.columns[i].validity is not None
                else np.ones(len(b.columns[i].data), bool)
                for b in batches])
        cols.append(H.HostCol(f.dtype, data, validity))
    return H.HostBatch(schema, cols)


class TpuSortExec(TpuExec):
    """[REF: GpuSortExec] — single lax.sort over encoded key limbs."""

    def __init__(self, orders: Sequence[SortOrder], child: TpuExec):
        super().__init__(child.schema, child)
        self.orders = list(orders)

    def node_string(self):
        return f"TpuSort [{', '.join(str(o.expr) for o in self.orders)}]"

    def num_partitions(self) -> int:
        return 1

    def execute(self, partition: int) -> Iterator[DeviceBatch]:
        child = self.children[0]
        batches = [compact(b) for p in range(child.num_partitions())
                   for b in child.execute(p)]
        if not batches:
            return
        with self.timer():
            merged = concat_device_batches(self.schema, batches)
            yield sort_batch(merged, self.orders)
        self.metric("numOutputBatches").add(1)


def sort_batch(batch: DeviceBatch, orders: Sequence[SortOrder]
               ) -> DeviceBatch:
    """Stable sort of live rows by the given orders; dead rows to the end.

    One cached jitted kernel per (orders, schema) — compiles once per
    bucket and stays hot across queries."""
    from spark_rapids_tpu.runtime.kernel_cache import (
        cached_kernel, fingerprint)
    fn = cached_kernel(
        ("sort", fingerprint(list(orders)), fingerprint(batch.schema)),
        lambda: (lambda b: _sort_batch_impl(b, orders)))
    return fn(batch)


def _sort_batch_impl(batch: DeviceBatch, orders: Sequence[SortOrder]
                     ) -> DeviceBatch:
    parts = [ORD._flag_part(~batch.sel)]
    for o in orders:
        c = o.expr.eval_tpu(batch)
        parts.extend(ORD.column_order_parts(c, o.ascending, o.nulls_first))
    _, perm = ORD.sort_by_keys(ORD.fuse_parts(parts))
    cols = tuple(c.gather(perm) for c in batch.columns)
    sel = jnp.take(batch.sel, perm)
    return DeviceBatch(batch.schema, cols, sel)


def _tag_sort(meta):
    meta.tag_expressions([o.expr for o in meta.cpu.orders])


def _convert_sort(cpu, ch, conf):
    return TpuSortExec(cpu.orders, ch[0])
