"""Basic physical operators: scan, project, filter, limit, union, range.

[REF: sql-plugin/../basicPhysicalOperators.scala :: GpuProjectExec,
 GpuFilterExec, GpuRangeExec; GpuUnionExec; limit execs in
 sql-plugin/../limit.scala]

TPU-first notes:
* ``TpuFilterExec`` never changes shapes — it ANDs the predicate into the
  batch ``sel`` mask (null predicate = drop row, Spark semantics).
  Compaction happens only at deliberate boundaries (shuffle/host transfer).
* ``TpuProjectExec`` evaluates the bound expression tree; XLA fuses the
  whole projection into one program per (schema, bucket) via jit caching
  inside the expression kernels.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar import host as H
from spark_rapids_tpu.columnar.column import (
    DeviceBatch, DeviceColumn, host_to_device, round_up_pow2)
from spark_rapids_tpu.exec.base import CpuExec, ExecNode, TpuExec
from spark_rapids_tpu.ops.expressions import Expression


def _slice_table(table: pa.Table, num_partitions: int) -> List[pa.Table]:
    n = table.num_rows
    if num_partitions <= 1:
        return [table]
    step = (n + num_partitions - 1) // num_partitions
    out = []
    for i in range(num_partitions):
        lo = min(i * step, n)
        out.append(table.slice(lo, min(step, n - lo)))
    return out


class CpuScanExec(CpuExec):
    """In-memory arrow table scan → HostBatch per partition slice."""

    def __init__(self, table: pa.Table, schema: T.StructType,
                 num_partitions: int = 1, batch_rows: int = 1 << 20):
        super().__init__(schema)
        self.table = table
        self._num_partitions = num_partitions
        self.batch_rows = batch_rows

    def num_partitions(self) -> int:
        return self._num_partitions

    def estimated_size_bytes(self):
        return self.table.nbytes

    def execute(self, partition: int) -> Iterator[H.HostBatch]:
        part = _slice_table(self.table, self._num_partitions)[partition]
        for lo in range(0, max(part.num_rows, 1), self.batch_rows):
            chunk = part.slice(lo, self.batch_rows)
            if chunk.num_rows == 0 and lo > 0:
                break
            with self.timer():
                b = H.from_arrow_table(chunk)
                b = H.HostBatch(self.schema, b.columns)
            self.metric("numOutputRows").add(b.num_rows)
            self.metric("numOutputBatches").add(1)
            yield b


import threading
import weakref

# Device-resident cache for in-memory relations: repeated executions of a
# query over the same table skip the H2D transfer (the steady-state regime
# the reference benchmarks — inter-stage data stays on device there; here
# the analog of Spark's columnar cache).  Entries die with their table.
_scan_cache: dict = {}
_scan_cache_lock = threading.Lock()


def _scan_cache_get(table: pa.Table, key):
    ent = _scan_cache.get(id(table))
    return None if ent is None else ent.get(key)


def _scan_cache_evict(tid):
    with _scan_cache_lock:
        entries = _scan_cache.pop(tid, None)
    if entries:
        for pairs in entries.values():
            for sp, _ in pairs:
                sp.close()  # release arbiter accounting + spill files


def clear_scan_cache():
    """Evict every cached scan (e.g. when the budget arbiter is
    replaced — registrations against the old arbiter would go stale)."""
    for tid in list(_scan_cache):
        _scan_cache_evict(tid)


def _scan_cache_put(table: pa.Table, key, batches):
    tid = id(table)
    with _scan_cache_lock:
        if tid not in _scan_cache:
            try:
                weakref.finalize(table, _scan_cache_evict, tid)
            except TypeError:
                return
            _scan_cache[tid] = {}
        if key in _scan_cache[tid]:
            # lost a build race: a preempted builder parked mid-scan
            # while a concurrent query built the same entry.  Readers
            # may already hold the installed list, so first-put wins —
            # close our duplicates instead of orphaning theirs.
            losers = batches
        else:
            _scan_cache[tid][key] = batches
            return
    for sp, _ in losers:
        sp.close()


class TpuScanExec(TpuExec):
    """In-memory arrow table scan → padded DeviceBatch per partition.

    The H2D transfer point [REF: GpuRowToColumnarExec.scala] — in this
    engine scans land device-resident batches directly.
    """

    def __init__(self, table: pa.Table, schema: T.StructType,
                 num_partitions: int = 1, batch_rows: int = 1 << 20,
                 min_bucket: int = 1024,
                 executor: Tuple[int, int] = (0, 1)):
        super().__init__(schema)
        self.table = table
        self._num_partitions = num_partitions
        self.batch_rows = batch_rows
        self.min_bucket = min_bucket
        # (executor_id, executor_count): in multi-executor mode each
        # process serves only source partitions p ≡ id (mod count) — the
        # analog of the Spark scheduler assigning scan tasks to
        # executors; the union over processes is exactly the table
        self.executor = tuple(executor)

    def num_partitions(self) -> int:
        return self._num_partitions

    def execute(self, partition: int) -> Iterator[DeviceBatch]:
        eid, ecount = self.executor
        if ecount > 1 and partition % ecount != eid:
            return
        from spark_rapids_tpu.runtime.memory import (
            RetryOOM, SpillableBatch, get_manager)
        key = (self._num_partitions, self.batch_rows, self.min_bucket,
               partition)
        cached = _scan_cache_get(self.table, key)
        if cached is not None:
            for bi, (sp, nrows) in enumerate(cached):
                try:
                    # restores the batch if the arbiter spilled it
                    restored = sp.get()
                except RetryOOM:
                    # no room to restore: drop the cache and stream the
                    # REMAINDER of the partition straight from the arrow
                    # table (earlier entries were already yielded — never
                    # restart from batch 0, that duplicates rows)
                    _scan_cache_evict(id(self.table))
                    yield from self._stream(partition, register=False,
                                            start_batch=bi)
                    return
                self.metric("numOutputRows").add(nrows)
                self.metric("numOutputBatches").add(1)
                yield restored
            return
        yield from self._stream(partition, key, register=True)

    def _stream(self, partition: int, key=None, register: bool = False,
                start_batch: int = 0) -> Iterator[DeviceBatch]:
        from spark_rapids_tpu.runtime.memory import (
            RetryOOM, SpillableBatch, get_manager)
        out = []
        part = _slice_table(self.table, self._num_partitions)[partition]
        start = start_batch * self.batch_rows
        if start and start >= part.num_rows:
            return
        for lo in range(start, max(part.num_rows, 1), self.batch_rows):
            chunk = part.slice(lo, self.batch_rows)
            if chunk.num_rows == 0 and lo > 0:
                break
            with self.timer():
                b = host_to_device(chunk, min_bucket=self.min_bucket)
                b = DeviceBatch(self.schema, b.columns, b.sel,
                                compacted=True)
            # row count is known host-side — NEVER sync the device here
            # (any D2H permanently degrades tunnel dispatch latency)
            nrows = chunk.num_rows
            self.metric("numOutputRows").add(nrows)
            self.metric("numOutputBatches").add(1)
            if register and out is not None:
                # device-resident cache entries are the arbiter's
                # reclaim pool: under pressure they spill host-side and
                # restore transparently on the next scan.  Registration
                # is best-effort — a full budget (or injected OOM) just
                # means this scan isn't cached, never a query failure.
                try:
                    out.append((SpillableBatch(b, get_manager()), nrows))
                except RetryOOM:
                    for sp, _ in out:
                        sp.close()
                    out = None
            yield b
        if register and out is not None:
            _scan_cache_put(self.table, key, out)


class CpuProjectExec(CpuExec):
    def __init__(self, exprs: Sequence[Expression], schema: T.StructType,
                 child: CpuExec):
        super().__init__(schema, child)
        self.exprs = list(exprs)

    def node_string(self):
        return f"Project [{', '.join(str(e) for e in self.exprs)}]"

    def execute(self, partition: int) -> Iterator[H.HostBatch]:
        for b in self.children[0].execute(partition):
            with self.timer():
                cols = [e.eval_cpu(b) for e in self.exprs]
                out = H.HostBatch(self.schema, cols)
            self.metric("numOutputRows").add(out.num_rows)
            self.metric("numOutputBatches").add(1)
            yield out


class TpuProjectExec(TpuExec):
    """[REF: basicPhysicalOperators.scala :: GpuProjectExec]"""

    def __init__(self, exprs: Sequence[Expression], schema: T.StructType,
                 child: TpuExec):
        super().__init__(schema, child)
        self.exprs = list(exprs)

    def node_string(self):
        return f"TpuProject [{', '.join(str(e) for e in self.exprs)}]"

    def fusion(self):
        from spark_rapids_tpu.runtime.kernel_cache import fingerprint
        exprs, schema = self.exprs, self.schema

        def run(batch):
            return DeviceBatch(
                schema, tuple(e.eval_tpu(batch) for e in exprs),
                batch.sel)

        return run, ("project", fingerprint(exprs), fingerprint(schema))

    def execute(self, partition: int) -> Iterator[DeviceBatch]:
        from spark_rapids_tpu.runtime.kernel_cache import cached_kernel
        run, key = self.fusion()
        fn = cached_kernel(key, lambda: run)
        for b in self.children[0].execute(partition):
            with self.timer():
                out = fn(b)
            self.metric("numOutputBatches").add(1)
            yield out


class CpuFilterExec(CpuExec):
    def __init__(self, condition: Expression, child: CpuExec):
        super().__init__(child.schema, child)
        self.condition = condition

    def node_string(self):
        return f"Filter [{self.condition}]"

    def execute(self, partition: int) -> Iterator[H.HostBatch]:
        for b in self.children[0].execute(partition):
            with self.timer():
                c = self.condition.eval_cpu(b)
                keep = c.data.astype(bool)
                if c.validity is not None:
                    keep = keep & c.validity  # null predicate drops the row
                cols = [H.HostCol(col.dtype, col.data[keep],
                                  None if col.validity is None
                                  else col.validity[keep])
                        for col in b.columns]
                out = H.HostBatch(b.schema, cols)
            self.metric("numOutputRows").add(out.num_rows)
            self.metric("numOutputBatches").add(1)
            yield out


class TpuFilterExec(TpuExec):
    """Predicate folds into ``sel`` — no shape change, no compaction.

    [REF: basicPhysicalOperators.scala :: GpuFilterExec] (cuDF materializes
    via apply_boolean_mask; here liveness is a mask by design).
    """

    def __init__(self, condition: Expression, child: TpuExec):
        super().__init__(child.schema, child)
        self.condition = condition

    def node_string(self):
        return f"TpuFilter [{self.condition}]"

    def fusion(self):
        from spark_rapids_tpu.runtime.kernel_cache import fingerprint
        cond = self.condition

        def run(batch):
            c = cond.eval_tpu(batch)
            keep = c.data.astype(jnp.bool_)
            if c.validity is not None:
                keep = keep & c.validity
            return batch.with_sel(batch.sel & keep)

        return run, ("filter", fingerprint(cond))

    def execute(self, partition: int) -> Iterator[DeviceBatch]:
        from spark_rapids_tpu.runtime.kernel_cache import cached_kernel
        run, key = self.fusion()
        fn = cached_kernel(key, lambda: run)
        for b in self.children[0].execute(partition):
            with self.timer():
                out = fn(b)
            self.metric("numOutputBatches").add(1)
            yield out


class CpuLocalLimitExec(CpuExec):
    def __init__(self, n: int, child: CpuExec):
        super().__init__(child.schema, child)
        self.n = n

    def node_string(self):
        return f"LocalLimit [{self.n}]"

    def execute(self, partition: int) -> Iterator[H.HostBatch]:
        remaining = self.n
        for b in self.children[0].execute(partition):
            if remaining <= 0:
                break
            take = min(remaining, b.num_rows)
            cols = [H.HostCol(c.dtype, c.data[:take],
                              None if c.validity is None else c.validity[:take])
                    for c in b.columns]
            remaining -= take
            yield H.HostBatch(b.schema, cols)


class TpuLocalLimitExec(TpuExec):
    """Keep the first n live rows (batch order).  Mask-only, static shape.

    [REF: limit.scala :: GpuLocalLimitExec]
    """

    def __init__(self, n: int, child: TpuExec):
        super().__init__(child.schema, child)
        self.n = n

    def node_string(self):
        return f"TpuLocalLimit [{self.n}]"

    def execute(self, partition: int) -> Iterator[DeviceBatch]:
        remaining = self.n
        for b in self.children[0].execute(partition):
            if remaining <= 0:
                break
            with self.timer():
                live_prefix = jnp.cumsum(b.sel.astype(jnp.int32))
                keep = b.sel & (live_prefix <= remaining)
                out = b.with_sel(keep)
            # how many we actually emitted (host sync per batch boundary)
            remaining -= int(jnp.sum(keep.astype(jnp.int32)))
            yield out


class CpuGlobalLimitExec(CpuExec):
    """Single-partition global cut across all child partitions.

    [REF: limit.scala :: GpuGlobalLimitExec] — planned above a per-
    partition LocalLimit, exactly Spark's GlobalLimit(LocalLimit(...)).
    """

    def __init__(self, n: int, child: CpuExec):
        super().__init__(child.schema, child)
        self.n = n

    def node_string(self):
        return f"GlobalLimit [{self.n}]"

    def num_partitions(self) -> int:
        return 1

    def execute(self, partition: int) -> Iterator[H.HostBatch]:
        remaining = self.n
        child = self.children[0]
        for p in range(child.num_partitions()):
            for b in child.execute(p):
                if remaining <= 0:
                    return
                take = min(remaining, b.num_rows)
                cols = [H.HostCol(c.dtype, c.data[:take],
                                  None if c.validity is None
                                  else c.validity[:take])
                        for c in b.columns]
                remaining -= take
                yield H.HostBatch(b.schema, cols)


class TpuGlobalLimitExec(TpuExec):
    """[REF: limit.scala :: GpuGlobalLimitExec]

    Multi-executor mode: LIMIT takes ANY n rows (Spark semantics), so no
    row exchange is needed — processes allgather their live-row counts
    and each emits its quota of the first-come budget in process order.
    """

    _multiproc_gather_ok = True

    def __init__(self, n: int, child: TpuExec):
        super().__init__(child.schema, child)
        self.n = n
        from spark_rapids_tpu.parallel.executor import get_executor
        self._ctx = get_executor()
        self._stage = (self._ctx.next_stage_id()
                       if self._ctx is not None else None)

    def node_string(self):
        return f"TpuGlobalLimit [{self.n}]"

    def num_partitions(self) -> int:
        return 1

    def execute(self, partition: int) -> Iterator[DeviceBatch]:
        remaining = self.n
        child = self.children[0]
        if self._ctx is not None:
            from spark_rapids_tpu.exec.distributed import owned_partitions
            ctx = self._ctx
            # drain lazily only until n local rows are seen — reporting
            # the CAPPED count keeps the quota math exact (counts past
            # n can never change any process's quota) while preserving
            # LIMIT's early termination
            batches: List[DeviceBatch] = []
            local = 0
            for p in owned_partitions(child):
                if local >= self.n:
                    break
                # counts pulled ONE overlapped round trip per partition
                # (a per-batch pull costs a full tunnel round trip);
                # early termination still checked between partitions
                part = list(child.execute(p))
                if not part:
                    continue
                batches.extend(part)
                local += sum(_overlapped_live_counts(part))
            replies = ctx.client.allgather(
                self._stage + ":limit", min(local, self.n), ctx.timeout)
            before = sum(replies[:ctx.process_id])
            remaining = max(0, min(local, self.n - before))
            stream = iter(batches)
        else:
            stream = (b for p in range(child.num_partitions())
                      for b in child.execute(p))
        for b in stream:
            if remaining <= 0:
                return
            with self.timer():
                live_prefix = jnp.cumsum(b.sel.astype(jnp.int32))
                keep = b.sel & (live_prefix <= remaining)
                out = b.with_sel(keep)
            remaining -= int(jnp.sum(keep.astype(jnp.int32)))
            yield out


class CpuUnionExec(CpuExec):
    def __init__(self, children_: Sequence[CpuExec]):
        super().__init__(children_[0].schema, *children_)

    def num_partitions(self) -> int:
        return sum(c.num_partitions() for c in self.children)

    def execute(self, partition: int) -> Iterator[H.HostBatch]:
        for c in self.children:
            np_ = c.num_partitions()
            if partition < np_:
                for b in c.execute(partition):
                    yield H.HostBatch(self.schema, b.columns)
                return
            partition -= np_
        raise IndexError("partition out of range")


class TpuUnionExec(TpuExec):
    """[REF: GpuUnionExec] — partitions concatenate across children."""

    def __init__(self, children_: Sequence[TpuExec]):
        super().__init__(children_[0].schema, *children_)

    def num_partitions(self) -> int:
        return sum(c.num_partitions() for c in self.children)

    def execute(self, partition: int) -> Iterator[DeviceBatch]:
        for c in self.children:
            np_ = c.num_partitions()
            if partition < np_:
                for b in c.execute(partition):
                    yield DeviceBatch(self.schema, b.columns, b.sel)
                return
            partition -= np_
        raise IndexError("partition out of range")


class TpuCoalesceBatchesExec(TpuExec):
    """Concatenate small device batches up to a target row budget.

    [REF: GpuCoalesceBatches.scala :: GpuCoalesceBatches] — goal-directed:
    ``target_rows`` (TargetSize analog) or require_single (RequireSingleBatch,
    used by ops that need the whole partition, e.g. final sort).
    Concat = pad columns to the shared bucket and jnp.concatenate; the
    result bucket is the pow-2 ceiling of the live-row total.
    """

    def __init__(self, child: TpuExec, target_rows: int = 1 << 22,
                 require_single: bool = False):
        super().__init__(child.schema, child)
        self.target_rows = target_rows
        self.require_single = require_single

    def node_string(self):
        goal = "single" if self.require_single else f"target={self.target_rows}"
        return f"TpuCoalesceBatches [{goal}]"

    def execute(self, partition: int) -> Iterator[DeviceBatch]:
        from spark_rapids_tpu.columnar.column import compact
        pending: List[DeviceBatch] = []
        pending_rows = 0
        for b in self.children[0].execute(partition):
            n = int(jnp.sum(b.sel.astype(jnp.int32)))
            if (not self.require_single and pending
                    and pending_rows + n > self.target_rows):
                yield self._emit(pending)
                pending, pending_rows = [], 0
            pending.append(compact(b))
            pending_rows += n
        if pending:
            yield self._emit(pending)

    def _emit(self, batches: List[DeviceBatch]) -> DeviceBatch:
        with self.timer("concatTime"):
            out = concat_device_batches(self.schema, batches)
        self.metric("numOutputBatches").add(1)
        return out


_BIG_BUCKET_ROWS = int(__import__("os").environ.get(
    "SPARK_RAPIDS_TPU_BIG_BUCKET_WARN_ROWS", str(1 << 22)))


def warn_big_bucket(where: str, bucket: int) -> None:
    """Stderr breadcrumb when any single device allocation crosses the
    warn threshold (default 4M rows).  A bucket that large is one bad
    shape away from a TPU worker kernel fault / HBM OOM that kills the
    process without a Python traceback — the breadcrumb names the call
    site so a post-mortem has somewhere to start."""
    if bucket < _BIG_BUCKET_ROWS:
        return
    import sys
    import traceback
    stack = traceback.extract_stack(limit=3)
    # stack[-1] = here, stack[-2] = the concat, stack[-3] = its caller
    frame = stack[-3] if len(stack) >= 3 else stack[0]
    print(f"[tpuq] WARNING: {where} building a {bucket}-row bucket "
          f"(caller {frame.name}:{frame.lineno})",
          file=sys.stderr, flush=True)


def _overlapped_live_counts(batches) -> List[int]:
    """Live-row counts for many batches with ONE overlapped transfer
    round trip (sequential scalar pulls cost a full tunnel round trip
    EACH — the breadth-query dispatch tax)."""
    from spark_rapids_tpu.shims import get_shim
    shim = get_shim()
    sums = [jnp.sum(b.sel.astype(jnp.int32)) for b in batches]
    for s_ in sums:
        shim.async_copy_to_host(s_)
    return [int(np.asarray(s_)) for s_ in sums]


def _concat_compacted_fast(schema: T.StructType,
                           batches: List[DeviceBatch],
                           counts: Optional[List[int]] = None
                           ) -> DeviceBatch:
    """Dispatch-bounded concat of COMPACTED batches.

    1. live counts for ALL batches pulled with one overlapped transfer
       round trip (sequential ``int(jnp.sum(...))`` pulls cost a full
       tunnel round trip EACH — the TPC-H breadth-query dispatch tax);
    2. each batch normalizes through at most ONE cached jitted kernel
       (shrink to its pow-2 live bucket, pad strings to the shared
       width, synthesize missing validity planes) instead of
       O(columns) eager slice/pad ops;
    3. one eager ``jnp.concatenate`` per leaf, then a single stable
       compact moves the per-batch live prefixes together.
    """
    from spark_rapids_tpu.columnar.column import compact as _compact
    from spark_rapids_tpu.columnar.column import empty_batch
    from spark_rapids_tpu.runtime.kernel_cache import (
        cached_kernel, fingerprint)
    if not batches:
        return empty_batch(schema)
    if counts is None:
        counts = _overlapped_live_counts(batches)
    total = sum(counts)
    out_bucket = round_up_pow2(max(total, 1))
    warn_big_bucket("concat", out_bucket)
    nfields = len(schema.fields)
    # Structural uniformity gate: every batch must carry one column per
    # schema field and agree on string-ness.  Without it a mismatched
    # batch (an upstream op emitting against the wrong schema — the q7
    # streamed-join side-override bug's signature) surfaces as a bare
    # `IndexError: tuple index out of range` from `.data.shape[1]` deep
    # in kernel build, with no hint of which operator produced it.
    for bi, b in enumerate(batches):
        if len(b.columns) != nfields:
            raise ValueError(
                f"concat: batch {bi} carries {len(b.columns)} columns "
                f"for a {nfields}-field schema — an upstream operator "
                "emitted a batch that does not match its declared "
                "schema")
    is_str = [batches[0].columns[ci].is_string for ci in range(nfields)]
    for bi, b in enumerate(batches):
        for ci in range(nfields):
            if (b.columns[ci].is_string != is_str[ci]
                    or (is_str[ci] and b.columns[ci].data.ndim < 2)):
                raise ValueError(
                    f"concat: column {ci} ({schema.fields[ci].name!r}) "
                    f"is {'string' if is_str[ci] else 'non-string'} in "
                    f"batch 0 but not in batch {bi} — mixed layouts "
                    "cannot be concatenated")
    widths = tuple(
        max(b.columns[ci].data.shape[1] for b in batches)
        if is_str[ci] else 0 for ci in range(nfields))
    has_val = tuple(any(b.columns[ci].validity is not None
                        for b in batches) for ci in range(nfields))
    has_ev = tuple(any(b.columns[ci].evalid is not None
                       for b in batches) for ci in range(nfields))
    sfp = fingerprint(schema)

    def build_norm(out_cap):
        def run(m):
            cols = []
            for ci, c in enumerate(m.columns):
                d = c.data[:out_cap]
                ln = None if c.lengths is None else c.lengths[:out_cap]
                if is_str[ci] and d.shape[1] < widths[ci]:
                    d = jnp.pad(d, ((0, 0), (0, widths[ci] - d.shape[1])))
                v = None
                if has_val[ci]:
                    v = (c.validity[:out_cap] if c.validity is not None
                         else jnp.ones((out_cap,), jnp.bool_))
                ev = None
                if has_ev[ci]:
                    ev = (c.evalid[:out_cap, :] if c.evalid is not None
                          else jnp.ones((out_cap, d.shape[1]),
                                        jnp.bool_))
                    if ev.shape[1] < d.shape[1]:
                        ev = jnp.pad(
                            ev, ((0, 0), (0, d.shape[1] - ev.shape[1])),
                            constant_values=True)
                cols.append(DeviceColumn(c.dtype, d, v, ln, ev))
            return DeviceBatch(schema, tuple(cols), m.sel[:out_cap],
                               compacted=True)
        return run

    norm = []
    all_full = True
    for b, n in zip(batches, counts):
        out_cap = min(b.capacity, max(8, round_up_pow2(max(n, 1), 8)))
        needs = out_cap < b.capacity or any(
            (is_str[ci] and b.columns[ci].data.shape[1] < widths[ci])
            or (has_val[ci] and b.columns[ci].validity is None)
            or (has_ev[ci] and b.columns[ci].evalid is None)
            for ci in range(nfields))
        if needs:
            fn = cached_kernel(
                ("concat_norm", out_cap, widths, has_val, has_ev, sfp),
                lambda oc=out_cap: build_norm(oc))
            b = fn(b)
        all_full = all_full and n == b.capacity
        norm.append(b)

    cols = []
    for ci, f in enumerate(schema.fields):
        data = jnp.concatenate([nb.columns[ci].data for nb in norm], 0)
        validity = (jnp.concatenate(
            [nb.columns[ci].validity for nb in norm]) if has_val[ci]
            else None)
        lengths = (jnp.concatenate(
            [nb.columns[ci].lengths for nb in norm])
            if norm[0].columns[ci].lengths is not None else None)
        evalid = (jnp.concatenate(
            [nb.columns[ci].evalid for nb in norm], 0) if has_ev[ci]
            else None)
        cols.append(DeviceColumn(f.dtype, data, validity, lengths,
                                 evalid))
    sel = jnp.concatenate([nb.sel for nb in norm])
    cat = DeviceBatch(schema, tuple(cols), sel, compacted=all_full)
    cat_bucket = round_up_pow2(cat.capacity)
    if cat_bucket > cat.capacity:
        from spark_rapids_tpu.columnar.column import pad_batch
        padded = pad_batch(cat, cat_bucket)
        cat = DeviceBatch(schema, padded.columns, padded.sel,
                          compacted=all_full)
    if not all_full:
        cat = _compact(cat)
    if out_bucket < cat.capacity:
        fn = cached_kernel(
            ("concat_trim", out_bucket, sfp),
            lambda: (lambda m: DeviceBatch(
                schema,
                tuple(DeviceColumn(
                    c.dtype, c.data[:out_bucket],
                    None if c.validity is None else
                    c.validity[:out_bucket],
                    None if c.lengths is None else
                    c.lengths[:out_bucket],
                    None if c.evalid is None else
                    c.evalid[:out_bucket, :])
                    for c in m.columns),
                m.sel[:out_bucket], compacted=True)))
        cat = fn(cat)
    return cat


def concat_device_batches(schema: T.StructType,
                          batches: List[DeviceBatch],
                          counts: Optional[List[int]] = None,
                          bucket: Optional[int] = None,
                          min_width: int = 0,
                          force_validity: Optional[Sequence[bool]] = None
                          ) -> DeviceBatch:
    """Concatenate compacted device batches into one bucketed batch.

    ``counts`` (live rows per batch) may be passed by callers that track
    them host-side — skips one device sync per input batch.  ``bucket``
    forces the output capacity (≥ total rows); ``min_width`` forces a
    minimum string-matrix width and ``force_validity`` a per-column
    validity presence (shard-uniformity: every shard of one global
    sharded array must carry identical leaf structure).
    """
    if not batches:
        from spark_rapids_tpu.columnar.column import empty_batch
        return empty_batch(schema)
    if (len(batches) == 1 and bucket is None and min_width == 0
            and force_validity is None):
        return batches[0]
    if (bucket is None and min_width == 0
            and force_validity is None and len(batches) > 2
            and all(b.compacted for b in batches)):
        # many-batch gathers (partial-agg merges, join/sort gathers) pay
        # O(batches) tunnel syncs + O(batches × leaves) eager slices on
        # the sequential path below — ~15s of a 16s TPC-H q1 on the
        # tunnel.  The fast path pulls every count in ONE overlapped
        # round trip (reusing caller-tracked counts when given) and
        # keeps per-batch work to one cached kernel.
        return _concat_compacted_fast(schema, batches, counts)
    if counts is None:
        counts = _overlapped_live_counts(batches)
    total = sum(counts)
    if bucket is None:
        bucket = round_up_pow2(max(total, 1))
    assert bucket >= total, (bucket, total)
    warn_big_bucket("concat", bucket)
    cols = []
    for ci, f in enumerate(schema.fields):
        parts_data = []
        parts_val = []
        parts_len = []
        parts_ev = []
        any_val = (force_validity[ci] if force_validity is not None
                   else any(b.columns[ci].validity is not None
                            for b in batches))
        any_ev = any(b.columns[ci].evalid is not None for b in batches)
        is_str = batches[0].columns[ci].is_string
        # min_width may be per-column (sequence) — a global min would pad
        # every string column to the schema's widest one
        mw = (min_width[ci] if isinstance(min_width, (list, tuple))
              else min_width)
        width = max(max(b.columns[ci].data.shape[1] for b in batches),
                    mw) if is_str else 0
        for b, n in zip(batches, counts):
            c = b.columns[ci]
            if is_str:
                d = c.data[:n]
                if d.shape[1] < width:
                    d = jnp.pad(d, ((0, 0), (0, width - d.shape[1])))
                parts_data.append(d)
                parts_len.append(c.lengths[:n])
                if any_ev:
                    ev = (c.evalid[:n] if c.evalid is not None
                          else jnp.ones((n, c.data.shape[1]), jnp.bool_))
                    if ev.shape[1] < width:
                        ev = jnp.pad(ev, ((0, 0), (0, width - ev.shape[1])),
                                     constant_values=True)
                    parts_ev.append(ev)
            else:
                parts_data.append(c.data[:n])
            if any_val:
                v = (c.validity[:n] if c.validity is not None
                     else jnp.ones((n,), jnp.bool_))
                parts_val.append(v)
        data = jnp.concatenate(parts_data, axis=0)
        pad = bucket - total
        if pad:
            data = (jnp.pad(data, ((0, pad), (0, 0))) if is_str
                    else jnp.pad(data, (0, pad)))
        validity = None
        if any_val:
            validity = jnp.pad(jnp.concatenate(parts_val), (0, pad))
        lengths = None
        if is_str:
            lengths = jnp.pad(jnp.concatenate(parts_len), (0, pad))
        evalid = None
        if any_ev:
            evalid = jnp.pad(jnp.concatenate(parts_ev, axis=0),
                             ((0, pad), (0, 0)), constant_values=True)
        cols.append(type(batches[0].columns[ci])(f.dtype, data, validity,
                                                 lengths, evalid))
    sel = jnp.arange(bucket, dtype=jnp.int32) < total
    return DeviceBatch(schema, tuple(cols), sel)
