"""ICI shuffle exchange exec: the distributed stage boundary.

[REF: GpuShuffleExchangeExecBase.scala + RapidsShuffleManager (UCX mode)]
— rethought for TPU (SURVEY §5.8): instead of reduce tasks pulling blocks
point-to-point, the exchange runs ONE SPMD collective program over the
device mesh (parallel/shuffle.py) and downstream operators then consume
their partition's received rows locally, exactly like Spark reduce tasks
after a shuffle fetch.  Stage shape on an N-device mesh:

  upstream partitions → gather+compact → row-shard over mesh
    → {murmur3 pid → layout → all_to_all} (one jitted program)
    → N output partitions, each device-local, capacity re-bucketed

Activated by ``spark.rapids.shuffle.mode=ICI`` when the mesh has more
than one device; the planner then splits aggregates into partial/final
around this exchange and co-partitions join inputs through it.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.column import (
    DeviceBatch, compact, round_up_pow2)
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.ops.expressions import Expression
from spark_rapids_tpu.parallel import shuffle as SH
from spark_rapids_tpu.parallel.mesh import make_mesh


def _accumulate_shards(child: TpuExec, devices, d: int):
    """Stream child partitions onto mesh devices (round-robin) WITHOUT
    ever materializing the whole table on one device.

    Each upstream batch is compacted, sliced to its pow-2 row bucket and
    ``device_put`` to its target device immediately — the peak footprint
    on any one device is its own shard plus one in-flight batch (the r2
    global-gather concentrated everything on device 0 first; VERDICT r2
    missing #2).  Returns (per-device [(batch, rows)], per-device rows,
    per-column max string width, per-column validity presence).
    """
    import jax
    schema = child.schema
    nstr = len(schema.fields)
    parts: List[List[Tuple[DeviceBatch, int]]] = [[] for _ in range(d)]
    rows = [0] * d
    widths = [0] * nstr
    has_val = [False] * nstr
    for p in range(child.num_partitions()):
        dev = p % d
        for b in child.execute(p):
            cb = compact(b)
            n = cb.num_rows_host()
            if n == 0:
                continue
            cap = round_up_pow2(max(n, 1), 8)
            if cap < cb.capacity:
                cb = SH.slice_batch(cb, 0, cap)
            for ci, c in enumerate(cb.columns):
                if c.is_string:
                    widths[ci] = max(widths[ci], int(c.data.shape[1]))
                if c.validity is not None:
                    has_val[ci] = True
            parts[dev].append((jax.device_put(cb, devices[dev]), n))
            rows[dev] += n
    return parts, rows, widths, has_val


def _batch_from_shards(mesh, schema: T.StructType,
                       shards: List[DeviceBatch],
                       local_b: int) -> DeviceBatch:
    """Per-device shard batches (identical structure, committed to their
    mesh devices) → ONE globally-sharded DeviceBatch, zero data movement
    (``jax.make_array_from_single_device_arrays``)."""
    import jax
    axis = mesh.axis_names[0]
    sharding = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(axis))
    d = len(shards)
    flat = [jax.tree.flatten(s) for s in shards]
    treedef = flat[0][1]
    for _, td in flat[1:]:
        assert td == treedef, "shards must have identical structure"
    out_leaves = []
    for i in range(len(flat[0][0])):
        arrs = [flat[dev][0][i] for dev in range(d)]
        shape = (d * local_b,) + arrs[0].shape[1:]
        out_leaves.append(jax.make_array_from_single_device_arrays(
            shape, sharding, arrs))
    return jax.tree.unflatten(treedef, out_leaves)


def _local_shard(batch: DeviceBatch, p: int) -> DeviceBatch:
    """Extract device p's local shard of a sharded batch as a
    single-device batch (stays resident on device p)."""
    import jax
    leaves, treedef = jax.tree.flatten(batch)
    cap = leaves[0].shape[0]
    d = len(leaves[0].addressable_shards)
    per = cap // d
    lo = p * per
    out = []
    for leaf in leaves:
        shard = next(s for s in leaf.addressable_shards
                     if (s.index[0].start or 0) == lo)
        out.append(shard.data)
    return jax.tree.unflatten(treedef, out)


class TpuIciShuffleExchangeExec(TpuExec):
    """Collective shuffle exchange over the ICI mesh.

    ``num_partitions() == mesh size``; ``execute(p)`` yields the rows
    that hashed to partition p, already on device p's shard.
    """

    def __init__(self, child: TpuExec, keys: Sequence[Expression],
                 mesh=None, canon_int64: Sequence[bool] = (),
                 min_bucket: int = 1024):
        super().__init__(child.schema, child)
        self.keys = list(keys)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.canon_int64 = tuple(canon_int64)
        self.min_bucket = min_bucket
        self._result: Optional[DeviceBatch] = None
        self._empty = False
        import threading
        self._mat_lock = threading.Lock()

    @property
    def nparts(self) -> int:
        return int(self.mesh.devices.size)

    def node_string(self):
        ks = ", ".join(str(k) for k in self.keys)
        return f"TpuIciShuffleExchange [hash({ks}) over {self.nparts}dev]"

    def num_partitions(self) -> int:
        return self.nparts

    def _materialize(self) -> Optional[DeviceBatch]:
        with self._mat_lock:
            return self._materialize_locked()

    def _materialize_locked(self) -> Optional[DeviceBatch]:
        if self._result is not None or self._empty:
            return self._result
        from spark_rapids_tpu.exec.basic import concat_device_batches
        from spark_rapids_tpu.runtime.memory import get_manager
        d = self.nparts
        devices = list(self.mesh.devices.flatten())
        schema = self.children[0].schema
        with self.timer("partitionTime"):
            parts, rows, widths, has_val = _accumulate_shards(
                self.children[0], devices, d)
        if sum(rows) == 0:
            self._empty = True
            return None
        # uniform per-device shard capacity (SPMD: one static shape)
        local_b = round_up_pow2(max(max(rows), 1), self.min_bucket)
        from spark_rapids_tpu.columnar.column import empty_batch
        from spark_rapids_tpu.plan.overrides import _estimated_row_bytes
        row_bytes = _estimated_row_bytes(
            schema, str_width=max(widths, default=0))
        shards: List[DeviceBatch] = []
        mgr = get_manager()
        # the arbiter budget models ONE device's HBM: account the
        # per-device working set, not the global table (the whole point
        # of the shard-resident exchange)
        with mgr.transient(2 * local_b * row_bytes):
            with self.timer("partitionTime"):
                for dev in range(d):
                    batch_list = [b for b, _ in parts[dev]]
                    counts = [n for _, n in parts[dev]]
                    if not batch_list:
                        import jax
                        batch_list = [jax.device_put(
                            empty_batch(schema, 8), devices[dev])]
                        counts = [0]
                    shard = concat_device_batches(
                        schema, batch_list, counts=counts, bucket=local_b,
                        min_width=widths, force_validity=has_val)
                    # freshly-created leaves (sel iota, synthesized
                    # validity) land on the default device — re-commit
                    # the whole shard (no-op for resident leaves)
                    import jax
                    shards.append(jax.device_put(shard, devices[dev]))
                sharded = _batch_from_shards(self.mesh, schema, shards,
                                             local_b)
            del parts, shards

            from spark_rapids_tpu.runtime.kernel_cache import (
                cached_kernel, fingerprint)
            base_key = (self.nparts, self.canon_int64,
                        fingerprint(self.keys), fingerprint(schema))
            with self.timer("partitionTime"):
                count_fn = cached_kernel(
                    ("ici_count",) + base_key,
                    lambda: SH.build_count_program(
                        self.mesh, self.keys, d, self.canon_int64))
                counts = np.asarray(count_fn(sharded))  # [d*d]
                cap = round_up_pow2(max(int(counts.max()), 1), 8)
            # per-device collective working set: the [d*cap] layout and
            # the [d*cap] received block
            with mgr.transient(2 * d * cap * row_bytes):
                with self.timer("collectiveTime"):
                    shuffle_fn = cached_kernel(
                        ("ici_shuffle", cap) + base_key,
                        lambda: SH.build_shuffle_program(
                            self.mesh, self.keys, d, cap,
                            self.canon_int64))
                    self._result = shuffle_fn(sharded)
        return self._result

    def execute(self, partition: int) -> Iterator[DeviceBatch]:
        result = self._materialize()
        if result is None:
            return
        # partition p's received rows live on device p's shard — extract
        # the LOCAL shard (no cross-device slice of the global array), so
        # stage outputs stay device-resident for the next stage
        block = _local_shard(result, partition)
        block = compact(block)
        n = block.num_rows_host()
        cap = round_up_pow2(max(n, 1), self.min_bucket)
        if cap < block.capacity:
            block = SH.slice_batch(block, 0, cap)
        self.metric("numOutputRows").add(n)
        self.metric("numOutputBatches").add(1)
        yield block


def ici_active(conf) -> bool:
    """ICI shuffle requested and a real mesh exists."""
    if conf.shuffle_mode != "ICI":
        return False
    import jax
    return jax.device_count() > 1


def hashable_on_device(dt: T.DataType) -> bool:
    try:
        from spark_rapids_tpu.plan.overrides import is_device_supported_type
        return is_device_supported_type(dt) is None
    except ImportError:
        return False
