"""ICI shuffle exchange exec: the distributed stage boundary.

[REF: GpuShuffleExchangeExecBase.scala + RapidsShuffleManager (UCX mode)]
— rethought for TPU (SURVEY §5.8): instead of reduce tasks pulling blocks
point-to-point, the exchange runs ONE SPMD collective program over the
device mesh (parallel/shuffle.py) and downstream operators then consume
their partition's received rows locally, exactly like Spark reduce tasks
after a shuffle fetch.  Stage shape on an N-device mesh:

  upstream partitions → gather+compact → row-shard over mesh
    → {murmur3 pid → layout → all_to_all} (one jitted program)
    → N output partitions, each device-local, capacity re-bucketed

Activated by ``spark.rapids.shuffle.mode=ICI`` when the mesh has more
than one device; the planner then splits aggregates into partial/final
around this exchange and co-partitions join inputs through it.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.column import (
    DeviceBatch, compact, round_up_pow2)
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.ops.expressions import Expression
from spark_rapids_tpu.parallel import shuffle as SH
from spark_rapids_tpu.parallel.mesh import make_mesh


def _gather_child(child: TpuExec) -> Optional[DeviceBatch]:
    """All child partitions → one compact device batch (None if empty)."""
    from spark_rapids_tpu.exec.basic import concat_device_batches
    batches = [compact(b) for p in range(child.num_partitions())
               for b in child.execute(p)]
    if not batches:
        return None
    if len(batches) == 1:
        return batches[0]
    return compact(concat_device_batches(child.schema, batches))


class TpuIciShuffleExchangeExec(TpuExec):
    """Collective shuffle exchange over the ICI mesh.

    ``num_partitions() == mesh size``; ``execute(p)`` yields the rows
    that hashed to partition p, already on device p's shard.
    """

    def __init__(self, child: TpuExec, keys: Sequence[Expression],
                 mesh=None, canon_int64: Sequence[bool] = (),
                 min_bucket: int = 1024):
        super().__init__(child.schema, child)
        self.keys = list(keys)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.canon_int64 = tuple(canon_int64)
        self.min_bucket = min_bucket
        self._result: Optional[DeviceBatch] = None
        self._empty = False
        import threading
        self._mat_lock = threading.Lock()

    @property
    def nparts(self) -> int:
        return int(self.mesh.devices.size)

    def node_string(self):
        ks = ", ".join(str(k) for k in self.keys)
        return f"TpuIciShuffleExchange [hash({ks}) over {self.nparts}dev]"

    def num_partitions(self) -> int:
        return self.nparts

    def _materialize(self) -> Optional[DeviceBatch]:
        with self._mat_lock:
            return self._materialize_locked()

    def _materialize_locked(self) -> Optional[DeviceBatch]:
        if self._result is not None or self._empty:
            return self._result
        gathered = _gather_child(self.children[0])
        if gathered is None:
            self._empty = True
            return None
        d = self.nparts
        n = gathered.num_rows_host()
        # local shard capacity: pow-2 bucket of the per-device share
        local_b = round_up_pow2(max((n + d - 1) // d, 1), self.min_bucket)
        global_cap = d * local_b
        if gathered.capacity < global_cap:
            from spark_rapids_tpu.columnar.column import pad_batch
            gathered = pad_batch(gathered, global_cap)
        elif gathered.capacity > global_cap:
            gathered = SH.slice_batch(gathered, 0, global_cap)
        sharded = SH.shard_batch(self.mesh, gathered)

        from spark_rapids_tpu.runtime.kernel_cache import (
            cached_kernel, fingerprint)
        base_key = (self.nparts, self.canon_int64, fingerprint(self.keys),
                    fingerprint(gathered.schema))
        with self.timer("partitionTime"):
            count_fn = cached_kernel(
                ("ici_count",) + base_key,
                lambda: SH.build_count_program(
                    self.mesh, self.keys, d, self.canon_int64))
            counts = np.asarray(count_fn(sharded))  # [d*d]
            cap = round_up_pow2(max(int(counts.max()), 1), 8)
        with self.timer("collectiveTime"):
            shuffle_fn = cached_kernel(
                ("ici_shuffle", cap) + base_key,
                lambda: SH.build_shuffle_program(
                    self.mesh, self.keys, d, cap, self.canon_int64))
            self._result = shuffle_fn(sharded)
        self._cap = cap
        return self._result

    def execute(self, partition: int) -> Iterator[DeviceBatch]:
        result = self._materialize()
        if result is None:
            return
        d = self.nparts
        per_dev = result.capacity // d
        block = SH.slice_batch(result, partition * per_dev, per_dev)
        # stage boundary: compact + re-bucket so downstream operators
        # work at the partition's size, not the worst-case capacity
        block = compact(block)
        n = block.num_rows_host()
        cap = round_up_pow2(max(n, 1), self.min_bucket)
        if cap < block.capacity:
            block = SH.slice_batch(block, 0, cap)
        self.metric("numOutputRows").add(n)
        self.metric("numOutputBatches").add(1)
        yield block


def ici_active(conf) -> bool:
    """ICI shuffle requested and a real mesh exists."""
    if conf.shuffle_mode != "ICI":
        return False
    import jax
    return jax.device_count() > 1


def hashable_on_device(dt: T.DataType) -> bool:
    try:
        from spark_rapids_tpu.plan.overrides import is_device_supported_type
        return is_device_supported_type(dt) is None
    except ImportError:
        return False
