"""ICI shuffle exchange exec: the distributed stage boundary.

[REF: GpuShuffleExchangeExecBase.scala + RapidsShuffleManager (UCX mode)]
— rethought for TPU (SURVEY §5.8): instead of reduce tasks pulling blocks
point-to-point, the exchange runs ONE SPMD collective program over the
device mesh (parallel/shuffle.py) and downstream operators then consume
their partition's received rows locally, exactly like Spark reduce tasks
after a shuffle fetch.  Stage shape on an N-device mesh (the COMPILED exchange, the
single-process default — ``spark.rapids.tpu.exchange.mode``):

  upstream partitions → gather+compact → row-shard over mesh
    → {murmur3 pid → rank → gather index table + counts}   (prepare)
    → {slice → clip-gather → all_to_all → receive mask}    (boundary)
    → N output partitions, each device-local, capacity re-bucketed

The *prepare* program runs once per accumulated stage input and emits
both the routing table and the per-partition counts in one launch; the
*boundary* program is the only launch on the stage seam — its input
buffers are donated, and the host feeds it the transposed receive
counts so no second collective runs.  Multi-executor mode keeps the
two-phase count/shuffle agreement protocol (its rendezvous epochs are
what make cross-process retry bit-identical); ``mode=host`` routes the
exchange through the host-shuffle transport at plan time, which is
also the degrade target for the ``collective`` failure domain.

Activated by ``spark.rapids.shuffle.mode=ICI`` when the mesh has more
than one device; the planner then splits aggregates into partial/final
around this exchange and co-partitions join inputs through it.
"""

from __future__ import annotations

import time
from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.column import (
    DeviceBatch, compact, round_up_pow2)
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.ops.expressions import Expression
from spark_rapids_tpu.parallel import shuffle as SH
from spark_rapids_tpu.parallel.mesh import make_mesh
from spark_rapids_tpu.runtime import stats as ST
from spark_rapids_tpu.runtime import telemetry as TM

_TM_COLLECTIVE_S = TM.REGISTRY.counter(
    "tpuq_ici_collective_seconds_total",
    "ICI all-to-all collective dispatch seconds")
_TM_ICI_BYTES = TM.REGISTRY.counter(
    "tpuq_ici_exchange_bytes_total",
    "bytes moved through ICI shuffle exchanges (global batch size)")
_TM_ICI_EX_COLL_S = TM.REGISTRY.counter(
    "tpuq_ici_exchange_collective_seconds_total",
    "compiled-exchange boundary-program dispatch seconds")


def exchange_opts(conf) -> dict:
    """Conf-derived ICI-exchange constructor kwargs — every plan-time
    construction site passes these through, so runtime behavior
    (buffer donation) follows the session conf without each site
    re-reading it."""
    from spark_rapids_tpu import conf as C
    return {"donate": bool(conf.get(C.EXCHANGE_DONATE))}


def owned_partitions(plan) -> List[int]:
    """Partitions an executor process serves of ``plan``: descend the
    partition-preserving spine to the nearest ICI exchange and take its
    local partitions; plans without an exchange serve every partition
    (executor-sliced scans make non-owned ones empty)."""
    node = plan
    while True:
        if isinstance(node, TpuIciShuffleExchangeExec):
            return node.local_partitions()
        if (node.children and node.num_partitions()
                == node.children[0].num_partitions()):
            node = node.children[0]
            continue
        return list(range(plan.num_partitions()))


def _accumulate_shards(child: TpuExec, devices, d: int,
                       partitions=None):
    """Stream child partitions onto mesh devices (round-robin) WITHOUT
    ever materializing the whole table on one device.

    Each upstream batch is compacted, sliced to its pow-2 row bucket and
    ``device_put`` to its target device immediately — the peak footprint
    on any one device is its own shard plus one in-flight batch (the r2
    global-gather concentrated everything on device 0 first; VERDICT r2
    missing #2).  Returns (per-device [(batch, rows)], per-device rows,
    per-column max string width, per-column validity presence).
    """
    import jax
    schema = child.schema
    nstr = len(schema.fields)
    parts: List[List[Tuple[DeviceBatch, int]]] = [[] for _ in range(d)]
    rows = [0] * d
    widths = [0] * nstr
    has_val = [False] * nstr
    if partitions is None:
        partitions = range(child.num_partitions())
    # round-robin by ENUMERATION index: owned partition ids can share a
    # factor with d (executor slicing hands each process p ≡ id mod
    # count), and `p % d` would then pile every batch on one device
    for i, p in enumerate(partitions):
        dev = i % d
        for b in child.execute(p):
            cb = compact(b)
            n = cb.num_rows_host()
            if n == 0:
                continue
            cap = round_up_pow2(max(n, 1), 8)
            if cap < cb.capacity:
                cb = SH.slice_batch(cb, 0, cap)
            for ci, c in enumerate(cb.columns):
                if c.is_string:
                    widths[ci] = max(widths[ci], int(c.data.shape[1]))
                if c.validity is not None:
                    has_val[ci] = True
            parts[dev].append((jax.device_put(cb, devices[dev]), n))
            rows[dev] += n
    return parts, rows, widths, has_val


def _batch_from_shards(mesh, schema: T.StructType,
                       shards: List[DeviceBatch],
                       local_b: int,
                       global_devices: int = 0) -> DeviceBatch:
    """Per-device shard batches (identical structure, committed to their
    mesh devices) → ONE globally-sharded DeviceBatch, zero data movement
    (``jax.make_array_from_single_device_arrays``).

    In multi-process mode ``shards`` holds only this process's LOCAL
    shards (jax matches them to the global sharding by their committed
    devices); ``global_devices`` then sizes the global shape."""
    import jax
    from spark_rapids_tpu.parallel.mesh import named_sharding
    sharding = named_sharding(mesh)
    d = global_devices or len(shards)
    flat = [jax.tree.flatten(s) for s in shards]
    treedef = flat[0][1]
    for _, td in flat[1:]:
        assert td == treedef, "shards must have identical structure"
    out_leaves = []
    for i in range(len(flat[0][0])):
        arrs = [flat[k][0][i] for k in range(len(shards))]
        shape = (d * local_b,) + arrs[0].shape[1:]
        out_leaves.append(jax.make_array_from_single_device_arrays(
            shape, sharding, arrs))
    return jax.tree.unflatten(treedef, out_leaves)


def _local_shard(batch: DeviceBatch, p: int) -> DeviceBatch:
    """Extract device p's local shard of a sharded batch as a
    single-device batch (stays resident on device p)."""
    import jax
    leaves, treedef = jax.tree.flatten(batch)
    per = int(leaves[0].addressable_shards[0].data.shape[0])
    lo = p * per
    out = []
    for leaf in leaves:
        shard = next((s for s in leaf.addressable_shards
                      if (s.index[0].start or 0) == lo), None)
        if shard is None:
            raise RuntimeError(
                f"partition {p} is not local to this process "
                "(multi-executor pump must only pull owned partitions)")
        out.append(shard.data)
    return jax.tree.unflatten(treedef, out)


class TpuIciShuffleExchangeExec(TpuExec):
    """Collective shuffle exchange over the ICI mesh.

    ``num_partitions() == mesh size``; ``execute(p)`` yields the rows
    that hashed to partition p, already on device p's shard.
    """

    def __init__(self, child: TpuExec, keys: Sequence[Expression],
                 mesh=None, canon_int64: Sequence[bool] = (),
                 min_bucket: int = 1024, donate: bool = True):
        super().__init__(child.schema, child)
        self.keys = list(keys)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.canon_int64 = tuple(canon_int64)
        self.min_bucket = min_bucket
        self.donate = donate
        self._result: Optional[DeviceBatch] = None
        # compiled path: per-partition received rows, known host-side
        # from prepare's counts — execute() then needs no device sync
        self._recv_counts: Optional[np.ndarray] = None
        self._empty = False
        # set when the collective degraded to the host-shuffle transport
        self._host_fallback = None
        import threading
        self._mat_lock = threading.Lock()
        # multi-executor mode: rendezvous-coordinated collective entry.
        # Stage ids are assigned at plan-conversion time — every process
        # plans the same query with the same deterministic planner, so
        # the Nth exchange here is the Nth exchange everywhere (the
        # analog of the driver-assigned shuffle id).
        from spark_rapids_tpu.parallel.executor import get_executor
        self._ctx = get_executor()
        self._stage = (self._ctx.next_stage_id()
                       if self._ctx is not None else None)

    def local_partitions(self) -> List[int]:
        """Partition ids this process can serve (all, single-process)."""
        if self._ctx is None:
            return list(range(self.nparts))
        return self._ctx.local_partition_ids(self.mesh)

    @property
    def nparts(self) -> int:
        return int(self.mesh.devices.size)

    def node_string(self):
        ks = ", ".join(str(k) for k in self.keys)
        return f"TpuIciShuffleExchange [hash({ks}) over {self.nparts}dev]"

    def num_partitions(self) -> int:
        return self.nparts

    def _materialize(self) -> Optional[DeviceBatch]:
        with self._mat_lock:
            return self._materialize_locked()

    def _materialize_locked(self) -> Optional[DeviceBatch]:
        if (self._result is not None or self._empty
                or self._host_fallback is not None):
            return self._result
        if self._ctx is not None:
            return self._materialize_multiproc()
        from spark_rapids_tpu.exec.basic import concat_device_batches
        from spark_rapids_tpu.runtime.memory import get_manager
        d = self.nparts
        devices = list(self.mesh.devices.flatten())
        schema = self.children[0].schema
        with self.timer("partitionTime"):
            parts, rows, widths, has_val = _accumulate_shards(
                self.children[0], devices, d)
        if sum(rows) == 0:
            self._empty = True
            return None
        # uniform per-device shard capacity (SPMD: one static shape)
        local_b = round_up_pow2(max(max(rows), 1), self.min_bucket)
        from spark_rapids_tpu.columnar.column import empty_batch
        from spark_rapids_tpu.plan.overrides import _estimated_row_bytes
        row_bytes = _estimated_row_bytes(
            schema, str_width=max(widths, default=0))
        shards: List[DeviceBatch] = []
        mgr = get_manager()
        # the arbiter budget models ONE device's HBM: account the
        # per-device working set, not the global table (the whole point
        # of the shard-resident exchange)
        with mgr.transient(2 * local_b * row_bytes):
            with self.timer("partitionTime"):
                for dev in range(d):
                    batch_list = [b for b, _ in parts[dev]]
                    counts = [n for _, n in parts[dev]]
                    if not batch_list:
                        import jax
                        batch_list = [jax.device_put(
                            empty_batch(schema, 8), devices[dev])]
                        counts = [0]
                    shard = concat_device_batches(
                        schema, batch_list, counts=counts, bucket=local_b,
                        min_width=widths, force_validity=has_val)
                    # freshly-created leaves (sel iota, synthesized
                    # validity) land on the default device — re-commit
                    # the whole shard (no-op for resident leaves)
                    import jax
                    shards.append(jax.device_put(shard, devices[dev]))
                sharded = _batch_from_shards(self.mesh, schema, shards,
                                             local_b)
            del parts, shards

            from spark_rapids_tpu.runtime.kernel_cache import (
                cached_kernel, fingerprint)
            base_key = self._base_key(schema)
            aux = self._aux_args(sharded)
            # the compiled exchange: ONE producer-side prepare launch
            # (routing table + counts together), then ONE boundary
            # launch on the stage seam — the all_to_all plus receive
            # masking, with the input batch donated to the wire
            with mgr.transient(4 * d * local_b):  # per-device idx table
                with self.timer("partitionTime"):
                    prep_fn = cached_kernel(
                        ("ici_prepare",) + base_key,
                        self._prepare_builder())
                    idx, counts = prep_fn(sharded, *aux)
                    counts_np = np.asarray(counts).reshape(d, d)
                    cap = SH.exchange_cap(counts_np.max(), local_b)
                st = ST.current()
                if st is not None:
                    # counts is per-source-device × per-partition:
                    # summing over sources gives global partition sizes
                    st.record_partitions(self, counts_np.sum(axis=0),
                                         unit="rows")
                # receive counts ride host→device: partition p's
                # liveness needs counts FROM every source — a transpose
                # on the host, not a second collective on the wire
                from spark_rapids_tpu.parallel.mesh import named_sharding
                crecv = jax.device_put(
                    np.ascontiguousarray(counts_np.T.astype(np.int32)),
                    named_sharding(self.mesh))
                # per-device collective working set: the [d*cap]
                # gathered leaves and the [d*cap] received block
                with mgr.transient(2 * d * cap * row_bytes):
                    nbytes = sharded.nbytes()  # before donation
                    t0 = time.perf_counter()
                    with self.timer("collectiveTime"):
                        boundary_fn = cached_kernel(
                            ("ici_boundary", cap, d, self.donate,
                             fingerprint(schema)),
                            self._boundary_builder(cap))
                        self._result = self._run_collective(
                            boundary_fn, sharded, (idx, crecv))
                    dt = time.perf_counter() - t0
                    _TM_COLLECTIVE_S.inc(dt)
                    _TM_ICI_EX_COLL_S.inc(dt)
                    _TM_ICI_BYTES.inc(nbytes)
            if self._result is not None:
                self._recv_counts = counts_np.sum(axis=0)
        return self._result

    # -- resilience: the ``collective`` failure domain ----------------------
    def _run_collective(self, shuffle_fn, sharded, aux):
        """Dispatch the all-to-all through the ``collective`` failure
        domain.  Single-process retry exhaustion degrades to the
        host-path shuffle transport over the same child (the works-
        everywhere fallback); multi-executor collectives fail together
        with a domain-tagged error — one process degrading alone would
        deadlock the others at the rendezvous."""
        from spark_rapids_tpu.runtime import resilience as R

        def attempt():
            R.INJECTOR.on("collective")
            return shuffle_fn(sharded, *aux)

        out = R.run_guarded("collective", attempt, op=self.node_string(),
                            degrade=self._host_degrade_fn())
        if self._host_fallback is not None:
            return None
        return out

    def _host_degrade_fn(self):
        """The degradation callable, or None when this exchange cannot
        degrade (multi-executor; RANGE overrides to None too — a hash
        host shuffle would break its total-order contract)."""
        if self._ctx is not None:
            return None

        def degrade():
            from spark_rapids_tpu.shuffle.exchange import (
                TpuHostShuffleExchangeExec)
            self._host_fallback = TpuHostShuffleExchangeExec(
                self.children[0], self.nparts, keys=self.keys,
                min_bucket=self.min_bucket)
            return None

        return degrade

    # -- pid-program hooks (overridden by the RANGE exchange) ---------------
    def _base_key(self, schema) -> tuple:
        from spark_rapids_tpu.runtime.kernel_cache import fingerprint
        return (self.nparts, self.canon_int64, fingerprint(self.keys),
                fingerprint(schema))

    def _aux_args(self, sharded) -> tuple:
        """Extra traced arguments for the count/shuffle programs."""
        return ()

    def _prepare_builder(self):
        """Compiled-path producer program (index table + counts)."""
        return lambda: SH.build_prepare_program(
            self.mesh, self.keys, self.nparts, self.canon_int64)

    def _boundary_builder(self, cap: int):
        """Compiled-path seam program — pid-agnostic, so the cache key
        above deliberately drops the partitioning fingerprint: hash and
        range exchanges with one schema share one boundary per cap."""
        return lambda: SH.build_boundary_program(
            self.mesh, self.nparts, cap, donate=self.donate)

    def _count_builder(self):
        """Legacy two-phase count program (multi-executor path only —
        its rendezvous epochs need per-shard counts a cross-process
        count program could not make addressable)."""
        return lambda: SH.build_count_program(
            self.mesh, self.keys, self.nparts, self.canon_int64)

    def _shuffle_builder(self, cap: int):
        return lambda: SH.build_shuffle_program(
            self.mesh, self.keys, self.nparts, cap, self.canon_int64)

    def _local_pid(self, batch, base_key):
        """Partition ids of a LOCAL shard (multiproc count phase)."""
        from spark_rapids_tpu.runtime.kernel_cache import cached_kernel
        fn = cached_kernel(
            ("ici_mp_pid",) + base_key,
            lambda: SH.make_pid_fn(self.keys, self.nparts,
                                   self.canon_int64))
        return fn(batch)

    def _materialize_multiproc(self) -> Optional[DeviceBatch]:
        """Rendezvous-coordinated collective shuffle across executor
        processes [REF: RapidsShuffleInternalManagerBase; SURVEY §5.8].

        1. accumulate this process's upstream slice onto LOCAL devices;
        2. rendezvous ``:shape`` allgather — every process must build
           byte-identical XLA programs, so shard capacity, string widths
           and validity presence are agreed globally;
        3. assemble the globally-sharded batch from local shards;
        4. per-shard partition counts (plain local jit), rendezvous
           ``:counts`` allgather → the global all_to_all cap;
        5. ``:enter`` barrier, then every process calls the SAME jitted
           collective program.  Any rendezvous deadline failure raises
           in EVERY process (fail-together) — nobody blocks alone inside
           a collective that cannot complete.

        Steps 2-5 run inside ``run_stage_epochs``: a transient
        rendezvous fault aborts the epoch for every peer and the whole
        agreement re-runs at epoch+1 over the SAME accumulated inputs
        (bit-identical recovery); a confirmed-dead peer raises a
        peer-tagged ``TerminalDeviceError`` on every survivor instead.
        """
        import jax
        from spark_rapids_tpu.exec.basic import concat_device_batches
        from spark_rapids_tpu.columnar.column import empty_batch
        from spark_rapids_tpu.runtime.kernel_cache import (
            cached_kernel, fingerprint)
        from spark_rapids_tpu.runtime.memory import get_manager
        from spark_rapids_tpu.parallel.rendezvous import run_stage_epochs
        ctx = self._ctx
        timeout = ctx.timeout
        d = self.nparts
        all_devices = list(self.mesh.devices.flatten())
        local_ids = ctx.local_partition_ids(self.mesh)
        local_devices = [all_devices[i] for i in local_ids]
        schema = self.children[0].schema
        with self.timer("partitionTime"):
            # only the child partitions THIS process owns: a downstream
            # exchange's partitions live on local devices only, and
            # executor-sliced scans make the rest empty anyway
            parts, rows, widths0, has_val0 = _accumulate_shards(
                self.children[0], local_devices, len(local_devices),
                partitions=owned_partitions(self.children[0]))
        base_key = self._base_key(schema)
        # the payload carries the stage's structural fingerprint: stage
        # ids are plan-conversion-ordered, so if executors ever run
        # DIFFERENT queries (or the same queries in different order)
        # the mismatch must fail loudly, not cross-match allgathers
        fp = repr(base_key)
        payload = {"rows": max(rows) if rows else 0,
                   "total": sum(rows), "widths": widths0,
                   "has_val": has_val0, "fp": fp}
        mgr = get_manager()

        def attempt(epoch: int):
            # a retried epoch re-agrees EVERYTHING that rode the
            # rendezvous — a peer that restarted mid-stage has none of
            # it cached (range bounds included, or the processes would
            # derive different pid programs and desync)
            self._epoch = epoch
            self._bounds = None
            replies = ctx.client.allgather(self._stage + ":shape",
                                           payload, timeout, epoch=epoch)
            if any(r["fp"] != fp for r in replies):
                raise RuntimeError(
                    f"rendezvous stage {self._stage} mismatch across "
                    "executors (different queries or different order) — "
                    "every executor process must run the same queries "
                    "in the same order")
            if sum(r["total"] for r in replies) == 0:
                return None
            local_b = round_up_pow2(
                max(max(r["rows"] for r in replies), 1), self.min_bucket)
            widths = [max(ws) for ws in
                      zip(*[r["widths"] for r in replies])
                      ] or list(widths0)
            has_val = [any(hv) for hv in
                       zip(*[r["has_val"] for r in replies])
                       ] or list(has_val0)
            from spark_rapids_tpu.plan.overrides import (
                _estimated_row_bytes)
            row_bytes = _estimated_row_bytes(
                schema, str_width=max(widths, default=0))
            shards: List[DeviceBatch] = []
            # per-device working set, same accounting as the single-
            # process path: this process hosts len(local_devices) shards
            # of local_b rows each while building, then the [d*cap]
            # layout + received block per local device during the
            # collective
            with mgr.transient(
                    2 * len(local_devices) * local_b * row_bytes):
                with self.timer("partitionTime"):
                    for li, dev in enumerate(local_devices):
                        batch_list = [b for b, _ in parts[li]]
                        counts = [n for _, n in parts[li]]
                        if not batch_list:
                            batch_list = [jax.device_put(
                                empty_batch(schema, 8), dev)]
                            counts = [0]
                        shard = concat_device_batches(
                            schema, batch_list, counts=counts,
                            bucket=local_b, min_width=widths,
                            force_validity=has_val)
                        shards.append(jax.device_put(shard, dev))
                    sharded = _batch_from_shards(
                        self.mesh, schema, shards, local_b,
                        global_devices=d)
                del shards[:]
                aux = self._aux_args(sharded)
                with self.timer("partitionTime"):
                    # per-shard counts via a plain LOCAL jit: a
                    # cross-process count program's output shards would
                    # not be addressable
                    local_max = 0
                    local_counts = np.zeros(d, np.int64)
                    for li in range(len(local_devices)):
                        shard_b = _local_shard(sharded, local_ids[li])
                        cnt = np.asarray(SH.local_partition_counts(
                            shard_b, self._local_pid(shard_b, base_key),
                            d))
                        local_max = max(local_max, int(cnt.max()))
                        local_counts += cnt
                # the payload carries this process's full per-partition
                # contribution, not just the max: every process (the
                # coordinator included) merges the replies into the
                # CLUSTER-WIDE partition sizes, so skew is attributable
                # from any executor's profile record
                replies = ctx.client.allgather(
                    self._stage + ":counts",
                    {"max": local_max, "parts": local_counts.tolist()},
                    timeout, epoch=epoch)
                cap = round_up_pow2(
                    max(max(r["max"] for r in replies), 1), 8)
                st = ST.current()
                if st is not None:
                    st.record_partitions(
                        self,
                        ST.merge_partition_counts(
                            r["parts"] for r in replies),
                        unit="rows", executors=len(replies))
                with mgr.transient(2 * d * cap * row_bytes):
                    ctx.client.barrier(self._stage + ":enter", timeout,
                                       epoch=epoch)
                    t0 = time.perf_counter()
                    with self.timer("collectiveTime"):
                        shuffle_fn = cached_kernel(
                            ("ici_shuffle", cap) + base_key,
                            self._shuffle_builder(cap))
                        result = self._run_collective(
                            shuffle_fn, sharded, aux)
                    _TM_COLLECTIVE_S.inc(time.perf_counter() - t0)
                    _TM_ICI_BYTES.inc(sharded.nbytes())
            return result

        out = run_stage_epochs(ctx.client, self._stage, attempt)
        del parts
        if out is None:
            self._empty = True
            return None
        self._result = out
        return self._result

    def execute(self, partition: int) -> Iterator[DeviceBatch]:
        result = self._materialize()
        if self._host_fallback is not None:
            # collective degraded: serve this partition through the
            # host-shuffle transport (same hash kernel, same row set)
            yield from self._host_fallback.execute(partition)
            return
        if result is None:
            return
        # partition p's received rows live on device p's shard — extract
        # the LOCAL shard (no cross-device slice of the global array), so
        # stage outputs stay device-resident for the next stage
        block = _local_shard(result, partition)
        block = compact(block)
        if self._recv_counts is not None:
            # compiled path: the receive count is already on the host
            # (prepare's counts), so the seam→downstream handoff costs
            # zero device syncs — the regroup fuses into the first
            # downstream pump's dispatch chain
            n = int(self._recv_counts[partition])
        else:
            n = block.num_rows_host()
        cap = round_up_pow2(max(n, 1), self.min_bucket)
        if cap < block.capacity:
            block = SH.slice_batch(block, 0, cap)
        self.metric("numOutputRows").add(n)
        self.metric("numOutputBatches").add(1)
        yield block


class TpuIciRangeExchangeExec(TpuIciShuffleExchangeExec):
    """RANGE-partitioned collective exchange [REF:
    GpuRangePartitioning.scala + GpuShuffleExchangeExecBase]: sampled
    order-key boundaries (agreed across executor processes via a
    rendezvous allgather) route each row to the partition owning its key
    range, so partition p's received rows all order before partition
    p+1's — a local per-partition sort then yields a TOTAL order.  The
    distribution mechanism for global Sort/Window-without-keys/TopN."""

    def __init__(self, child: TpuExec, orders, mesh=None,
                 donate: bool = True):
        # keys only drive fingerprints/tagging; pids come from orders
        super().__init__(child, [o.expr for o in orders], mesh=mesh,
                         donate=donate)
        self.orders = list(orders)
        self._bounds: Optional[List[np.ndarray]] = None

    def node_string(self):
        ks = ", ".join(str(o.expr) for o in self.orders)
        return f"TpuIciRangeExchange [range({ks}) over {self.nparts}dev]"

    def _base_key(self, schema) -> tuple:
        from spark_rapids_tpu.runtime.kernel_cache import fingerprint
        return ("range", self.nparts, fingerprint(list(self.orders)),
                fingerprint(schema))

    def _host_degrade_fn(self):
        # the host transport hash-partitions; range partitions carry a
        # total-order contract a hash shuffle would silently break
        return None

    def _sample_bounds(self, sharded) -> List[np.ndarray]:
        """Per-limb boundary arrays uint64[nparts-1]: sample local
        shards' key limbs, (multiproc: allgather the samples so every
        process derives IDENTICAL boundaries), lexsort, take
        quantiles."""
        import jax.numpy as jnp
        from spark_rapids_tpu.exec.sort import _encode_key_limbs
        local_ids = (self._ctx.local_partition_ids(self.mesh)
                     if self._ctx is not None
                     else list(range(self.nparts)))
        samples = []
        for p in local_ids:
            shard = _local_shard(sharded, p)
            limbs = _encode_key_limbs(shard, self.orders)
            # slice to the shard's LIVE count: nonzero pads with index 0,
            # and a sparse shard would otherwise flood the sample with
            # one (possibly dead) row's key, collapsing the quantiles
            live = int(jnp.sum(shard.sel.astype(jnp.int32)))
            k = min(shard.capacity, 256, max(live, 0))
            if k == 0:
                continue
            idx = jnp.nonzero(shard.sel, size=min(shard.capacity, 256),
                              fill_value=0)[0][:k]
            samples.append([np.asarray(jnp.take(l, idx))
                            for l in limbs])
        if not samples:
            # no live rows on this process — boundaries still must be
            # agreed; contribute empty arrays per limb
            shard = _local_shard(sharded, local_ids[0])
            nlimbs = len(_encode_key_limbs(shard, self.orders))
            samples.append([np.zeros(0, np.uint64)
                            for _ in range(nlimbs)])
        cols = [np.concatenate([s[i] for s in samples]).astype(np.uint64)
                for i in range(len(samples[0]))]
        if self._ctx is not None:
            payload = [c.tolist() for c in cols]
            replies = self._ctx.client.allgather(
                self._stage + ":range", payload, self._ctx.timeout,
                epoch=getattr(self, "_epoch", 0))
            cols = [np.concatenate([np.array(r[i], dtype=np.uint64)
                                    for r in replies])
                    for i in range(len(cols))]
        from spark_rapids_tpu.exec.sort import pick_quantile_boundaries
        return pick_quantile_boundaries(cols, self.nparts)

    def _aux_args(self, sharded) -> tuple:
        if self._bounds is None:
            self._bounds = self._sample_bounds(sharded)
        return (self._bounds,)

    def _prepare_builder(self):
        # the boundary program is pid-agnostic, so only prepare differs:
        # range pids from the sampled boundary limbs (traced aux)
        return lambda: SH.build_range_prepare_program(
            self.mesh, self.orders, self.nparts)

    def _count_builder(self):
        return lambda: SH.build_range_count_program(
            self.mesh, self.orders, self.nparts)

    def _shuffle_builder(self, cap: int):
        return lambda: SH.build_range_shuffle_program(
            self.mesh, self.orders, self.nparts, cap)

    def _local_pid(self, batch, base_key):
        from spark_rapids_tpu.runtime.kernel_cache import cached_kernel
        fn = cached_kernel(
            ("ici_mp_range_pid",) + base_key,
            lambda: SH.range_pid_fn(self.orders))
        return fn(batch, self._bounds)


def ici_active(conf) -> bool:
    """ICI shuffle requested, a real mesh exists, and the exchange is
    not conf-pinned to the host transport (``exchange.mode=host`` keeps
    ICI planning off entirely — exchanges then run the host-shuffle
    transport and sort/window/aggregate skip the distributed split)."""
    if conf.shuffle_mode != "ICI":
        return False
    if conf.exchange_mode == "host":
        return False
    import jax
    return jax.device_count() > 1


def hashable_on_device(dt: T.DataType) -> bool:
    try:
        from spark_rapids_tpu.plan.overrides import is_device_supported_type
        return is_device_supported_type(dt) is None
    except ImportError:
        return False
