"""FusedStageExec: one exec node, one jitted program, N operators.

[REF: sql-plugin/../basicPhysicalOperators.scala :: GpuTieredProject;
 Spark WholeStageCodegenExec]

The fusion pass (fusion/regions.py) replaces a chain of fusable map
operators with one of these.  Execution composes the members'
``fusion()`` functions bottom-up into a single batch→batch function and
compiles it once through ``cached_kernel`` under a region signature
(the tuple of member cache keys), so per batch the whole chain costs
one pump boundary and one XLA dispatch — the intermediate batches the
unfused chain would materialize exist only as SSA values inside the
program.  Because the region is ONE exec node, the auto-wrapped pump
stack (stats / cancel / shape-bucket / prefetch) and the shape plane's
pad-mask handling also run once per region instead of once per member.

Fall-open: the member nodes keep their original chain wiring (bottom
member → shared source), so if the region program fails to build or
trace on its first dispatch the region permanently reverts to pumping
that unfused chain — counted in ``tpuq_fusion_fallback_total`` and
flagged by the ``fusionFellOpen`` metric.  Failures after the first
successful dispatch are real execution failures and propagate through
``cached_kernel``'s execute failure domain like any operator's.

A region is itself a pure batch→batch map, so it exposes ``fusion()``
too: an aggregate that tiers its upstream maps into its own kernel
(``fuse_upstream``) absorbs the whole region exactly as it absorbed
the loose chain before the fusion plane existed.
"""

from __future__ import annotations

from typing import Iterator, List, Sequence

from spark_rapids_tpu.columnar.column import DeviceBatch
from spark_rapids_tpu.exec.base import TpuExec


class FusedStageExec(TpuExec):
    def __init__(self, members: Sequence[TpuExec], sigs: List[dict],
                 child: TpuExec):
        if not members:
            raise ValueError("a fused region needs at least one member")
        super().__init__(members[0].schema, child)
        self.members = list(members)  # top-down (consumer first)
        # member metadata consumed by the stats plane: each member's
        # pre-fusion plan signature/path, so profile records stay
        # diffable against unfused history (runtime/stats.py)
        self.fusion_members = list(sigs)
        self._region_key = ("fused_region",) + tuple(
            s["key"] for s in sigs)
        self._fell_open = False

    def node_string(self) -> str:
        names = "+".join(
            m.name[:-4] if m.name.endswith("Exec") else m.name
            for m in self.members)
        return f"FusedStage [fused: {names}]"

    def fusion(self):
        return self._composed(), self._region_key

    def _composed(self):
        # bottom-up application order: members are stored top-down
        fns = [m.fusion()[0] for m in reversed(self.members)]

        def run(batch: DeviceBatch) -> DeviceBatch:
            for f in fns:
                batch = f(batch)
            return batch

        return run

    def _fall_open(self) -> None:
        from spark_rapids_tpu import fusion as F
        self._fell_open = True
        self.metric("fusionFellOpen").value = 1
        F.FALLBACKS.inc()

    def execute(self, partition: int) -> Iterator[DeviceBatch]:
        from spark_rapids_tpu import fusion as F
        from spark_rapids_tpu.runtime.kernel_cache import (
            cached_kernel, compile_snapshot)
        fn = None
        if not self._fell_open:
            try:
                fn = cached_kernel(self._region_key, self._composed)
            except Exception:
                self._fall_open()
        if self._fell_open:
            yield from self.members[0].execute(partition)
            return
        first = True
        for b in self.children[0].execute(partition):
            try:
                if first:
                    c0, s0 = compile_snapshot()
                with self.timer():
                    out = fn(b)
                if first:
                    c1, s1 = compile_snapshot()
                    if c1 > c0:
                        self.metric("regionCompileTime").add(s1 - s0)
                        F.COMPILE_SECONDS.inc(s1 - s0)
            except Exception:
                if not first:
                    raise  # a real mid-stream execution failure
                # nothing yielded yet: fall open to the unfused chain,
                # which re-pulls the shared source from scratch
                self._fall_open()
                yield from self.members[0].execute(partition)
                return
            first = False
            self.metric("numOutputBatches").add(1)
            yield out
