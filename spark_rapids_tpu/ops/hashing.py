"""Bit-exact Spark murmur3 hash — the shuffle-partitioning keystone.

[REF: spark-rapids-jni :: src/main/cpp/src/murmur_hash.cu, SURVEY §2.2 N9]
Spark's ``hash()`` / ``HashPartitioning`` use Murmur3_x86_32 with seed 42
and Spark-specific quirks that MUST be reproduced bit-for-bit or shuffle
partitions disagree with Spark CPU results:

* each column's hash seeds the next (h = hash(col_i, h), h0 = 42)
* nulls leave the running hash unchanged
* int/short/byte/bool hash as a single 4-byte block; long/timestamp as 8
* float/double: NaN canonicalized to the positive quiet NaN bit pattern,
  -0.0 is NOT normalized (Spark hashes the raw bits)
* strings: 4-byte little-endian blocks, then TAIL BYTES ARE EACH
  SIGN-EXTENDED AND MIXED AS A FULL BLOCK (Spark's hashUnsafeBytes —
  deviates from canonical murmur3)
* decimal(<=18): unscaled long

Three implementations, cross-checked in tests: pure-python scalar
(reference), vectorized numpy (CPU exec path), and jax (device path,
uint32 lane ops on the VPU).
"""

from __future__ import annotations

import dataclasses
from typing import List

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.columnar.host import HostCol
from spark_rapids_tpu.ops.expressions import Expression

C1 = 0xCC9E2D51
C2 = 0x1B873593
SEED = 42

# ---------------------------------------------------------------------------
# pure-python scalar reference
# ---------------------------------------------------------------------------

_M = 0xFFFFFFFF


def _rotl_py(x, r):
    return ((x << r) | (x >> (32 - r))) & _M


def _mix_k1_py(k1):
    k1 = (k1 * C1) & _M
    k1 = _rotl_py(k1, 15)
    return (k1 * C2) & _M


def _mix_h1_py(h1, k1):
    h1 ^= k1
    h1 = _rotl_py(h1, 13)
    return (h1 * 5 + 0xE6546B64) & _M


def _fmix_py(h1, length):
    h1 ^= length
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & _M
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & _M
    h1 ^= h1 >> 16
    return h1


def hash_int_py(value: int, seed: int) -> int:
    h1 = _mix_h1_py(seed & _M, _mix_k1_py(value & _M))
    return _fmix_py(h1, 4)


def hash_long_py(value: int, seed: int) -> int:
    low = value & _M
    high = (value >> 32) & _M
    h1 = _mix_h1_py(seed & _M, _mix_k1_py(low))
    h1 = _mix_h1_py(h1, _mix_k1_py(high))
    return _fmix_py(h1, 8)


def hash_bytes_py(data: bytes, seed: int) -> int:
    h1 = seed & _M
    n = len(data)
    aligned = n - n % 4
    for i in range(0, aligned, 4):
        block = int.from_bytes(data[i:i + 4], "little")
        h1 = _mix_h1_py(h1, _mix_k1_py(block))
    for i in range(aligned, n):
        b = data[i]
        if b >= 128:
            b -= 256  # sign-extend
        h1 = _mix_h1_py(h1, _mix_k1_py(b & _M))
    return _fmix_py(h1, n)


def _f32_bits(v: float) -> int:
    b = np.float32(v).view(np.uint32)
    return int(b)


def _f64_bits(v: float) -> int:
    return int(np.float64(v).view(np.uint64))


def spark_hash_py(values: List, dtypes: List[T.DataType],
                  seed: int = SEED) -> int:
    """Row hash across columns, python reference."""
    h = seed
    for v, dt in zip(values, dtypes):
        if v is None:
            continue
        if isinstance(dt, (T.ByteType, T.ShortType, T.IntegerType,
                           T.DateType)):
            h = hash_int_py(int(v) & _M, h)
        elif isinstance(dt, T.BooleanType):
            h = hash_int_py(1 if v else 0, h)
        elif isinstance(dt, (T.LongType, T.TimestampType)):
            h = hash_long_py(int(v), h)
        elif isinstance(dt, T.FloatType):
            f = np.float32(v)
            bits = (0x7FC00000 if np.isnan(f) else _f32_bits(v))
            h = hash_int_py(bits, h)
        elif isinstance(dt, T.DoubleType):
            d = np.float64(v)
            bits = (0x7FF8000000000000 if np.isnan(d) else _f64_bits(v))
            h = hash_long_py(bits, h)
        elif isinstance(dt, T.StringType):
            h = hash_bytes_py(v.encode() if isinstance(v, str) else v, h)
        elif isinstance(dt, T.DecimalType):
            h = hash_long_py(int(v), h)  # caller passes unscaled
        else:
            raise NotImplementedError(f"hash of {dt}")
    # java int
    return h - (1 << 32) if h >= (1 << 31) else h


# ---------------------------------------------------------------------------
# vectorized (numpy / jax share the code via xp dispatch on uint32)
# ---------------------------------------------------------------------------


def _rotl(x, r, xp):
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _mix_k1(k1, xp):
    k1 = k1 * np.uint32(C1)
    k1 = _rotl(k1, 15, xp)
    return k1 * np.uint32(C2)


def _mix_h1(h1, k1, xp):
    h1 = h1 ^ k1
    h1 = _rotl(h1, 13, xp)
    return h1 * np.uint32(5) + np.uint32(0xE6546B64)


def _fmix(h1, length, xp):
    # length may be a python int OR a per-row array (string lengths) —
    # np.uint32() on a traced jax array would force a host conversion
    length = (np.uint32(length) if isinstance(length, (int, np.integer))
              else length.astype(np.uint32))
    h1 = h1 ^ length
    h1 = h1 ^ (h1 >> np.uint32(16))
    h1 = h1 * np.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> np.uint32(13))
    h1 = h1 * np.uint32(0xC2B2AE35)
    h1 = h1 ^ (h1 >> np.uint32(16))
    return h1


def _hash_int_vec(vals_u32, seed_u32, xp):
    h1 = _mix_h1(seed_u32, _mix_k1(vals_u32, xp), xp)
    return _fmix(h1, 4, xp)


def _hash_long_vec(vals_i64, seed_u32, xp):
    u = vals_i64.astype(np.uint64) if xp is np else vals_i64.astype(jnp.uint64)
    low = (u & np.uint64(_M)).astype(np.uint32)
    high = (u >> np.uint64(32)).astype(np.uint32)
    h1 = _mix_h1(seed_u32, _mix_k1(low, xp), xp)
    h1 = _mix_h1(h1, _mix_k1(high, xp), xp)
    return _fmix(h1, 8, xp)


def _hash_string_vec(mat, lengths, seed_u32, xp):
    """mat: uint8[B, W]; per-row Spark hashUnsafeBytes."""
    b, w = mat.shape
    h1 = seed_u32
    m32 = mat.astype(np.uint32)
    aligned = lengths - lengths % 4
    for blk in range(0, w - w % 4, 4):
        k = (m32[:, blk] | (m32[:, blk + 1] << np.uint32(8))
             | (m32[:, blk + 2] << np.uint32(16))
             | (m32[:, blk + 3] << np.uint32(24)))
        active = blk < aligned
        mixed = _mix_h1(h1, _mix_k1(k, xp), xp)
        h1 = xp.where(active, mixed, h1)
    # tail bytes: sign-extended single-byte blocks
    for pos in range(w):
        active = (pos >= aligned) & (pos < lengths)
        byte = m32[:, pos]
        signed = xp.where(byte >= 128,
                          byte | np.uint32(0xFFFFFF00), byte)
        mixed = _mix_h1(h1, _mix_k1(signed.astype(np.uint32), xp), xp)
        h1 = xp.where(active, mixed, h1)
    return _fmix(h1, lengths.astype(np.uint32), xp)


def _canon_float_bits(data, xp):
    f32 = data.astype(np.float32)
    bits = f32.view(np.uint32) if xp is np else jax_view32(f32)
    return xp.where(xp.isnan(f32), np.uint32(0x7FC00000), bits)


def _canon_double_bits(data, xp):
    f64 = data.astype(np.float64)
    if xp is np:
        bits = f64.view(np.uint64)
    else:
        bits = jax_view64(f64)
    nanbits = np.uint64(0x7FF8000000000000)
    return xp.where(xp.isnan(f64), nanbits, bits).astype(np.int64)


def jax_view32(f32):
    return jax_bitcast(f32, jnp.uint32)


def jax_view64(f64):
    return jax_bitcast(f64, jnp.uint64)


def jax_bitcast(x, dt):
    import jax.lax as lax
    return lax.bitcast_convert_type(x, dt)


def host_strings_to_matrix(data) -> tuple:
    """Host object-array of str/bytes → (uint8[B, W] matrix, int32
    lengths) — the one shared padding helper for every host hash/shuffle
    path."""
    enc = [v.encode() if isinstance(v, str) else bytes(v) for v in data]
    w = max(max((len(v) for v in enc), default=1), 1)
    mat = np.zeros((len(enc), w), np.uint8)
    lengths = np.zeros(len(enc), np.int32)
    for i, v in enumerate(enc):
        mat[i, :len(v)] = np.frombuffer(v, np.uint8)
        lengths[i] = len(v)
    return mat, lengths


def hash_column(col, dt: T.DataType, h, valid, xp):
    """Mix one column into running uint32 hash h; rows where ~valid keep h."""
    if isinstance(col, DeviceColumn) or isinstance(col, HostCol):
        raise TypeError("pass raw arrays")
    data, lengths = col
    if isinstance(dt, (T.ByteType, T.ShortType, T.IntegerType, T.DateType)):
        if xp is np:
            v = data.astype(np.int32).view(np.uint32)
        else:
            v = jax_bitcast(data.astype(jnp.int32), jnp.uint32)
        nh = _hash_int_vec(v, h, xp)
    elif isinstance(dt, T.BooleanType):
        v = data.astype(np.uint32)
        nh = _hash_int_vec(v, h, xp)
    elif isinstance(dt, (T.LongType, T.TimestampType)):
        nh = _hash_long_vec(data.astype(np.int64), h, xp)
    elif isinstance(dt, T.FloatType):
        nh = _hash_int_vec(_canon_float_bits(data, xp), h, xp)
    elif isinstance(dt, T.DoubleType):
        nh = _hash_long_vec(_canon_double_bits(data, xp), h, xp)
    elif isinstance(dt, T.DecimalType):
        if getattr(data, "ndim", 1) == 2:
            # decimal128 (hi, lo): mix both lanes.  Internal-consistency
            # hash (grouping/partitioning); NOT bit-exact with Spark's
            # byte-array hash of wide decimals.
            nh = _hash_long_vec(data[..., 1].astype(np.int64), h, xp)
            nh = _hash_long_vec(data[..., 0].astype(np.int64), nh, xp)
        elif data.dtype == object:
            from spark_rapids_tpu.ops.decimal128 import np_pack
            pair = np_pack(list(data))
            nh = _hash_long_vec(pair[:, 1], h, xp)
            nh = _hash_long_vec(pair[:, 0], nh, xp)
        else:
            nh = _hash_long_vec(data.astype(np.int64), h, xp)
    elif isinstance(dt, (T.StringType, T.BinaryType)):
        nh = _hash_string_vec(data, lengths, h, xp)
    else:
        raise NotImplementedError(f"hash of {dt}")
    return xp.where(valid, nh, h)


def _np_int32_from_u32(h):
    return h.astype(np.int64).astype(np.int32) if isinstance(h, np.ndarray) \
        else h


@dataclasses.dataclass
class Murmur3Hash(Expression):
    exprs: List[Expression]
    seed: int = SEED
    dtype: T.DataType = dataclasses.field(default_factory=T.IntegerType)

    @property
    def name(self):
        return "Murmur3Hash"

    @property
    def children(self):
        return tuple(self.exprs)

    def eval_tpu(self, batch):
        b = batch.capacity
        h = jnp.full((b,), self.seed, jnp.uint32)
        for e in self.exprs:
            c = e.eval_tpu(batch)
            h = hash_column((c.data, c.lengths), e.dtype, h,
                            c.valid_mask(), jnp)
        return DeviceColumn(self.dtype, jax_bitcast(h, jnp.int32).astype(jnp.int32))

    def eval_cpu(self, batch):
        n = batch.num_rows
        h = np.full(n, self.seed, np.uint32)
        for e in self.exprs:
            c = e.eval_cpu(batch)
            if isinstance(e.dtype, (T.StringType, T.BinaryType)):
                data = host_strings_to_matrix(c.data)
            else:
                data = (c.data, None)
            h = hash_column(data, e.dtype, h, c.valid_mask(), np)
        return HostCol(self.dtype, h.view(np.int32))


def partition_ids_from_hash(h_i32, num_partitions: int, xp):
    """Spark pmod(hash, n): non-negative partition id."""
    n = np.int32(num_partitions)
    r = h_i32 % n
    return xp.where(r < 0, r + n, r).astype(np.int32)


# ---------------------------------------------------------------------------
# xxhash64 — Spark's second hash family [REF: spark-rapids-jni ::
# src/main/cpp/src/xxhash64.cu; Spark XXH64.java semantics]
#
# Same column protocol as murmur3 (seed chain h = hash(col_i, h), h0 = 42,
# nulls leave h unchanged), but 64-bit lanes.  uint64 arithmetic wraps in
# both numpy and jax (x64 mode), so one xp-dispatched implementation
# serves the CPU oracle and the device path; a scalar python reference
# cross-checks both in tests.
# ---------------------------------------------------------------------------

XXH_P1 = 0x9E3779B185EBCA87
XXH_P2 = 0xC2B2AE3D27D4EB4F
XXH_P3 = 0x165667B19E3779F9
XXH_P4 = 0x85EBCA77C2B2AE63
XXH_P5 = 0x27D4EB2F165667C5
_M64 = 0xFFFFFFFFFFFFFFFF


def _rotl64_py(x, r):
    return ((x << r) | (x >> (64 - r))) & _M64


def _fmix64_py(h):
    h ^= h >> 33
    h = (h * XXH_P2) & _M64
    h ^= h >> 29
    h = (h * XXH_P3) & _M64
    h ^= h >> 32
    return h


def xxh_int_py(i: int, seed: int) -> int:
    h = (seed + XXH_P5 + 4) & _M64
    h ^= ((i & 0xFFFFFFFF) * XXH_P1) & _M64
    h = (_rotl64_py(h, 23) * XXH_P2 + XXH_P3) & _M64
    return _fmix64_py(h)


def xxh_long_py(v: int, seed: int) -> int:
    h = (seed + XXH_P5 + 8) & _M64
    h ^= (_rotl64_py((v * XXH_P2) & _M64, 31) * XXH_P1) & _M64
    h = (_rotl64_py(h, 27) * XXH_P1 + XXH_P4) & _M64
    return _fmix64_py(h)


def xxh_bytes_py(data: bytes, seed: int) -> int:
    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + XXH_P1 + XXH_P2) & _M64
        v2 = (seed + XXH_P2) & _M64
        v3 = seed & _M64
        v4 = (seed - XXH_P1) & _M64
        while i + 32 <= n:
            for k, v in enumerate((v1, v2, v3, v4)):
                x = int.from_bytes(data[i + 8 * k:i + 8 * k + 8],
                                   "little")
                v = (v + x * XXH_P2) & _M64
                v = (_rotl64_py(v, 31) * XXH_P1) & _M64
                if k == 0:
                    v1 = v
                elif k == 1:
                    v2 = v
                elif k == 2:
                    v3 = v
                else:
                    v4 = v
            i += 32
        h = (_rotl64_py(v1, 1) + _rotl64_py(v2, 7)
             + _rotl64_py(v3, 12) + _rotl64_py(v4, 18)) & _M64
        for v in (v1, v2, v3, v4):
            h ^= (_rotl64_py((v * XXH_P2) & _M64, 31) * XXH_P1) & _M64
            h = (h * XXH_P1 + XXH_P4) & _M64
    else:
        h = (seed + XXH_P5) & _M64
    h = (h + n) & _M64
    while i + 8 <= n:
        k = int.from_bytes(data[i:i + 8], "little")
        h ^= (_rotl64_py((k * XXH_P2) & _M64, 31) * XXH_P1) & _M64
        h = (_rotl64_py(h, 27) * XXH_P1 + XXH_P4) & _M64
        i += 8
    if i + 4 <= n:
        k = int.from_bytes(data[i:i + 4], "little")
        h ^= (k * XXH_P1) & _M64
        h = (_rotl64_py(h, 23) * XXH_P2 + XXH_P3) & _M64
        i += 4
    while i < n:
        h ^= (data[i] * XXH_P5) & _M64
        h = (_rotl64_py(h, 11) * XXH_P1) & _M64
        i += 1
    return _fmix64_py(h)


def spark_xxhash_py(values: List, dtypes: List[T.DataType],
                    seed: int = SEED) -> int:
    """Row xxhash64 across columns, python reference (java long out)."""
    h = seed & _M64
    for v, dt in zip(values, dtypes):
        if v is None:
            continue
        if isinstance(dt, (T.ByteType, T.ShortType, T.IntegerType,
                           T.DateType)):
            h = xxh_int_py(int(v) & 0xFFFFFFFF, h)
        elif isinstance(dt, T.BooleanType):
            h = xxh_int_py(1 if v else 0, h)
        elif isinstance(dt, (T.LongType, T.TimestampType)):
            h = xxh_long_py(int(v) & _M64, h)
        elif isinstance(dt, T.FloatType):
            f = np.float32(v)
            bits = 0x7FC00000 if np.isnan(f) else _f32_bits(v)
            h = xxh_int_py(bits, h)
        elif isinstance(dt, T.DoubleType):
            d = np.float64(v)
            bits = (0x7FF8000000000000 if np.isnan(d) else _f64_bits(v))
            h = xxh_long_py(bits, h)
        elif isinstance(dt, T.StringType):
            h = xxh_bytes_py(v.encode() if isinstance(v, str) else v, h)
        elif isinstance(dt, T.DecimalType):
            h = xxh_long_py(int(v) & _M64, h)
        else:
            raise NotImplementedError(f"xxhash64 of {dt}")
    return h - (1 << 64) if h >= (1 << 63) else h


# -- vectorized (numpy / jnp via xp dispatch on uint64 lanes) ---------------

def _u64(x):
    return np.uint64(x)


def _rotl64(x, r, xp):
    return (x << _u64(r)) | (x >> _u64(64 - r))


def _fmix64(h, xp):
    h = h ^ (h >> _u64(33))
    h = h * _u64(XXH_P2)
    h = h ^ (h >> _u64(29))
    h = h * _u64(XXH_P3)
    h = h ^ (h >> _u64(32))
    return h


def _xxh_int_vec(vals_u32, seed_u64, xp):
    h = seed_u64 + _u64(XXH_P5 + 4)
    h = h ^ (vals_u32.astype(np.uint64) * _u64(XXH_P1))
    h = _rotl64(h, 23, xp) * _u64(XXH_P2) + _u64(XXH_P3)
    return _fmix64(h, xp)


def _xxh_long_vec(vals_u64, seed_u64, xp):
    h = seed_u64 + _u64(XXH_P5 + 8)
    h = h ^ (_rotl64(vals_u64 * _u64(XXH_P2), 31, xp) * _u64(XXH_P1))
    h = _rotl64(h, 27, xp) * _u64(XXH_P1) + _u64(XXH_P4)
    return _fmix64(h, xp)


def _xxh_string_vec(mat, lengths, seed_u64, xp):
    """Per-row Spark XXH64.hashUnsafeBytes over a uint8[B, W] matrix.

    Lane-masked unrolling: every row walks the same W-wide loop; inactive
    positions keep the running state unchanged."""
    b, w = mat.shape
    m64 = mat.astype(np.uint64)
    len64 = lengths.astype(np.uint64)

    def le_word(base, nbytes):
        k = len64 * _u64(0)
        for byte in range(nbytes):
            col = base + byte
            if col < w:
                k = k | (m64[:, col] << _u64(8 * byte))
        return k

    stripes = (lengths // 32) * 32
    big = lengths >= 32
    # seed_u64 is the per-row running hash, so the accumulators are
    # per-row lanes from the start
    v1 = seed_u64 + _u64((XXH_P1 + XXH_P2) & _M64)
    v2 = seed_u64 + _u64(XXH_P2)
    v3 = seed_u64 + _u64(0)
    v4 = seed_u64 - _u64(XXH_P1)
    for base in range(0, w - w % 32, 32):
        active = base < stripes
        for k_i, acc in enumerate((v1, v2, v3, v4)):
            x = le_word(base + 8 * k_i, 8)
            nv = _rotl64(acc + x * _u64(XXH_P2), 31, xp) * _u64(XXH_P1)
            if k_i == 0:
                v1 = xp.where(active, nv, v1)
            elif k_i == 1:
                v2 = xp.where(active, nv, v2)
            elif k_i == 2:
                v3 = xp.where(active, nv, v3)
            else:
                v4 = xp.where(active, nv, v4)
    h_big = (_rotl64(v1, 1, xp) + _rotl64(v2, 7, xp)
             + _rotl64(v3, 12, xp) + _rotl64(v4, 18, xp))
    for acc in (v1, v2, v3, v4):
        h_big = h_big ^ (_rotl64(acc * _u64(XXH_P2), 31, xp)
                         * _u64(XXH_P1))
        h_big = h_big * _u64(XXH_P1) + _u64(XXH_P4)
    h_small = seed_u64 + _u64(XXH_P5) + len64 * _u64(0)
    h = xp.where(big, h_big, h_small)
    h = h + len64
    # trailing 8-byte words after the stripes
    rem8_end = stripes + ((lengths - stripes) // 8) * 8
    for base in range(0, w - w % 8, 8):
        active = (base >= stripes) & (base < rem8_end)
        k = le_word(base, 8)
        nh = h ^ (_rotl64(k * _u64(XXH_P2), 31, xp) * _u64(XXH_P1))
        nh = _rotl64(nh, 27, xp) * _u64(XXH_P1) + _u64(XXH_P4)
        h = xp.where(active, nh, h)
    # one 4-byte word
    rem4_end = rem8_end + ((lengths - rem8_end) // 4) * 4
    for base in range(0, w - w % 4, 4):
        active = (base >= rem8_end) & (base < rem4_end)
        k = le_word(base, 4)
        nh = h ^ ((k & _u64(0xFFFFFFFF)) * _u64(XXH_P1))
        nh = _rotl64(nh, 23, xp) * _u64(XXH_P2) + _u64(XXH_P3)
        h = xp.where(active, nh, h)
    # tail bytes
    for pos in range(w):
        active = (pos >= rem4_end) & (pos < lengths)
        nh = h ^ (m64[:, pos] * _u64(XXH_P5))
        nh = _rotl64(nh, 11, xp) * _u64(XXH_P1)
        h = xp.where(active, nh, h)
    return _fmix64(h, xp)


def xxhash_column(col, dt: T.DataType, h, valid, xp):
    """Mix one column into the running uint64 hash h (nulls keep h)."""
    data, lengths = col
    if isinstance(dt, (T.ByteType, T.ShortType, T.IntegerType, T.DateType)):
        if xp is np:
            v = data.astype(np.int32).view(np.uint32)
        else:
            v = jax_bitcast(data.astype(jnp.int32), jnp.uint32)
        nh = _xxh_int_vec(v, h, xp)
    elif isinstance(dt, T.BooleanType):
        nh = _xxh_int_vec(data.astype(np.uint32), h, xp)
    elif isinstance(dt, (T.LongType, T.TimestampType)):
        if xp is np:
            v = data.astype(np.int64).view(np.uint64)
        else:
            v = data.astype(jnp.int64).astype(jnp.uint64)
        nh = _xxh_long_vec(v, h, xp)
    elif isinstance(dt, T.FloatType):
        nh = _xxh_int_vec(_canon_float_bits(data, xp), h, xp)
    elif isinstance(dt, T.DoubleType):
        bits = _canon_double_bits(data, xp)  # int64 canonical bits
        if xp is np:
            v = bits.view(np.uint64)
        else:
            v = bits.astype(jnp.uint64)
        nh = _xxh_long_vec(v, h, xp)
    elif isinstance(dt, T.DecimalType):
        if getattr(data, "ndim", 1) == 2:
            # decimal128 (hi, lo) device lanes: mix both (internal
            # consistency, not bit-exact with Spark's byte-array hash)
            lo64 = data[..., 1]
            hi64 = data[..., 0]
            if xp is np:
                nh = _xxh_long_vec(lo64.astype(np.int64).view(np.uint64),
                                   h, xp)
                nh = _xxh_long_vec(hi64.astype(np.int64).view(np.uint64),
                                   nh, xp)
            else:
                nh = _xxh_long_vec(lo64.astype(jnp.uint64), h, xp)
                nh = _xxh_long_vec(hi64.astype(jnp.uint64), nh, xp)
        elif data.dtype == object:
            from spark_rapids_tpu.ops.decimal128 import np_pack
            pair = np_pack(list(data))
            nh = _xxh_long_vec(pair[:, 1].view(np.uint64), h, xp)
            nh = _xxh_long_vec(pair[:, 0].view(np.uint64), nh, xp)
        else:
            if xp is np:
                v = data.astype(np.int64).view(np.uint64)
            else:
                v = data.astype(jnp.int64).astype(jnp.uint64)
            nh = _xxh_long_vec(v, h, xp)
    elif isinstance(dt, (T.StringType, T.BinaryType)):
        nh = _xxh_string_vec(data, lengths, h, xp)
    else:
        raise NotImplementedError(f"xxhash64 of {dt}")
    return xp.where(valid, nh, h)


@dataclasses.dataclass
class XxHash64(Expression):
    """xxhash64(cols) → long [REF: spark-rapids-jni xxhash64.cu]."""

    exprs: List[Expression]
    seed: int = SEED
    dtype: T.DataType = dataclasses.field(default_factory=T.LongType)

    @property
    def name(self):
        return "XxHash64"

    @property
    def children(self):
        return tuple(self.exprs)

    def eval_tpu(self, batch):
        b = batch.capacity
        h = jnp.full((b,), self.seed, jnp.uint64)
        for e in self.exprs:
            c = e.eval_tpu(batch)
            h = xxhash_column((c.data, c.lengths), e.dtype, h,
                              c.valid_mask(), jnp)
        return DeviceColumn(self.dtype, h.astype(jnp.int64))

    def eval_cpu(self, batch):
        n = batch.num_rows
        h = np.full(n, self.seed, np.uint64)
        for e in self.exprs:
            c = e.eval_cpu(batch)
            if isinstance(e.dtype, (T.StringType, T.BinaryType)):
                data = host_strings_to_matrix(c.data)
            else:
                data = (c.data, None)
            h = xxhash_column(data, e.dtype, h, c.valid_mask(), np)
        return HostCol(self.dtype, h.view(np.int64))


# -- TypeSig declarations (see expressions.py) ------------------------------
from spark_rapids_tpu.ops import expressions as _E  # noqa: E402

Murmur3Hash.type_sig = _E.SIG_INTEGRAL
Murmur3Hash.input_sig = _E.SIG_ALL_SCALAR
XxHash64.type_sig = _E.SIG_INTEGRAL
XxHash64.input_sig = _E.SIG_ALL_SCALAR
