"""Aggregate functions with Spark result-type and null semantics.

[REF: sql-plugin/../aggregate/ :: GpuAggregateFunction, GpuSum, GpuMin,
 GpuMax, GpuCount, GpuAverage]

Two evaluation modes, mirroring the reference's partial/merge/final split:

* ``update``: per-batch segment reduction over sorted groups (device) or
  per-group numpy reduction (host).  Produces the partial buffer columns.
* ``merge``: combines partial buffers with the SAME reduction (sum of
  sums, min of mins, sum of counts) — this is what makes multi-batch and
  post-shuffle final aggregation correct.
* ``final``: projects the result column from buffer columns (avg = sum /
  count; everything else is identity).

Spark semantics honored: sum(int*) -> long, sum over empty/all-null group
-> null, count never null, avg -> double.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.ops.expressions import Expression


@dataclasses.dataclass
class AggregateFunction:
    child: Expression  # bound input expression (ignored for CountStar)

    # class attributes (NOT dataclass fields — subclasses override them)
    name = "agg"
    # reduction kind per buffer column: "sum" | "min" | "max" | "first"
    buffer_kinds = None

    @property
    def input_dtype(self) -> T.DataType:
        return self.child.dtype

    @property
    def result_dtype(self) -> T.DataType:
        raise NotImplementedError

    def buffer_dtypes(self) -> List[T.DataType]:
        raise NotImplementedError


class Sum(AggregateFunction):
    name = "sum"
    buffer_kinds = ["sum", "sum"]  # (sum, valid_count)

    @property
    def result_dtype(self):
        dt = self.input_dtype
        if T.is_integral(dt):
            return T.LongT
        if isinstance(dt, T.DecimalType):
            return T.DecimalType(min(dt.precision + 10, 38), dt.scale)
        return T.DoubleT

    def buffer_dtypes(self):
        return [self.result_dtype, T.LongT]


class Min(AggregateFunction):
    name = "min"
    buffer_kinds = ["min"]

    @property
    def result_dtype(self):
        return self.input_dtype

    def buffer_dtypes(self):
        return [self.input_dtype]


class Max(AggregateFunction):
    name = "max"
    buffer_kinds = ["max"]

    @property
    def result_dtype(self):
        return self.input_dtype

    def buffer_dtypes(self):
        return [self.input_dtype]


class Count(AggregateFunction):
    """count(expr): number of non-null values."""

    name = "count"
    buffer_kinds = ["sum"]

    @property
    def result_dtype(self):
        return T.LongT

    def buffer_dtypes(self):
        return [T.LongT]


class CountStar(AggregateFunction):
    name = "count_star"
    buffer_kinds = ["sum"]

    @property
    def result_dtype(self):
        return T.LongT

    def buffer_dtypes(self):
        return [T.LongT]


class Average(AggregateFunction):
    name = "avg"
    buffer_kinds = ["sum", "sum"]  # (sum as double, valid_count)

    @property
    def result_dtype(self):
        return T.DoubleT

    def buffer_dtypes(self):
        return [T.DoubleT, T.LongT]


class First(AggregateFunction):
    """first(expr, ignoreNulls=False) — order-dependent; within this engine
    batches preserve input order so 'first' is the first row of the group."""

    name = "first"
    buffer_kinds = ["first"]

    @property
    def result_dtype(self):
        return self.input_dtype

    def buffer_dtypes(self):
        return [self.input_dtype]


class _VarianceBase(AggregateFunction):
    """Variance family over a sum-of-squares buffer decomposition.

    [REF: aggregate/GpuStddev/GpuVariance — cuDF M2 buffers there]
    TPU re-design: buffers are (Σx, Σx², n) — plain "sum" kinds that ride
    the existing segment-reduce/merge protocol (a joint Welford/M2 merge
    would need a multi-column combine the scan kernels don't have).
    Trade-off vs Spark's Welford: catastrophic cancellation for
    |mean| >> stddev data; tests compare with float tolerance.
    """

    buffer_kinds = ["sum", "sum", "sum"]  # Σx, Σx², valid n
    ddof = 1          # sample by default
    sqrt_final = False

    @property
    def result_dtype(self):
        return T.DoubleT

    def buffer_dtypes(self):
        return [T.DoubleT, T.DoubleT, T.LongT]


class VarianceSamp(_VarianceBase):
    name = "var_samp"
    ddof = 1


class VariancePop(_VarianceBase):
    name = "var_pop"
    ddof = 0


class StddevSamp(_VarianceBase):
    name = "stddev_samp"
    ddof = 1
    sqrt_final = True


class StddevPop(_VarianceBase):
    name = "stddev_pop"
    ddof = 0
    sqrt_final = True


class CountDistinct(AggregateFunction):
    """count(DISTINCT x) — planner-rewritten into a two-level aggregate
    (dedup groupby on (keys, x) below a plain count), so it never reaches
    the kernels.  [REF: Spark's RewriteDistinctAggregates]"""

    name = "count_distinct"

    @property
    def result_dtype(self):
        return T.LongT


class CollectList(AggregateFunction):
    """collect_list(x) → array<x> — each group's values in input order.

    Device design (TPU-idiom, mirrors the string layout): the result
    column is a padded element matrix [G, Lmax] + lengths, produced
    scatter-free from the sorted-groupby order (each group's rows are
    contiguous after the stable key sort, so group g's list is one
    gather from its start offset).  Lmax is the pow-2 bucket of the
    largest group (one host sync, like the join's output sizing).
    Whole-aggregation runs single-kernel over the gathered input
    (no partial/merge: merging variable-length buffers needs a
    re-collect, deferred).  [REF: GpuCollectList]
    """

    name = "collect_list"
    buffer_kinds = ["collect"]

    @property
    def result_dtype(self):
        return T.ArrayType(self.input_dtype)

    def buffer_dtypes(self):
        return [self.result_dtype]


class CollectSet(CollectList):
    """collect_set(x) → array<x> — each group's DISTINCT values.

    Spark leaves set element order undefined; here both the device
    kernel and the CPU oracle emit ascending value order (sorted-group
    dedup falls out of the same stable sort the collect path already
    pays for).  [REF: GpuCollectSet]
    """

    name = "collect_set"


@dataclasses.dataclass
class Percentile(AggregateFunction):
    """percentile(x, p) — EXACT percentile with linear interpolation,
    computed holistically over value-sorted groups (one stable sort +
    two gathers — no scatter).  [REF: GpuPercentileDefault]"""

    pct: float = 0.5
    name = "percentile"
    buffer_kinds = ["collect"]  # holistic: whole-agg single kernel

    @property
    def result_dtype(self):
        return T.DoubleT

    def buffer_dtypes(self):
        return [self.result_dtype]


@dataclasses.dataclass
class ApproxPercentile(Percentile):
    """approx_percentile(x, p[, accuracy]) — nearest-rank percentile.

    The reference sketches with t-digest; this engine computes the
    holistic nearest-rank element directly (a zero-rank-error answer is
    inside any accuracy bound, so results can differ from Spark's
    t-digest OUTPUT while being at least as accurate; the value is
    always an actual element of the group).  [REF:
    ApproxPercentileFromTDigest]"""

    accuracy: int = 10000
    name = "approx_percentile"

    @property
    def result_dtype(self):
        return self.input_dtype
