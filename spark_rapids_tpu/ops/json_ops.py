"""JSON expressions — phase-1 host evaluation.

[REF: sql-plugin/../GpuGetJsonObject + spark-rapids-jni
 get_json_object kernel; GpuJsonToStructs]  The reference runs a CUDA
JSON tokenizer; the TPU path for byte-matrix JSON scanning is planned as
a Pallas kernel (SURVEY N9) — until then these expressions evaluate on
the HOST (the CPU oracle path), and the plan-rewrite engine tags their
subtree with a clear NOT_ON_TPU reason instead of failing.

Semantics follow Spark's ``get_json_object``:

* malformed JSON input → null (never an error, non-ANSI),
* path must start with ``$``; ``.field``, ``['field']`` and ``[index]``
  steps; a missing step → null,
* a matched STRING value returns its raw (unquoted) text; any other
  matched value returns its JSON serialization.

Known divergence (documented): numbers re-serialize through Python
(``1.00`` → ``1.0``) and object key order is preserved but whitespace is
normalized — byte-exactness with Spark's raw-token extraction is the
device kernel's job.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import List, Optional

import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.host import HostBatch, HostCol
from spark_rapids_tpu.ops.expressions import (
    SIG_STRINGY, Expression)

_STEP_RE = re.compile(
    r"\.(?P<field>[A-Za-z_][A-Za-z0-9_]*)"
    r"|\[\s*'(?P<qfield>[^']*)'\s*\]"
    r"|\[\s*\"(?P<dqfield>[^\"]*)\"\s*\]"
    r"|\[\s*(?P<index>\d+)\s*\]")


def parse_json_path(path: str) -> Optional[List[object]]:
    """``$.a.b[0]`` → ['a', 'b', 0]; None when the path is invalid
    (Spark: invalid path → null result for every row)."""
    if not path or not path.startswith("$"):
        return None
    steps: List[object] = []
    pos = 1
    while pos < len(path):
        m = _STEP_RE.match(path, pos)
        if m is None:
            return None
        if m.group("field") is not None:
            steps.append(m.group("field"))
        elif m.group("qfield") is not None:
            steps.append(m.group("qfield"))
        elif m.group("dqfield") is not None:
            steps.append(m.group("dqfield"))
        else:
            steps.append(int(m.group("index")))
        pos = m.end()
    return steps


def extract_json_path(doc: str, steps: List[object]) -> Optional[str]:
    try:
        v = json.loads(doc)
    except (ValueError, TypeError):
        return None
    for s in steps:
        if isinstance(s, int):
            if not isinstance(v, list) or s >= len(v):
                return None
            v = v[s]
        else:
            if not isinstance(v, dict) or s not in v:
                return None
            v = v[s]
    if v is None:
        return None
    if isinstance(v, str):
        return v
    return json.dumps(v, separators=(",", ":"), ensure_ascii=False)


@dataclasses.dataclass
class GetJsonObject(Expression):
    """get_json_object(json, path) → string | null."""

    child: Expression
    path: str
    dtype: T.DataType = dataclasses.field(
        default_factory=lambda: T.StringT)

    type_sig = SIG_STRINGY
    input_sig = SIG_STRINGY

    @property
    def children(self):
        return (self.child,)

    def eval_cpu(self, batch: HostBatch) -> HostCol:
        c = self.child.eval_cpu(batch)
        steps = parse_json_path(self.path)
        n = len(c.data)
        out = np.empty(n, dtype=object)
        validity = np.zeros(n, bool)
        if steps is not None:
            for i in range(n):
                if c.validity is not None and not c.validity[i]:
                    continue
                v = c.data[i]
                if isinstance(v, bytes):
                    v = v.decode("utf-8", "replace")
                r = extract_json_path(v, steps)
                if r is not None:
                    out[i] = r
                    validity[i] = True
        for i in range(n):
            if out[i] is None:
                out[i] = ""
        return HostCol(T.StringT, out, validity)

    def __str__(self):
        return f"get_json_object({self.child}, {self.path!r})"
