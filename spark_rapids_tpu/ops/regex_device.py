"""Device regex engine: Java-regex subset → DFA tables interpreted over
the byte matrices.

[REF: sql-plugin/../RegexParser.scala :: CudfRegexTranspiler — the
reference transpiles Java regex to cuDF's regex engine; SURVEY §2.2 N5
prescribes "pre-compiled NFA table interpreted in a kernel" for TPU.]

Pipeline (plan time, pattern is a literal): parse the supported subset →
Thompson NFA → subset-construction DFA over the 256-byte alphabet →
``DeviceRegex`` (transition table int32[S,256], accept bool[S], flags).
Unsupported constructs return ``None`` and the expression stays on the
host ``re`` path with a tag reason.

Matching (device or host — ONE shared simulation, so the CPU oracle and
the kernel agree byte-for-byte): all match starts run simultaneously as
a [B, W] state matrix; step j feeds byte j to every run whose start
s <= j.  The result is the leftmost-LONGEST match-length table — equal
to Java's leftmost-greedy result for the gated subset (alternation is
excluded from extract/replace, where greedy != longest can differ).

Byte-level semantics: ``.`` and classes act on BYTES.  For ASCII data
this equals Java exactly; multi-byte UTF-8 code points count as
multiple ``.`` positions (documented divergence, same on both paths).

Supported: literals, escapes (\\n \\t \\r \\d \\D \\w \\W \\s \\S \\.
etc.), ``.``, char classes with ranges/negation, ``(?:...)``/``(...)``
grouping (no capture extraction), ``|``, greedy ``* + ? {m} {m,}
{m,n}``, ``^`` at pattern start, ``$`` at pattern end (Java find
semantics: also matches before a final \\n, \\r\\n or \\r).
Rejected: lazy/possessive quantifiers, backreferences, lookaround,
mid-pattern anchors, \\b, \\p{...}, non-ASCII pattern characters.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

MAX_DFA_STATES = 192
_LINE_TERMS = (10, 13)  # \n, \r — '.' excludes these (Java non-DOTALL)


class Unsupported(Exception):
    pass


# ---------------------------------------------------------------------------
# Parser → NFA (Thompson construction)
# ---------------------------------------------------------------------------

class _Nfa:
    def __init__(self):
        self.eps: List[List[int]] = []
        self.trans: List[List[Tuple[np.ndarray, int]]] = []

    def new_state(self) -> int:
        self.eps.append([])
        self.trans.append([])
        return len(self.eps) - 1


def _class_bytes(chars) -> np.ndarray:
    m = np.zeros(256, bool)
    for c in chars:
        m[c] = True
    return m


_D = _class_bytes(range(48, 58))
_W = _class_bytes(list(range(48, 58)) + list(range(65, 91))
                  + list(range(97, 123)) + [95])
_S = _class_bytes([32, 9, 10, 11, 12, 13])
_DOT = ~_class_bytes(_LINE_TERMS)


def _escape_set(ch: str) -> Optional[np.ndarray]:
    if ch == "d":
        return _D
    if ch == "D":
        return ~_D
    if ch == "w":
        return _W
    if ch == "W":
        return ~_W
    if ch == "s":
        return _S
    if ch == "S":
        return ~_S
    return None


_ESC_LIT = {"n": 10, "t": 9, "r": 13, "f": 12, "a": 7, "e": 27}


class _Parser:
    """Recursive-descent over the supported subset; builds NFA fragments
    (start, end) with eps/byte-set transitions."""

    def __init__(self, pattern: str, nfa: _Nfa):
        self.p = pattern
        self.i = 0
        self.nfa = nfa

    def peek(self) -> str:
        return self.p[self.i] if self.i < len(self.p) else ""

    def take(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def parse(self) -> Tuple[int, int]:
        s, e = self.alternation()
        if self.i != len(self.p):
            raise Unsupported(f"unexpected '{self.peek()}'")
        return s, e

    def alternation(self) -> Tuple[int, int]:
        frags = [self.concat()]
        while self.peek() == "|":
            self.take()
            frags.append(self.concat())
        if len(frags) == 1:
            return frags[0]
        s = self.nfa.new_state()
        e = self.nfa.new_state()
        for fs, fe in frags:
            self.nfa.eps[s].append(fs)
            self.nfa.eps[fe].append(e)
        return s, e

    def concat(self) -> Tuple[int, int]:
        s = self.nfa.new_state()
        cur = s
        while self.peek() not in ("", "|", ")"):
            fs, fe = self.repeat()
            self.nfa.eps[cur].append(fs)
            cur = fe
        return s, cur

    def repeat(self) -> Tuple[int, int]:
        fs, fe = self.atom()
        while self.peek() in ("*", "+", "?", "{"):
            op = self.peek()
            if op == "{":
                save = self.i
                lo, hi = self._braces()
                if lo is None:
                    self.i = save
                    break
                fs, fe = self._repeat_range(fs, fe, lo, hi)
            else:
                self.take()
                if self.peek() in ("?", "+"):
                    raise Unsupported("lazy/possessive quantifier")
                if op == "*":
                    fs, fe = self._repeat_range(fs, fe, 0, None)
                elif op == "+":
                    fs, fe = self._repeat_range(fs, fe, 1, None)
                else:
                    fs, fe = self._repeat_range(fs, fe, 0, 1)
            # only one quantifier per atom (a** is a Java error anyway)
            break
        return fs, fe

    def _braces(self):
        assert self.take() == "{"
        num = ""
        while self.peek().isdigit():
            num += self.take()
        if not num:
            return None, None
        lo = int(num)
        hi = lo
        if self.peek() == ",":
            self.take()
            num2 = ""
            while self.peek().isdigit():
                num2 += self.take()
            hi = int(num2) if num2 else None
        if self.peek() != "}":
            return None, None
        self.take()
        if self.peek() in ("?", "+"):
            raise Unsupported("lazy/possessive quantifier")
        if lo > 64 or (hi is not None and (hi > 64 or hi < lo)):
            raise Unsupported("repetition count too large")
        return lo, hi

    def _clone(self, fs: int, fe: int) -> Tuple[int, int]:
        """Deep-copy an NFA fragment (for counted repetition)."""
        mapping: Dict[int, int] = {}
        stack = [fs]
        seen = {fs}
        order = []
        while stack:
            st = stack.pop()
            order.append(st)
            for t in self.nfa.eps[st]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
            for _, t in self.nfa.trans[st]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        for st in order:
            mapping[st] = self.nfa.new_state()
        for st in order:
            self.nfa.eps[mapping[st]] = [mapping[t]
                                         for t in self.nfa.eps[st]]
            self.nfa.trans[mapping[st]] = [
                (bs, mapping[t]) for bs, t in self.nfa.trans[st]]
        return mapping[fs], mapping[fe]

    def _repeat_range(self, fs, fe, lo, hi) -> Tuple[int, int]:
        s = self.nfa.new_state()
        cur = s
        for _ in range(lo):
            cs, ce = self._clone(fs, fe)
            self.nfa.eps[cur].append(cs)
            cur = ce
        e = self.nfa.new_state()
        if hi is None:  # unbounded tail: loop
            cs, ce = self._clone(fs, fe)
            self.nfa.eps[cur].append(cs)
            self.nfa.eps[cur].append(e)
            self.nfa.eps[ce].append(cs)
            self.nfa.eps[ce].append(e)
        else:
            for _ in range(hi - lo):
                cs, ce = self._clone(fs, fe)
                self.nfa.eps[cur].append(cs)
                self.nfa.eps[cur].append(e)
                cur = ce
            self.nfa.eps[cur].append(e)
        return s, e

    def _byte_frag(self, byteset: np.ndarray) -> Tuple[int, int]:
        s = self.nfa.new_state()
        e = self.nfa.new_state()
        self.nfa.trans[s].append((byteset, e))
        return s, e

    def atom(self) -> Tuple[int, int]:
        ch = self.peek()
        if ch == "(":
            self.take()
            if self.peek() == "?":
                self.take()
                if self.peek() != ":":
                    raise Unsupported("lookaround / named group")
                self.take()
            frag = self.alternation()
            if self.peek() != ")":
                raise Unsupported("unbalanced group")
            self.take()
            return frag
        if ch == "[":
            return self._byte_frag(self._char_class())
        if ch == ".":
            self.take()
            return self._byte_frag(_DOT)
        if ch == "\\":
            self.take()
            if self.i >= len(self.p):
                raise Unsupported("trailing backslash")
            nxt = self.take()
            cls = _escape_set(nxt)
            if cls is not None:
                return self._byte_frag(cls)
            if nxt in ("b", "B", "A", "Z", "z", "G"):
                raise Unsupported(f"anchor escape \\{nxt}")
            if nxt in ("p", "P"):
                raise Unsupported("\\p classes")
            if nxt.isdigit():
                raise Unsupported("backreference / octal escape")
            code = _ESC_LIT.get(nxt, None)
            if code is None:
                if ord(nxt) > 127:
                    raise Unsupported("non-ASCII pattern")
                if nxt.isalnum():
                    # \x41, \uFFFF, \cX, \Q...: Java-special escapes
                    raise Unsupported(f"escape \\{nxt}")
                code = ord(nxt)
            return self._byte_frag(_class_bytes([code]))
        if ch in ("^", "$"):
            raise Unsupported("mid-pattern anchor")
        if ch in ("*", "+", "?", "{", ")"):
            raise Unsupported(f"dangling '{ch}'")
        self.take()
        if ord(ch) > 127:
            raise Unsupported("non-ASCII pattern")
        return self._byte_frag(_class_bytes([ord(ch)]))

    def _char_class(self) -> np.ndarray:
        assert self.take() == "["
        neg = False
        if self.peek() == "^":
            neg = True
            self.take()
        mask = np.zeros(256, bool)
        first = True
        while True:
            ch = self.peek()
            if ch == "":
                raise Unsupported("unterminated class")
            if ch == "]" and not first:
                self.take()
                break
            first = False
            if ch == "\\":
                self.take()
                nxt = self.take()
                cls = _escape_set(nxt)
                if cls is not None:
                    mask |= cls
                    continue
                code = _ESC_LIT.get(nxt)
                if code is None:
                    if nxt.isalnum():
                        raise Unsupported(f"escape \\{nxt} in class")
                    code = ord(nxt)
                lo_c = code
            else:
                self.take()
                if ord(ch) > 127:
                    raise Unsupported("non-ASCII pattern")
                lo_c = ord(ch)
            if self.peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self.take()
                hc = self.take()
                if hc == "\\":
                    hc = self.take()
                    hi_c = _ESC_LIT.get(hc)
                    if hi_c is None:
                        if hc.isalnum():
                            raise Unsupported(f"escape \\{hc} in class")
                        hi_c = ord(hc)
                else:
                    if ord(hc) > 127:
                        raise Unsupported("non-ASCII pattern")
                    hi_c = ord(hc)
                if hi_c < lo_c:
                    raise Unsupported("bad class range")
                mask[lo_c:hi_c + 1] = True
            else:
                mask[lo_c] = True
        return ~mask if neg else mask


# ---------------------------------------------------------------------------
# NFA → DFA (subset construction)
# ---------------------------------------------------------------------------

def _eps_closure(nfa: _Nfa, states: FrozenSet[int]) -> FrozenSet[int]:
    out = set(states)
    stack = list(states)
    while stack:
        s = stack.pop()
        for t in nfa.eps[s]:
            if t not in out:
                out.add(t)
                stack.append(t)
    return frozenset(out)


@dataclasses.dataclass
class DeviceRegex:
    table: np.ndarray          # int32 [S, 256]; state 0 = dead
    accept: np.ndarray         # bool [S]
    start_state: int
    anchored_start: bool
    anchored_end: bool
    has_alternation: bool
    matches_empty: bool
    pattern: str


def compile_regex(pattern: str) -> Optional[DeviceRegex]:
    """DFA-compile the pattern; None when outside the device subset."""
    try:
        if any(ord(c) > 127 for c in pattern):
            raise Unsupported("non-ASCII pattern")
        body = pattern
        anchored_start = body.startswith("^")
        if anchored_start:
            body = body[1:]
        anchored_end = body.endswith("$") and not body.endswith("\\$")
        if anchored_end:
            body = body[:-1]
        if anchored_start or anchored_end:
            # Java scopes ^/$ to the adjacent ALTERNATIVE, not the whole
            # pattern ('^a|b' == (^a)|(b)) — reject top-level '|'
            depth = 0
            in_class = False
            i = 0
            while i < len(body):
                ch = body[i]
                if ch == "\\":
                    i += 2
                    continue
                if in_class:
                    in_class = ch != "]"
                elif ch == "[":
                    in_class = True
                elif ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                elif ch == "|" and depth == 0:
                    raise Unsupported("anchor with top-level alternation")
                i += 1
        nfa = _Nfa()
        parser = _Parser(body, nfa)
        start, end = parser.parse()
        has_alt = "|" in body

        s0 = _eps_closure(nfa, frozenset([start]))
        states: Dict[FrozenSet[int], int] = {s0: 1}
        worklist = [s0]
        rows = {1: np.zeros(256, np.int32)}
        accepts = {1: end in s0}
        while worklist:
            cur = worklist.pop()
            ci = states[cur]
            row = rows[ci]
            # group target NFA-state sets per byte
            move: List[Optional[set]] = [None] * 256
            for s in cur:
                for byteset, t in nfa.trans[s]:
                    idxs = np.nonzero(byteset)[0]
                    for bval in idxs:
                        if move[bval] is None:
                            move[bval] = set()
                        move[bval].add(t)
            cache: Dict[FrozenSet[int], int] = {}
            for bval in range(256):
                if move[bval] is None:
                    continue
                key = frozenset(move[bval])
                di = cache.get(key)
                if di is None:
                    clo = _eps_closure(nfa, key)
                    di = states.get(clo)
                    if di is None:
                        if len(states) + 1 > MAX_DFA_STATES:
                            raise Unsupported("DFA too large")
                        di = len(states) + 1
                        states[clo] = di
                        rows[di] = np.zeros(256, np.int32)
                        accepts[di] = end in clo
                        worklist.append(clo)
                    cache[key] = di
                row[bval] = di
        nstates = len(states) + 1
        table = np.zeros((nstates, 256), np.int32)
        accept = np.zeros(nstates, bool)
        for di, row in rows.items():
            table[di] = row
        for di, a in accepts.items():
            accept[di] = a
        return DeviceRegex(table, accept, 1, anchored_start,
                           anchored_end, has_alt, bool(accept[1]),
                           pattern)
    except Unsupported:
        return None
    except RecursionError:
        return None


# ---------------------------------------------------------------------------
# Shared simulation (jnp on device, np on the CPU oracle — identical)
# ---------------------------------------------------------------------------

def _end_ok_mask(data, lengths, rx: DeviceRegex, xp):
    """[B, W+1] — position p is a legal match END.

    Unanchored: any p <= len.  ``$``: p == len, or just before a final
    line terminator (Java Pattern ``$`` under find, non-UNIX_LINES):
    \\n, \\r\\n, \\r, and the Unicode terminators \\u0085 (UTF-8 C2 85)
    and \\u2028/\\u2029 (E2 80 A8|A9)."""
    b, w = data.shape
    pos = xp.arange(w + 1, dtype=xp.int32)[None, :]
    ln = lengths[:, None].astype(xp.int32)
    if not rx.anchored_end:
        return pos <= ln
    at_end = pos == ln
    idt = xp.int64 if xp is np else xp.int32

    def byte_at(off):
        ix = xp.clip(ln - off, 0, w - 1)
        return xp.take_along_axis(data, ix.astype(idt), axis=1)

    last_b = byte_at(1)
    last2_b = byte_at(2)
    last3_b = byte_at(3)
    is_nl = (last_b == 10) | (last_b == 13)
    crlf = (last2_b == 13) & (last_b == 10) & (ln >= 2)
    # Java's Dollar never matches BETWEEN \r and \n of a final CRLF
    before_final = (pos == ln - 1) & is_nl & (ln >= 1) & ~crlf
    before_crlf = (pos == ln - 2) & crlf
    nel = (last2_b == 0xC2) & (last_b == 0x85) & (ln >= 2)
    lsep = ((last3_b == 0xE2) & (last2_b == 0x80)
            & ((last_b == 0xA8) | (last_b == 0xA9)) & (ln >= 3))
    before_nel = (pos == ln - 2) & nel
    before_lsep = (pos == ln - 3) & lsep
    return (at_end | before_final | before_crlf
            | before_nel | before_lsep)


def match_lens(data, lengths, rx: DeviceRegex, xp):
    """Leftmost-longest match length per start → int32 [B, W+1]
    (-1 = no match at that start; column W covers the empty match at
    end-of-string).  Starts beyond the row length are -1 except the
    end-of-string empty-match column."""
    b, w = data.shape
    flat = rx.table.reshape(-1).astype(np.int32)
    acc = rx.accept
    if xp is not np:
        import jax.numpy as jnp
        flat = jnp.asarray(flat)
        acc = jnp.asarray(acc)
    col = xp.arange(w + 1, dtype=xp.int32)[None, :]
    ln = lengths[:, None].astype(xp.int32)
    end_ok = _end_ok_mask(data, lengths, rx, xp)
    valid_start = col <= ln
    if rx.anchored_start:
        valid_start = valid_start & (col == 0)
    state = xp.full((b, w + 1), rx.start_state, np.int32)
    mlen = xp.where(valid_start & rx.matches_empty & end_ok,
                    xp.int32(0), xp.int32(-1))
    if xp is np:
        for j in range(w):
            byte = data[:, j].astype(np.int32)[:, None]
            nxt = np.take(flat, state * 256 + byte)
            active = (col <= j) & (j < ln) & valid_start
            state = np.where(active, nxt, state)
            ok = np.take(acc, state) & active & end_ok[:, j + 1][:, None]
            mlen = np.where(ok, j + 1 - col, mlen)
        return mlen
    # device: lax.fori_loop keeps the traced graph O(1) in W (an
    # unrolled W-stage pipeline is exactly the compile-cost pathology
    # this backend budgets against)
    import jax
    import jax.numpy as jnp

    data_i = data.astype(jnp.int32)
    end_ok_i = end_ok

    def body(j, carry):
        state, mlen = carry
        byte = jax.lax.dynamic_slice_in_dim(data_i, j, 1, 1)  # [B,1]
        nxt = jnp.take(flat, state * 256 + byte)
        active = (col <= j) & (j < ln) & valid_start
        state = jnp.where(active, nxt, state)
        eok = jax.lax.dynamic_slice_in_dim(end_ok_i, j + 1, 1, 1)
        ok = jnp.take(acc, state) & active & eok
        mlen = jnp.where(ok, (j + 1 - col).astype(jnp.int32), mlen)
        return state, mlen

    _, mlen = jax.lax.fori_loop(0, w, body, (state, mlen))
    return mlen


def match_any(data, lengths, rx: DeviceRegex, xp):
    """Java Pattern.find existence per row → bool [B]."""
    return xp.any(match_lens(data, lengths, rx, xp) >= 0, axis=1)


def extract_first(data, lengths, rx: DeviceRegex, xp):
    """First (leftmost, longest) match substring per row →
    (matrix [B, W], lengths [B], matched bool [B]).  No match → ''."""
    b, w = data.shape
    ml = match_lens(data, lengths, rx, xp)
    has = xp.any(ml >= 0, axis=1)
    s0 = xp.argmax(ml >= 0, axis=1).astype(xp.int32)
    l0 = xp.take_along_axis(ml, s0[:, None].astype(
        xp.int64 if xp is np else xp.int32), axis=1)[:, 0]
    l0 = xp.where(has, l0, 0).astype(xp.int32)
    k = xp.arange(w, dtype=xp.int32)[None, :]
    idx = xp.clip(s0[:, None] + k, 0, w - 1)
    mat = xp.take_along_axis(
        data, idx.astype(xp.int64 if xp is np else xp.int32), axis=1)
    mat = xp.where(k < l0[:, None], mat, 0).astype(data.dtype)
    return mat, l0, has


def replace_all(data, lengths, rx: DeviceRegex, repl: bytes, xp):
    """Replace every non-overlapping leftmost match with the literal
    ``repl`` → (matrix [B, Wout], lengths [B]).  Gated upstream: no
    alternation, no empty-matching patterns, no $ group refs."""
    b, w = data.shape
    r = len(repl)
    ml = match_lens(data, lengths, rx, xp)[:, :w]
    ln = lengths[:, None].astype(xp.int32)
    if xp is np:
        nxt = np.zeros((b,), np.int32)
        covered = np.zeros((b,), np.int32)
        starts = []
        consumed = []
        for j in range(w):
            here = (j >= nxt) & (ml[:, j] >= 1)
            end_j = (j + ml[:, j]).astype(np.int32)
            nxt = np.where(here, end_j, nxt)
            covered = np.maximum(covered, np.where(here, end_j, 0))
            starts.append(here)
            consumed.append(j < covered)
        S = np.stack(starts, axis=1)
        C = np.stack(consumed, axis=1)
    else:
        import jax
        import jax.numpy as jnp

        def body(j, carry):
            nxt, covered, S, C = carry
            mlj = jax.lax.dynamic_slice_in_dim(ml, j, 1, 1)[:, 0]
            here = (j >= nxt) & (mlj >= 1)
            end_j = (j + mlj).astype(jnp.int32)
            nxt = jnp.where(here, end_j, nxt)
            covered = jnp.maximum(covered,
                                  jnp.where(here, end_j, 0))
            S = jax.lax.dynamic_update_slice_in_dim(
                S, here[:, None], j, 1)
            C = jax.lax.dynamic_update_slice_in_dim(
                C, (j < covered)[:, None], j, 1)
            return nxt, covered, S, C

        init = (jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.int32),
                jnp.zeros((b, w), bool), jnp.zeros((b, w), bool))
        _, _, S, C = jax.lax.fori_loop(0, w, body, init)
    col = xp.arange(w, dtype=xp.int32)[None, :]
    keep = (~C) & (col < ln)
    e = (r * S.astype(xp.int32) + keep.astype(xp.int32))
    offs = xp.cumsum(e, axis=1).astype(xp.int32)
    total = offs[:, -1]
    wout = max(w, w * max(r, 1))
    k = xp.arange(wout, dtype=xp.int32)
    if xp is np:
        j_idx = np.empty((b, wout), np.int32)
        for i in range(b):
            j_idx[i] = np.searchsorted(offs[i], k, side="right")
    else:
        import jax
        import jax.numpy as jnp
        j_idx = jax.vmap(
            lambda o: jnp.searchsorted(o, k, side="right"))(offs)
    j_c = xp.clip(j_idx, 0, w - 1)
    ga = (xp.int64 if xp is np else xp.int32)
    off_j = xp.take_along_axis(offs, j_c.astype(ga), axis=1)
    e_j = xp.take_along_axis(e, j_c.astype(ga), axis=1)
    oic = k[None, :] - (off_j - e_j)
    s_j = xp.take_along_axis(S, j_c.astype(ga), axis=1)
    is_repl = s_j & (oic < max(r, 1)) & (r > 0)
    repl_arr = (np.frombuffer(repl, np.uint8) if r else
                np.zeros(1, np.uint8))
    if xp is not np:
        import jax.numpy as jnp
        repl_arr = jnp.asarray(repl_arr)
    rb = xp.take(repl_arr, xp.clip(oic, 0, max(r - 1, 0)))
    db = xp.take_along_axis(data, j_c.astype(ga), axis=1)
    out = xp.where(is_repl, rb, db)
    valid = k[None, :] < total[:, None]
    return xp.where(valid, out, 0).astype(data.dtype), total
