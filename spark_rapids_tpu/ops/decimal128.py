"""decimal128 device arithmetic: int32-limb kernels over an int64[B,2]
(hi, lo) column representation.

[REF: NVIDIA/spark-rapids-jni :: src/main/cpp/src/decimal128 kernels —
the reference implements 128-bit decimal math in CUDA; SURVEY §2.2 N9]

TPU-first design notes:
* the device representation is two int64 lanes per row — ``data[:, 0]``
  the signed high limb, ``data[:, 1]`` the low limb's BIT PATTERN (an
  int64 holding a logically-unsigned value).  XLA's x64 int64 is native
  enough; only 64-bit *bitcasts* are forbidden on TPU, and none are
  used here.
* multiplication decomposes each 64-bit lane into 32-bit halves and
  runs wrapping schoolbook products: a 32x32 product's int64 BIT
  PATTERN is exact mod 2^64 even when it wraps negative, and its
  masked halves (& 0xFFFFFFFF, arithmetic-shift + mask) are the true
  unsigned halves — so the whole pipeline stays in int64 ops.
* division (avg, down-rescale) is vectorized long division over the
  four 32-bit limbs of |x| with a positive divisor < 2^31 — each step's
  partial remainder fits well inside a positive int64.

Overflow wraps mod 2^128 (non-ANSI Spark behavior for the enabled ops).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as T

# python-int constants: they bind lazily at op time (module import can
# precede the engine's x64 enablement, where jnp.int64(...) would clip)
_MASK32 = 0xFFFFFFFF
_SIGN = -0x8000000000000000  # 1 << 63 as int64


def is128(dt) -> bool:
    return (isinstance(dt, T.DecimalType)
            and dt.precision > T.DecimalType.MAX_LONG_DIGITS)


def pack(hi: jnp.ndarray, lo: jnp.ndarray) -> jnp.ndarray:
    return jnp.stack([hi, lo], axis=-1)


def hi(d: jnp.ndarray) -> jnp.ndarray:
    return d[..., 0]


def lo(d: jnp.ndarray) -> jnp.ndarray:
    return d[..., 1]


def _ult(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Unsigned a < b over int64 bit patterns."""
    return (a ^ _SIGN) < (b ^ _SIGN)


def from_i64(x: jnp.ndarray) -> jnp.ndarray:
    """Sign-extend an int64 unscaled value to (hi, lo)."""
    return pack(x >> jnp.int64(63), x)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    lo_s = lo(a) + lo(b)  # wraps mod 2^64
    carry = _ult(lo_s, lo(a)).astype(jnp.int64)
    return pack(hi(a) + hi(b) + carry, lo_s)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    lo_n = -lo(a)  # two's complement of the low lane
    borrow = (lo(a) != 0).astype(jnp.int64)
    return pack(-hi(a) - borrow, lo_n)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return add(a, neg(b))


def is_negative(a: jnp.ndarray) -> jnp.ndarray:
    return hi(a) < 0


def abs128(a: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    n = is_negative(a)
    return jnp.where(n[..., None], neg(a), a), n


def _limbs32(a: jnp.ndarray):
    """(hi, lo) -> four 32-bit limbs, most significant first, each held
    as a non-negative int64."""
    h, l = hi(a), lo(a)
    return ((h >> jnp.int64(32)) & _MASK32, h & _MASK32,
            (l >> jnp.int64(32)) & _MASK32, l & _MASK32)


def _from_limbs32(l3, l2, l1, l0) -> jnp.ndarray:
    """Four CARRY-FREE 32-bit limbs (each < 2^32) -> (hi, lo)."""
    return pack((l3 << jnp.int64(32)) | l2, (l1 << jnp.int64(32)) | l0)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a * b mod 2^128 (wrapping schoolbook over 32-bit limbs)."""
    a3, a2, a1, a0 = _limbs32(a)
    b3, b2, b1, b0 = _limbs32(b)

    def p(x, y):
        """32x32 product as (hi32, lo32) — the int64 product's bit
        pattern is exact mod 2^64 even when it wraps negative."""
        v = x * y
        return (v >> jnp.int64(32)) & _MASK32, v & _MASK32

    # column sums c_k of partial products contributing to limb k
    # (k = 0 least significant); each term < 2^32, <= 8 terms -> the
    # accumulators stay positive int64
    c0 = jnp.zeros_like(a0)
    c1 = jnp.zeros_like(a0)
    c2 = jnp.zeros_like(a0)
    c3 = jnp.zeros_like(a0)
    for i, ai in enumerate((a3, a2, a1, a0)):
        for j, bj in enumerate((b3, b2, b1, b0)):
            k = (3 - i) + (3 - j)  # limb index of the low half
            if k > 3:
                continue
            ph, pl = p(ai, bj)
            if k == 0:
                c0 = c0 + pl
                c1 = c1 + ph
            elif k == 1:
                c1 = c1 + pl
                c2 = c2 + ph
            elif k == 2:
                c2 = c2 + pl
                c3 = c3 + ph
            else:
                c3 = c3 + pl
    # carry propagation
    l0 = c0 & _MASK32
    c1 = c1 + (c0 >> jnp.int64(32))
    l1 = c1 & _MASK32
    c2 = c2 + (c1 >> jnp.int64(32))
    l2 = c2 & _MASK32
    c3 = c3 + (c2 >> jnp.int64(32))
    l3 = c3 & _MASK32
    return _from_limbs32(l3, l2, l1, l0)


def mul_small(a: jnp.ndarray, m: int) -> jnp.ndarray:
    """a * m mod 2^128 for a non-negative python int m < 2^31."""
    mm = jnp.int64(m)
    a3, a2, a1, a0 = _limbs32(a)
    p0 = a0 * mm
    p1 = a1 * mm
    p2 = a2 * mm
    p3 = a3 * mm
    l0 = p0 & _MASK32
    p1 = p1 + ((p0 >> jnp.int64(32)) & _MASK32)
    l1 = p1 & _MASK32
    p2 = p2 + ((p1 >> jnp.int64(32)) & _MASK32)
    l2 = p2 & _MASK32
    p3 = p3 + ((p2 >> jnp.int64(32)) & _MASK32)
    l3 = p3 & _MASK32
    return _from_limbs32(l3, l2, l1, l0)


def scale_up(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """a * 10^k mod 2^128 — factored into < 2^31 multipliers."""
    while k > 0:
        step = min(k, 9)
        a = mul_small(a, 10 ** step)
        k -= step
    return a


def scale_up_checked(a: jnp.ndarray, k: int, precision: int
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(a * 10^k, ok) where ok ⇔ |a * 10^k| < 10^precision, decided
    BEFORE the multiply: |a| < 10^(precision-k) ⇔ the exact product
    fits, so a wrap mod 2^128 can never land back inside the valid
    range and be returned as a plausible wrong value."""
    assert k >= 0
    rem = precision - k
    if rem <= 0:
        ok = cmp_eq(a, jnp.zeros_like(a))
    else:
        ok = fits_precision(a, rem)
    return scale_up(a, k), ok


def divmod_small(a: jnp.ndarray, d: int
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(|a| // d, |a| % d) with the SIGN of a applied to the quotient
    via the caller; a must already be non-negative.  d: positive python
    int < 2^31.  Vectorized long division over the 32-bit limbs."""
    dd = jnp.int64(d)
    limbs = _limbs32(a)
    r = jnp.zeros_like(limbs[0])
    q = []
    for l in limbs:
        cur = (r << jnp.int64(32)) | l  # < 2^63: r < d < 2^31
        q.append(cur // dd)
        r = cur % dd
    return _from_limbs32(*q), r


def div_small_round(a: jnp.ndarray, d: int) -> jnp.ndarray:
    """a / d with HALF_UP rounding away from zero (Spark decimal
    divide/average rounding); d: positive python int < 2^31."""
    mag, sign = abs128(a)
    q, r = divmod_small(mag, d)
    round_up = (r * jnp.int64(2) >= jnp.int64(d)).astype(jnp.int64)
    q = add(q, pack(jnp.zeros_like(round_up), round_up))
    return jnp.where(sign[..., None], neg(q), q)


def scale_down_round(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """a / 10^k with HALF_UP rounding; supported for k <= 9 (divisor
    must stay < 2^31 so the single rounding division is exact)."""
    if k == 0:
        return a
    assert k <= 9, "scale-down beyond 10^9 is tagged out"
    return div_small_round(a, 10 ** k)


def cmp_lt(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return (hi(a) < hi(b)) | ((hi(a) == hi(b)) & _ult(lo(a), lo(b)))


def cmp_eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return (hi(a) == hi(b)) & (lo(a) == lo(b))


def to_double(a: jnp.ndarray, scale: int) -> jnp.ndarray:
    """Approximate double value (hi * 2^64 + unsigned lo) / 10^scale."""
    l = lo(a)
    lo_u = (l & ~_SIGN).astype(jnp.float64) + jnp.where(
        l < 0, jnp.float64(2.0 ** 63), jnp.float64(0.0))
    v = hi(a).astype(jnp.float64) * jnp.float64(2.0 ** 64) + lo_u
    return v / jnp.float64(10.0 ** scale)


def np_pack(values) -> np.ndarray:
    """Host iterable of python ints -> int64[n, 2] (hi, lo)."""
    out = np.zeros((len(values), 2), dtype=np.int64)
    for i, v in enumerate(values):
        v = int(v)
        out[i, 0] = np.int64(v >> 64)  # arithmetic shift keeps sign
        l = v & 0xFFFFFFFFFFFFFFFF
        out[i, 1] = np.int64(l - (1 << 64) if l >= (1 << 63) else l)
    return out


def np_unpack(data: np.ndarray) -> np.ndarray:
    """int64[n, 2] -> host object-array of python ints."""
    n = data.shape[0]
    out = np.empty(n, dtype=object)
    for i in range(n):
        h = int(data[i, 0])
        l = int(data[i, 1]) & 0xFFFFFFFFFFFFFFFF
        out[i] = (h << 64) | l
    return out


def add_checked(a: jnp.ndarray, b: jnp.ndarray
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(a + b, ok) — ok False on signed-128 overflow (same-sign operands
    producing the opposite sign), so a wrap can never masquerade as an
    in-precision value."""
    s = add(a, b)
    sa, sb, sr = is_negative(a), is_negative(b), is_negative(s)
    return s, ~((sa == sb) & (sr != sa))


def sub_checked(a: jnp.ndarray, b: jnp.ndarray
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    return add_checked(a, neg(b))


def mul_checked(a: jnp.ndarray, b: jnp.ndarray
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(a * b, ok) — schoolbook over MAGNITUDES with explicit overflow
    detection (dropped high columns, final carry, or a magnitude taking
    the sign bit), so results beyond 2^127 cannot wrap back into the
    valid range."""
    ma, sa = abs128(a)
    mb, sb = abs128(b)
    a3, a2, a1, a0 = _limbs32(ma)
    b3, b2, b1, b0 = _limbs32(mb)

    def p(x, y):
        v = x * y
        return (v >> jnp.int64(32)) & _MASK32, v & _MASK32

    c0 = jnp.zeros_like(a0)
    c1 = jnp.zeros_like(a0)
    c2 = jnp.zeros_like(a0)
    c3 = jnp.zeros_like(a0)
    ovf = jnp.zeros(a0.shape, jnp.bool_)
    for i, ai in enumerate((a3, a2, a1, a0)):
        for j, bj in enumerate((b3, b2, b1, b0)):
            k = (3 - i) + (3 - j)
            ph, pl = p(ai, bj)
            if k > 3:
                ovf = ovf | (pl != 0) | (ph != 0)
                continue
            if k == 0:
                c0 = c0 + pl
                c1 = c1 + ph
            elif k == 1:
                c1 = c1 + pl
                c2 = c2 + ph
            elif k == 2:
                c2 = c2 + pl
                c3 = c3 + ph
            else:
                c3 = c3 + pl
                ovf = ovf | (ph != 0)
    l0 = c0 & _MASK32
    c1 = c1 + (c0 >> jnp.int64(32))
    l1 = c1 & _MASK32
    c2 = c2 + (c1 >> jnp.int64(32))
    l2 = c2 & _MASK32
    c3 = c3 + (c2 >> jnp.int64(32))
    l3 = c3 & _MASK32
    ovf = ovf | ((c3 >> jnp.int64(32)) != 0)
    mag = _from_limbs32(l3, l2, l1, l0)
    ovf = ovf | is_negative(mag)  # magnitude took the sign bit
    sign = sa ^ sb
    return jnp.where(sign[..., None], neg(mag), mag), ~ovf


def fits_precision(a: jnp.ndarray, precision: int) -> jnp.ndarray:
    """|a| < 10^precision — Spark nulls decimal results that overflow
    their declared precision (non-ANSI)."""
    bound = jnp.asarray(np_pack([10 ** precision]))[0]
    mag, _ = abs128(a)
    return cmp_lt(mag, jnp.broadcast_to(bound, mag.shape))


def py_wrap128(v: int) -> int:
    """Python-int twin of the device container: wrap mod 2^128 signed."""
    w = int(v) % (1 << 128)
    return w - (1 << 128) if w >= (1 << 127) else w


def py_fits(v: int, precision: int) -> bool:
    return abs(int(v)) < 10 ** precision


def py_rescale_half_up(v: int, k: int) -> int:
    """Exact python-int rescale by 10^k (HALF_UP away from zero for
    negative k) — no decimal.Context rounding surprises."""
    v = int(v)
    if k >= 0:
        return v * (10 ** k)
    d = 10 ** (-k)
    q, r = divmod(abs(v), d)
    q += 1 if 2 * r >= d else 0
    return -q if v < 0 else q


def py_unscaled(dec, scale: int) -> int:
    """Exact unscaled int of a decimal.Decimal at the given scale."""
    sign, digits, exp = dec.as_tuple()
    mag = int("".join(map(str, digits)) or "0")
    v = -mag if sign else mag
    return py_rescale_half_up(v, exp + scale)
