"""Datetime expressions.  [REF: sql-plugin/../datetimeExpressions.scala]

Dates are int32 days since epoch; timestamps int64 micros since epoch UTC
(see columnar/column.py).  Calendar decomposition uses the standard civil
calendar algorithm (integer-only, branch-free via where) so it lowers to
XLA cleanly — no table lookups or data-dependent control flow.

Timezone-sensitive ops (from_utc_timestamp etc.) need the timezone
transition LUT [SURVEY.md §2.2 N9]; until that lands they stay CPU-only.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.columnar.host import HostCol
from spark_rapids_tpu.ops.expressions import (
    Expression, merge_validity_d, merge_validity_h)

MICROS_PER_DAY = 86_400_000_000


def civil_from_days(z, xp):
    """days-since-epoch -> (year, month, day), integer ops only."""
    z = z.astype(xp.int64) + 719468
    era = xp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + xp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y.astype(xp.int32), m.astype(xp.int32), d.astype(xp.int32)


def days_from_civil(y, m, d, xp):
    """(year, month, day) -> days since epoch."""
    y = y.astype(xp.int64) - (m <= 2)
    era = xp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = xp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(xp.int32)


@dataclasses.dataclass
class _DateField(Expression):
    child: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.IntegerType)

    FIELD = 0  # 0=year 1=month 2=day

    @property
    def children(self):
        return (self.child,)

    def _days(self, data, xp):
        if isinstance(self.child.dtype, T.TimestampType):
            # floor to days (micros may be negative)
            return xp.where(data >= 0, data // MICROS_PER_DAY,
                            -((-data + MICROS_PER_DAY - 1) // MICROS_PER_DAY))
        return data

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        parts = civil_from_days(self._days(c.data, jnp), jnp)
        return DeviceColumn(self.dtype, parts[self.FIELD], c.validity)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        parts = civil_from_days(self._days(c.data, np), np)
        return HostCol(self.dtype, parts[self.FIELD], c.validity)


class Year(_DateField):
    FIELD = 0


class Month(_DateField):
    FIELD = 1


class DayOfMonth(_DateField):
    FIELD = 2


@dataclasses.dataclass
class DateAdd(Expression):
    """date_add(start, days) -> date."""

    left: Expression
    right: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.DateType)

    @property
    def children(self):
        return (self.left, self.right)

    def eval_tpu(self, batch):
        l = self.left.eval_tpu(batch)
        r = self.right.eval_tpu(batch)
        data = (l.data.astype(jnp.int64) + r.data.astype(jnp.int64)).astype(jnp.int32)
        return DeviceColumn(self.dtype, data,
                            merge_validity_d(l.validity, r.validity))

    def eval_cpu(self, batch):
        l = self.left.eval_cpu(batch)
        r = self.right.eval_cpu(batch)
        data = (l.data.astype(np.int64) + r.data.astype(np.int64)).astype(np.int32)
        return HostCol(self.dtype, data,
                       merge_validity_h(l.validity, r.validity))


@dataclasses.dataclass
class DateSub(Expression):
    left: Expression
    right: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.DateType)

    @property
    def children(self):
        return (self.left, self.right)

    def eval_tpu(self, batch):
        l = self.left.eval_tpu(batch)
        r = self.right.eval_tpu(batch)
        data = (l.data.astype(jnp.int64) - r.data.astype(jnp.int64)).astype(jnp.int32)
        return DeviceColumn(self.dtype, data,
                            merge_validity_d(l.validity, r.validity))

    def eval_cpu(self, batch):
        l = self.left.eval_cpu(batch)
        r = self.right.eval_cpu(batch)
        data = (l.data.astype(np.int64) - r.data.astype(np.int64)).astype(np.int32)
        return HostCol(self.dtype, data,
                       merge_validity_h(l.validity, r.validity))


@dataclasses.dataclass
class DateDiff(Expression):
    """datediff(end, start) -> int days."""

    left: Expression
    right: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.IntegerType)

    @property
    def children(self):
        return (self.left, self.right)

    def eval_tpu(self, batch):
        l = self.left.eval_tpu(batch)
        r = self.right.eval_tpu(batch)
        return DeviceColumn(self.dtype, (l.data - r.data).astype(jnp.int32),
                            merge_validity_d(l.validity, r.validity))

    def eval_cpu(self, batch):
        l = self.left.eval_cpu(batch)
        r = self.right.eval_cpu(batch)
        return HostCol(self.dtype, (l.data - r.data).astype(np.int32),
                       merge_validity_h(l.validity, r.validity))


# -- TypeSig declarations (see expressions.py) ------------------------------
from spark_rapids_tpu.ops import expressions as E  # noqa: E402

for _cls in (Year, Month, DayOfMonth):
    _cls.type_sig = E.SIG_INTEGRAL
    _cls.input_sig = E.SIG_DATETIME
for _cls in (DateAdd, DateSub):
    _cls.type_sig = E.SIG_DATETIME
    _cls.input_sig = E.SIG_DATETIME | E.SIG_INTEGRAL
DateDiff.type_sig = E.SIG_INTEGRAL
DateDiff.input_sig = E.SIG_DATETIME
