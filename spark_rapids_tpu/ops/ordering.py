"""Orderable-key encoding: any column → uint64 key columns whose unsigned
lexicographic order equals the SQL ordering.

This is the engine's device ordering primitive, shared by sort, sort-based
groupby, and sort-merge join (the roles cuDF's typed comparators play in
the reference [REF: cudf cpp/src/sort/ :: row lexicographic comparators]).
TPU-first: ``lax.sort`` is a fast multi-operand bitonic/merge sort but only
sorts ascending by unsigned key — so ordering semantics (descending,
nulls-first/last, NaN-last, -0.0 == 0.0 is NOT applied: Spark sorts by
total order where -0.0 < 0.0 is false; Spark treats them equal in
comparisons but sort is stable so either order is accepted by tests via
full-row comparison) are baked into the key encoding:

* signed ints: flip the sign bit → unsigned order == signed order
* floats: IEEE trick (negative → ~bits, else bits | sign) → total order
  with NaN greatest (Spark: NaN last ascending — matches)
* strings: big-endian packing of the padded byte matrix into ceil(W/8)
  uint64 limbs → unsigned limb order == bytewise (memcmp) order, which is
  Spark's UTF8String binary ordering
* bool/date/timestamp/decimal map through their physical ints
* descending: bitwise NOT of every key limb
* nulls: an extra leading key limb (0/1) positions nulls first or last
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.column import DeviceBatch, DeviceColumn


def _i_to_u64(x: jnp.ndarray) -> jnp.ndarray:
    """Signed int (any width) → order-preserving uint64."""
    x64 = x.astype(jnp.int64)
    return (x64.astype(jnp.uint64)) ^ jnp.uint64(1 << 63)


def _string_limbs(data: jnp.ndarray, lengths: jnp.ndarray) -> List[jnp.ndarray]:
    """uint8[B,W] + len → ceil(W/8) big-endian uint64 limbs.

    Bytes beyond each row's length are zeroed so 'ab' < 'ab\\x00…' padding
    can't corrupt comparisons (real NUL bytes inside strings still order
    correctly only when lengths differ at the same limb — to disambiguate
    'a' vs 'a\\0' a final length limb is appended by the caller).
    """
    b, w = data.shape
    wpad = (-w) % 8
    if wpad:
        data = jnp.pad(data, ((0, 0), (0, wpad)))
        w += wpad
    colidx = jnp.arange(w, dtype=jnp.int32)
    masked = jnp.where(colidx[None, :] < lengths[:, None], data,
                       jnp.uint8(0))
    limbs = []
    for i in range(w // 8):
        chunk = masked[:, i * 8:(i + 1) * 8].astype(jnp.uint64)
        limb = jnp.zeros((b,), jnp.uint64)
        for j in range(8):
            limb = (limb << jnp.uint64(8)) | chunk[:, j]
        limbs.append(limb)
    return limbs


def column_order_keys(col: DeviceColumn, ascending: bool = True,
                      nulls_first: bool = True,
                      distinguish_neg_zero: bool = True
                      ) -> List[jnp.ndarray]:
    """Encode one column as key limbs (most-significant first).

    Limbs are uint64 except floats, which stay RAW float limbs: XLA's
    ``lax.sort`` comparator is IEEE total order (-NaN < -inf < … < -0 <
    +0 < … < +inf < NaN), which matches Java ``Double.compare`` (Spark's
    ordering) once NaNs are canonicalized to the positive quiet NaN.  Raw
    floats avoid 64-bit bitcasts, which the TPU x64-rewrite pass cannot
    compile (f64↔u64 ``bitcast_convert_type`` fails on device — found by
    probing the real chip; see exec/aggregate.py float min/max for the
    same constraint).
    """
    dt = col.dtype
    if isinstance(dt, (T.StringType, T.BinaryType)):
        limbs = _string_limbs(col.data, col.lengths)
        limbs.append(col.lengths.astype(jnp.int64).astype(jnp.uint64))
        if not ascending:
            limbs = [~l for l in limbs]
    elif isinstance(dt, (T.FloatType, T.DoubleType)):
        # NaN placement rides its own limb: XLA negation does not flip
        # NaN's sign, so descending-by-negation alone would sort NaN last
        # instead of first.  Spark: NaN greatest (last asc, first desc).
        isn = jnp.isnan(col.data)
        nan_limb = jnp.where(isn, jnp.uint64(1 if ascending else 0),
                             jnp.uint64(0 if ascending else 1))
        zero = jnp.zeros((), col.data.dtype)
        val = jnp.where(isn, zero, col.data)
        limbs = [nan_limb, val if ascending else -val]
        if distinguish_neg_zero:
            # XLA's sort comparator treats -0.0 == 0.0; Spark (Java
            # Double.compare) orders -0.0 < 0.0.  signbit needs a bitcast
            # (unavailable for f64 on TPU), so detect the sign via 1/x.
            neg_zero = (col.data == zero) & ((jnp.ones(
                (), col.data.dtype) / col.data) < zero)
            limbs.append(jnp.where(
                neg_zero, jnp.uint64(0 if ascending else 1),
                jnp.uint64(1 if ascending else 0)))
    elif isinstance(dt, T.BooleanType):
        limbs = [col.data.astype(jnp.uint64)]
        if not ascending:
            limbs = [~l for l in limbs]
    else:  # integral, date, timestamp, decimal64
        limbs = [_i_to_u64(col.data)]
        if not ascending:
            limbs = [~l for l in limbs]
    # null limb: orders independently of direction: nulls_first ⇒ nulls 0
    if col.validity is not None:
        nl = jnp.where(col.validity,
                       jnp.uint64(1 if nulls_first else 0),
                       jnp.uint64(0 if nulls_first else 1))
        # also zero data limbs of nulls for deterministic grouping
        limbs = [jnp.where(col.validity, l, jnp.zeros((), l.dtype))
                 for l in limbs]
        limbs = [nl] + limbs
    return limbs


def limb_neq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Inequality under the grouping equivalence: NaN == NaN (one group),
    and IEEE -0.0 == 0.0 (Spark normalizes float group keys)."""
    if jnp.issubdtype(a.dtype, jnp.floating):
        return (a != b) & ~(jnp.isnan(a) & jnp.isnan(b))
    return a != b


def batch_group_keys(cols: List[DeviceColumn]) -> List[jnp.ndarray]:
    """Key limbs for GROUP BY (direction irrelevant; nulls one group;
    -0.0 and 0.0 one group — Spark normalizes float grouping keys)."""
    out: List[jnp.ndarray] = []
    for c in cols:
        out.extend(column_order_keys(c, True, True,
                                     distinguish_neg_zero=False))
    return out


def sort_by_keys(limbs: List[jnp.ndarray], payload=None
                 ) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
    """Stable lexicographic sort; returns (sorted limbs, permutation).

    The trailing iota doubles as stabilizer AND permutation output —
    sort operand count is the dominant TPU compile cost (measured ~25 s
    per u64 operand at 128k rows), so no separate payload operand.
    """
    import jax
    n = limbs[0].shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    operands = tuple(limbs) + (iota,)
    res = jax.lax.sort(operands, num_keys=len(limbs) + 1)
    return list(res[:len(limbs)]), res[-1]


# ----------------------------------------------------------------------------
# Host (numpy oracle) twin
# ----------------------------------------------------------------------------

def np_order_keys(data: np.ndarray, validity: Optional[np.ndarray],
                  dt: T.DataType, ascending: bool = True,
                  nulls_first: bool = True) -> List[np.ndarray]:
    if isinstance(dt, (T.StringType, T.BinaryType)):
        # host strings are object arrays — map to sortable tuples via bytes
        enc = np.array([
            v.encode() if isinstance(v, str) else bytes(v) for v in data
        ], dtype=object)
        mx = max((len(v) for v in enc), default=0)
        limbs = []
        padded = np.zeros((len(enc), mx + 1), dtype=np.uint8)
        for i, v in enumerate(enc):
            padded[i, :len(v)] = np.frombuffer(v, np.uint8)
        wpad = (-(mx + 1)) % 8
        padded = np.pad(padded, ((0, 0), (0, wpad)))
        for i in range(padded.shape[1] // 8):
            limb = np.zeros(len(enc), np.uint64)
            for j in range(8):
                limb = (limb << np.uint64(8)) | padded[:, i * 8 + j].astype(np.uint64)
            limbs.append(limb)
        limbs.append(np.array([len(v) for v in enc], np.uint64))
    elif isinstance(dt, T.FloatType):
        canon = np.where(np.isnan(data), np.float32(np.nan),
                         data.astype(np.float32))
        bits = canon.view(np.uint32)
        neg = (bits >> np.uint32(31)) != 0
        limbs = [np.where(neg, ~bits, bits | np.uint32(1 << 31)).astype(np.uint64)]
    elif isinstance(dt, T.DoubleType):
        canon = np.where(np.isnan(data), np.nan, data.astype(np.float64))
        bits = canon.view(np.uint64)
        neg = (bits >> np.uint64(63)) != 0
        limbs = [np.where(neg, ~bits, bits | np.uint64(1 << 63))]
    elif isinstance(dt, T.BooleanType):
        limbs = [data.astype(np.uint64)]
    else:
        limbs = [(data.astype(np.int64).view(np.uint64)) ^ np.uint64(1 << 63)]
    if not ascending:
        limbs = [~l for l in limbs]
    if validity is not None:
        nl = np.where(validity, np.uint64(1 if nulls_first else 0),
                      np.uint64(0 if nulls_first else 1))
        limbs = [np.where(validity, l, np.uint64(0)) for l in limbs]
        limbs = [nl] + limbs
    return limbs
