"""Orderable-key encoding: any column → uint64 key columns whose unsigned
lexicographic order equals the SQL ordering.

This is the engine's device ordering primitive, shared by sort, sort-based
groupby, and sort-merge join (the roles cuDF's typed comparators play in
the reference [REF: cudf cpp/src/sort/ :: row lexicographic comparators]).
TPU-first: ``lax.sort`` is a fast multi-operand bitonic/merge sort but only
sorts ascending by unsigned key — so ordering semantics (descending,
nulls-first/last, NaN-last, -0.0 == 0.0 is NOT applied: Spark sorts by
total order where -0.0 < 0.0 is false; Spark treats them equal in
comparisons but sort is stable so either order is accepted by tests via
full-row comparison) are baked into the key encoding:

* signed ints: flip the sign bit → unsigned order == signed order
* floats: IEEE trick (negative → ~bits, else bits | sign) → total order
  with NaN greatest (Spark: NaN last ascending — matches)
* strings: big-endian packing of the padded byte matrix into ceil(W/8)
  uint64 limbs → unsigned limb order == bytewise (memcmp) order, which is
  Spark's UTF8String binary ordering
* bool/date/timestamp/decimal map through their physical ints
* descending: bitwise NOT of every key limb
* nulls: an extra leading key limb (0/1) positions nulls first or last
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.column import DeviceBatch, DeviceColumn


def _i_to_u64(x: jnp.ndarray) -> jnp.ndarray:
    """Signed int (any width) → order-preserving uint64."""
    x64 = x.astype(jnp.int64)
    return (x64.astype(jnp.uint64)) ^ jnp.uint64(1 << 63)


# A key "part" is (array, bits): an order-preserving unsigned value held
# in a uint64 array occupying the low `bits` bits — or (array, "f64") for
# a raw float64 limb (unfusable: no 64-bit bitcast compiles on TPU).
# ``fuse_parts`` then packs consecutive parts into as few uint64 sort
# operands as possible: sort operand count is the dominant TPU compile
# cost (~25-60 s per extra operand at 128k rows, measured), so a typical
# (dead, null, int32-key) triple becomes ONE operand instead of three.
Part = Tuple[jnp.ndarray, object]


def _int_part(x: jnp.ndarray, width: int, ascending: bool) -> Part:
    if width == 64:
        u = _i_to_u64(x)
        return ((~u if not ascending else u), 64)
    bias = jnp.int64(1 << (width - 1))
    u = (x.astype(jnp.int64) + bias).astype(jnp.uint64)
    if not ascending:
        u = u ^ jnp.uint64((1 << width) - 1)
    return (u, width)


def _flag_part(flag_is_one: jnp.ndarray) -> Part:
    return (flag_is_one.astype(jnp.uint64), 1)


def _f32_orderable_u32(x: jnp.ndarray, normalize_zero: bool) -> jnp.ndarray:
    import jax
    canon = jnp.where(jnp.isnan(x), jnp.asarray(np.nan, jnp.float32), x)
    if normalize_zero:
        canon = jnp.where(canon == 0.0, jnp.asarray(0.0, jnp.float32),
                          canon)
    bits = jax.lax.bitcast_convert_type(canon.astype(jnp.float32),
                                        jnp.uint32)
    neg = (bits >> jnp.uint32(31)) != 0
    return jnp.where(neg, ~bits, bits | jnp.uint32(1 << 31))


def fuse_parts(parts: List[Part]) -> List[jnp.ndarray]:
    """Pack consecutive uint parts into shared uint64 limbs (big-endian:
    earlier = more significant), flushing around raw-float parts."""
    limbs: List[jnp.ndarray] = []
    acc = None
    used = 0
    for arr, bits in parts:
        if bits == "f64":
            if acc is not None:
                limbs.append(acc)
                acc, used = None, 0
            limbs.append(arr)
            continue
        if acc is None:
            acc, used = arr, bits
        elif used + bits <= 64:
            acc = (acc << jnp.uint64(bits)) | arr
            used += bits
        else:
            limbs.append(acc)
            acc, used = arr, bits
    if acc is not None:
        limbs.append(acc)
    return limbs


def _string_parts(data: jnp.ndarray, lengths: jnp.ndarray) -> List[Part]:
    """uint8[B,W] + len → big-endian packed byte parts + a length part.

    Bytes beyond each row's length are zeroed so 'ab' < 'ab\\x00…' padding
    can't corrupt comparisons; the trailing length part disambiguates
    real NUL bytes ('a' vs 'a\\0').  The final byte chunk is annotated
    with its true bit width so short strings fuse with neighbors.
    """
    b, w = data.shape
    colidx = jnp.arange(w, dtype=jnp.int32)
    masked = jnp.where(colidx[None, :] < lengths[:, None], data,
                       jnp.uint8(0))
    parts: List[Part] = []
    for i in range(0, w, 8):
        chunk = masked[:, i:i + 8].astype(jnp.uint64)
        nbytes = chunk.shape[1]
        limb = jnp.zeros((b,), jnp.uint64)
        for j in range(nbytes):
            limb = (limb << jnp.uint64(8)) | chunk[:, j]
        parts.append((limb, 8 * nbytes))
    parts.append((lengths.astype(jnp.int64).astype(jnp.uint64), 32))
    return parts


_INT_WIDTH = {T.ByteType: 8, T.ShortType: 16, T.IntegerType: 32,
              T.DateType: 32, T.LongType: 64, T.TimestampType: 64}


def column_order_parts(col: DeviceColumn, ascending: bool = True,
                       nulls_first: bool = True,
                       distinguish_neg_zero: bool = True) -> List[Part]:
    """Encode one column as key parts (most-significant first).

    Parts are width-annotated unsigned values (fused downstream) except
    float64, which stays a RAW float limb: XLA's ``lax.sort`` comparator
    is IEEE total order (-NaN < -inf < … < -0/+0 < … < +inf < NaN, zeros
    tied), which matches Java ``Double.compare`` (Spark's ordering) once
    NaNs are canonicalized and the zero tie is broken by a trailing
    sign part.  Raw f64 avoids 64-bit bitcasts, which the TPU
    x64-rewrite pass cannot compile (probed on the real chip); f32 CAN
    bitcast, so it rides orderable u32 bits.
    """
    dt = col.dtype
    parts: List[Part]
    if isinstance(dt, (T.StringType, T.BinaryType)):
        parts = _string_parts(col.data, col.lengths)
        if not ascending:
            parts = [(a ^ jnp.uint64((1 << b) - 1), b) for a, b in parts]
    elif isinstance(dt, T.FloatType):
        u = _f32_orderable_u32(col.data,
                               normalize_zero=not distinguish_neg_zero)
        if not ascending:
            u = ~u
        parts = [(u.astype(jnp.uint64), 32)]
    elif isinstance(dt, T.DoubleType):
        # NaN placement rides its own part: XLA negation does not flip
        # NaN's sign, so descending-by-negation alone would sort NaN
        # last instead of first.  Spark: NaN greatest.
        isn = jnp.isnan(col.data)
        nan_part = _flag_part(isn if ascending else ~isn)
        zero = jnp.zeros((), col.data.dtype)
        val = jnp.where(isn, zero, col.data)
        parts = [nan_part, (val if ascending else -val, "f64")]
        if distinguish_neg_zero:
            # XLA's sort treats -0.0 == 0.0; Spark orders -0.0 < 0.0.
            # signbit needs a bitcast, so detect the sign via 1/x.
            neg_zero = (col.data == zero) & ((jnp.ones(
                (), col.data.dtype) / col.data) < zero)
            parts.append(_flag_part(~neg_zero if ascending else neg_zero))
    elif isinstance(dt, T.BooleanType):
        parts = [(col.data.astype(jnp.uint64)
                  if ascending else (~col.data).astype(jnp.uint64), 1)]
    elif isinstance(dt, T.DecimalType):
        if dt.precision > T.DecimalType.MAX_LONG_DIGITS:
            # decimal128 [B,2]: signed-biased hi limb, then the lo
            # limb's raw (unsigned-ordered) bit pattern
            h = col.data[:, 0]
            l = col.data[:, 1]
            hp = _int_part(h, 64, ascending)
            lu = l.astype(jnp.uint64)
            if not ascending:
                lu = ~lu
            parts = [hp, (lu, 64)]
        else:
            parts = [_int_part(col.data, 64, ascending)]
    else:  # integral, date, timestamp
        parts = [_int_part(col.data, _INT_WIDTH[type(dt)], ascending)]
    # null part: orders independently of direction: nulls_first ⇒ nulls 0
    if col.validity is not None:
        np_ = _flag_part(col.validity if nulls_first else ~col.validity)
        # also zero data parts of nulls for deterministic grouping
        parts = [(jnp.where(col.validity, a, jnp.zeros((), a.dtype)), b)
                 for a, b in parts]
        parts = [np_] + parts
    return parts


def column_order_keys(col: DeviceColumn, ascending: bool = True,
                      nulls_first: bool = True,
                      distinguish_neg_zero: bool = True
                      ) -> List[jnp.ndarray]:
    """Single-column convenience wrapper: encode + fuse."""
    return fuse_parts(column_order_parts(
        col, ascending, nulls_first, distinguish_neg_zero))


def limb_neq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Inequality under the grouping equivalence: NaN == NaN (one group),
    and IEEE -0.0 == 0.0 (Spark normalizes float group keys)."""
    if jnp.issubdtype(a.dtype, jnp.floating):
        return (a != b) & ~(jnp.isnan(a) & jnp.isnan(b))
    return a != b


def batch_group_parts(cols: List[DeviceColumn]) -> List[Part]:
    """Key parts for GROUP BY (direction irrelevant; nulls one group;
    -0.0 and 0.0 one group — Spark normalizes float grouping keys)."""
    out: List[Part] = []
    for c in cols:
        out.extend(column_order_parts(c, True, True,
                                      distinguish_neg_zero=False))
    return out


# above this many fused limbs, group sorts switch to the 128-bit
# key-tuple hash: lax.sort compile cost grows superlinearly PER OPERAND
# on TPU (measured: ~21 s at 2 operands; a ~10-limb multi-string key
# set ran >25 min without finishing)
GROUP_HASH_LIMB_CAP = 3


def group_sort_limbs(cols: List[DeviceColumn], sel,
                     tail_parts: List[Part] = ()
                     ) -> Tuple[List[jnp.ndarray], List[jnp.ndarray]]:
    """(sort limbs, key-only limbs) for GROUP BY segmentation.

    Narrow key tuples keep the exact lexicographic encoding (group
    output order = key order, stable for existing behavior), with any
    ``tail_parts`` (contrib flags, value order) fused into the same
    limb set's spare bits.  WIDE tuples (fused encoding >
    GROUP_HASH_LIMB_CAP limbs — e.g. several string keys, the TPC-H
    q10 shape) sort by a 128-bit hash of the normalized key tuple
    instead: grouping only needs equal-keys-contiguous, a hash
    aggregate's group order is undefined in Spark anyway, and distinct
    keys merge only on a full 128-bit collision (~2^-128 — four
    murmur3 passes with independent seeds).  Boundary detection must
    use the returned KEY limbs (tail parts must not split groups).
    """
    key_parts = [_flag_part(~sel)] + batch_group_parts(cols)
    exact = fuse_parts(key_parts)
    if len(exact) <= GROUP_HASH_LIMB_CAP:
        if not tail_parts:
            return exact, exact
        return fuse_parts(key_parts + list(tail_parts)), exact
    from spark_rapids_tpu.ops import hashing as HH
    n = int(sel.shape[0])

    def tuple_hash(seed: int) -> jnp.ndarray:
        h = jnp.full((n,), np.uint32(seed), jnp.uint32)
        for c in cols:
            dt = c.dtype
            data = c.data
            valid = c.valid_mask()
            # the per-column null flag ALWAYS mixes in: hash_column
            # leaves h unchanged for null rows, so without this,
            # (null, x) and (x, null) would hash identically on every
            # seed — a systematic merge, not a 2^-128 collision
            h = HH._mix_h1(h, HH._mix_k1(valid.astype(jnp.uint32),
                                         jnp), jnp)
            if isinstance(dt, T.DoubleType):
                from spark_rapids_tpu.parallel.shuffle import (
                    _hash_f64_tpu_safe)
                h = jnp.where(valid, _hash_f64_tpu_safe(data, h), h)
                continue
            if isinstance(dt, T.FloatType):
                data = jnp.where(data == 0.0,
                                 jnp.zeros((), data.dtype), data)
            h = HH.hash_column((data, c.lengths), dt, h, valid, jnp)
        return h

    h = [tuple_hash(s).astype(jnp.uint64)
         for s in (42, 0x5F3759DF, 0x9E3779B9, 0x85EBCA6B)]
    h64a = (h[0] << jnp.uint64(32)) | h[1]
    h64b = (h[2] << jnp.uint64(32)) | h[3]
    key_limbs = fuse_parts(
        [_flag_part(~sel), (h64a, 64), (h64b, 64)])
    if not tail_parts:
        return key_limbs, key_limbs
    return key_limbs + fuse_parts(list(tail_parts)), key_limbs


def sort_by_keys(limbs: List[jnp.ndarray], payload=None
                 ) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
    """Stable lexicographic sort; returns (sorted limbs, permutation).

    The trailing iota doubles as stabilizer AND permutation output —
    sort operand count is the dominant TPU compile cost (measured ~25 s
    per u64 operand at 128k rows), so no separate payload operand.
    """
    import jax
    n = limbs[0].shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    operands = tuple(limbs) + (iota,)
    res = jax.lax.sort(operands, num_keys=len(limbs) + 1)
    return list(res[:len(limbs)]), res[-1]


# ----------------------------------------------------------------------------
# Host (numpy oracle) twin
# ----------------------------------------------------------------------------

def np_order_keys(data: np.ndarray, validity: Optional[np.ndarray],
                  dt: T.DataType, ascending: bool = True,
                  nulls_first: bool = True) -> List[np.ndarray]:
    if isinstance(dt, (T.StringType, T.BinaryType)):
        # host strings are object arrays — map to sortable tuples via bytes
        enc = np.array([
            v.encode() if isinstance(v, str) else bytes(v) for v in data
        ], dtype=object)
        mx = max((len(v) for v in enc), default=0)
        limbs = []
        padded = np.zeros((len(enc), mx + 1), dtype=np.uint8)
        for i, v in enumerate(enc):
            padded[i, :len(v)] = np.frombuffer(v, np.uint8)
        wpad = (-(mx + 1)) % 8
        padded = np.pad(padded, ((0, 0), (0, wpad)))
        for i in range(padded.shape[1] // 8):
            limb = np.zeros(len(enc), np.uint64)
            for j in range(8):
                limb = (limb << np.uint64(8)) | padded[:, i * 8 + j].astype(np.uint64)
            limbs.append(limb)
        limbs.append(np.array([len(v) for v in enc], np.uint64))
    elif isinstance(dt, T.DecimalType) and data.dtype == object:
        # decimal128 host rep: python ints — split to biased hi + lo
        hi = np.array([int(v) >> 64 for v in data], dtype=np.int64)
        lo = np.array([int(v) & 0xFFFFFFFFFFFFFFFF for v in data],
                      dtype=np.uint64)
        hi_u = (hi.astype(np.int64) ^ np.int64(-(1 << 63))).view(
            np.uint64)
        limbs = [hi_u, lo]  # the shared tail applies the desc flip
    elif isinstance(dt, T.FloatType):
        canon = np.where(np.isnan(data), np.float32(np.nan),
                         data.astype(np.float32))
        bits = canon.view(np.uint32)
        neg = (bits >> np.uint32(31)) != 0
        limbs = [np.where(neg, ~bits, bits | np.uint32(1 << 31)).astype(np.uint64)]
    elif isinstance(dt, T.DoubleType):
        canon = np.where(np.isnan(data), np.nan, data.astype(np.float64))
        bits = canon.view(np.uint64)
        neg = (bits >> np.uint64(63)) != 0
        limbs = [np.where(neg, ~bits, bits | np.uint64(1 << 63))]
    elif isinstance(dt, T.BooleanType):
        limbs = [data.astype(np.uint64)]
    else:
        limbs = [(data.astype(np.int64).view(np.uint64)) ^ np.uint64(1 << 63)]
    if not ascending:
        limbs = [~l for l in limbs]
    if validity is not None:
        nl = np.where(validity, np.uint64(1 if nulls_first else 0),
                      np.uint64(0 if nulls_first else 1))
        limbs = [np.where(validity, l, np.uint64(0)) for l in limbs]
        limbs = [nl] + limbs
    return limbs
