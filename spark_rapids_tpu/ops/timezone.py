"""Timezone database as device lookup tables.

[REF: spark-rapids-jni :: src/main/cpp/src/GpuTimeZoneDB — the reference
 loads the JVM's zone rules into device tables and does transition
 binary search per row; SURVEY §2.2 N9]

TPU redesign: each zone's TZif file (the OS tzdata, same source as the
JVM's rules) parses into two sorted arrays — transition instants (int64
seconds) and utc offsets (int32 seconds) — uploaded once per zone and
cached.  Per-row lookup is one ``searchsorted`` + gather, fully
vectorized on device.

Semantics notes (documented divergences, same caveats as the reference):
* ``to_utc_timestamp`` resolves DST gaps/overlaps by the transition
  table keyed on local wall seconds (overlap → the post-transition
  offset); Java picks the pre-transition offset in overlaps, so results
  can differ by the DST delta inside the (≤1h) overlap window.
* Instants beyond the file's last transition use the last offset (the
  TZif footer's forward rule string is not evaluated).
"""

from __future__ import annotations

import dataclasses
import os
import struct
import threading
from typing import Dict, List, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.columnar.host import HostCol
from spark_rapids_tpu.ops.expressions import Expression

_SENTINEL = -(1 << 62)


def _tz_path(name: str) -> str:
    import zoneinfo
    for base in zoneinfo.TZPATH:
        p = os.path.join(base, name)
        if os.path.exists(p):
            return p
    raise ValueError(f"unknown timezone {name!r}")


def parse_tzif(name: str) -> Tuple[np.ndarray, np.ndarray]:
    """TZif v1/v2/v3 → (transitions int64[T+1], offsets int32[T+1]).

    Entry 0 is a -inf sentinel carrying the zone's pre-history offset,
    so ``searchsorted(..., 'right') - 1`` is always a valid index."""
    with open(_tz_path(name), "rb") as f:
        raw = f.read()

    def parse_block(buf, off, time_size):
        fmt = ">i" if time_size == 4 else ">q"
        magic, version = buf[off:off + 4], buf[off + 4:off + 5]
        assert magic == b"TZif", name
        (isutcnt, isstdcnt, leapcnt, timecnt, typecnt,
         charcnt) = struct.unpack(">6I", buf[off + 20:off + 44])
        p = off + 44
        trans = np.frombuffer(
            buf, dtype=np.dtype(fmt), count=timecnt, offset=p
        ).astype(np.int64)
        p += timecnt * time_size
        idxs = np.frombuffer(buf, np.uint8, timecnt, p)
        p += timecnt
        utoffs = np.zeros(typecnt, np.int32)
        isdst = np.zeros(typecnt, np.uint8)
        for t in range(typecnt):
            utoff, dst, _ = struct.unpack(">iBB", buf[p:p + 6])
            utoffs[t] = utoff
            isdst[t] = dst
            p += 6
        p += charcnt + leapcnt * (time_size + 4) + isstdcnt + isutcnt
        return (trans, idxs, utoffs, isdst), p

    (trans, idxs, utoffs, isdst), end = parse_block(raw, 0, 4)
    if raw[4:5] in (b"2", b"3"):
        (trans, idxs, utoffs, isdst), _ = parse_block(raw, end, 8)
    # pre-history offset: first non-dst type, else type 0 (RFC 8536 §3.2)
    std = np.nonzero(isdst == 0)[0]
    first_off = int(utoffs[std[0]] if len(std) else utoffs[0]) \
        if len(utoffs) else 0
    transitions = np.concatenate(
        [np.array([_SENTINEL], np.int64), trans])
    offsets = np.concatenate(
        [np.array([first_off], np.int32),
         utoffs[idxs].astype(np.int32) if len(trans) else
         np.zeros(0, np.int32)])
    return transitions, offsets


class _TzCache:
    """Host + device LUTs per zone name (process lifetime)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._host: Dict[str, tuple] = {}
        self._dev: Dict[str, tuple] = {}

    def host(self, name: str):
        with self._lock:
            if name not in self._host:
                trans, offs = parse_tzif(name)
                # local-time keyed table for the to_utc direction
                local = trans.astype(np.int64) + offs.astype(np.int64)
                self._host[name] = (trans, offs, local)
            return self._host[name]

    def device(self, name: str):
        trans, offs, local = self.host(name)
        with self._lock:
            if name not in self._dev:
                self._dev[name] = (jnp.asarray(trans), jnp.asarray(offs),
                                   jnp.asarray(local))
            return self._dev[name]


TZ_CACHE = _TzCache()


def _floor_div_us(ts_us, xp):
    return xp.floor_divide(ts_us, 1_000_000)


@dataclasses.dataclass
class FromUTCTimestamp(Expression):
    """from_utc_timestamp(ts, tz): the UTC instant re-rendered as the
    zone's wall time [REF: GpuTimeZoneDB::convert_timestamp_to_utc
    inverse]."""

    child: Expression
    tz: str
    dtype: T.DataType = dataclasses.field(default_factory=T.TimestampType)

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        trans, offs, _ = TZ_CACHE.device(self.tz)
        c = self.child.eval_tpu(batch)
        secs = _floor_div_us(c.data.astype(jnp.int64), jnp)
        idx = jnp.searchsorted(trans, secs, side="right") - 1
        off = jnp.take(offs, idx).astype(jnp.int64)
        return DeviceColumn(self.dtype, c.data + off * 1_000_000,
                            c.validity)

    def eval_cpu(self, batch):
        trans, offs, _ = TZ_CACHE.host(self.tz)
        c = self.child.eval_cpu(batch)
        secs = _floor_div_us(c.data.astype(np.int64), np)
        idx = np.searchsorted(trans, secs, side="right") - 1
        off = offs[idx].astype(np.int64)
        return HostCol(self.dtype, c.data + off * 1_000_000, c.validity)


@dataclasses.dataclass
class ToUTCTimestamp(Expression):
    """to_utc_timestamp(ts, tz): wall time in the zone → UTC instant
    (gap/overlap caveat in the module docstring)."""

    child: Expression
    tz: str
    dtype: T.DataType = dataclasses.field(default_factory=T.TimestampType)
    incompat = ("DST overlap resolves to the post-transition offset "
                "(Java uses pre-transition)")

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        _, offs, local = TZ_CACHE.device(self.tz)
        c = self.child.eval_tpu(batch)
        secs = _floor_div_us(c.data.astype(jnp.int64), jnp)
        idx = jnp.searchsorted(local, secs, side="right") - 1
        off = jnp.take(offs, idx).astype(jnp.int64)
        return DeviceColumn(self.dtype, c.data - off * 1_000_000,
                            c.validity)

    def eval_cpu(self, batch):
        _, offs, local = TZ_CACHE.host(self.tz)
        c = self.child.eval_cpu(batch)
        secs = _floor_div_us(c.data.astype(np.int64), np)
        idx = np.searchsorted(local, secs, side="right") - 1
        off = offs[idx].astype(np.int64)
        return HostCol(self.dtype, c.data - off * 1_000_000, c.validity)
