"""Expression library — the Gpu expression analog, dual-lowered.

[REF: sql-plugin/../rapids/arithmetic.scala, predicates.scala,
 conditionalExpressions.scala, nullExpressions.scala, mathExpressions.scala,
 GpuCast.scala]

Every expression node lowers two ways:

* ``eval_tpu(DeviceBatch) -> DeviceColumn`` — pure jax, so a whole
  project/filter tree fuses into ONE jitted XLA program (the reference
  launches one cuDF kernel per expression node; XLA fusion is the TPU-first
  win here, [SURVEY.md §2.2 N7]).
* ``eval_cpu(HostBatch) -> HostCol`` — the numpy CPU-fallback path, also
  the correctness oracle in tests.

Both implement **Spark semantics**: three-valued logic, null propagation,
x/0 -> null (non-ANSI), java wrap-on-overflow for integral ops, NaN equal
to NaN and greater than everything in comparisons, ``ln(x<=0) -> null``,
``floor/ceil -> long``.  ANSI mode is not yet accelerated: the planner
tags ANSI arithmetic as CPU-only (mirrors staged ANSI support in the
reference).

Expressions here are *bound*: children are typed and column references are
positional ``BoundReference``s (name resolution happens in the plan layer,
like Spark's analyzer) [REF: GpuBoundReference].
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.column import DeviceBatch, DeviceColumn
from spark_rapids_tpu.columnar.host import HostBatch, HostCol

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def merge_validity_d(*vs: Optional[jax.Array]) -> Optional[jax.Array]:
    out = None
    for v in vs:
        if v is None:
            continue
        out = v if out is None else (out & v)
    return out


def merge_validity_h(*vs: Optional[np.ndarray]) -> Optional[np.ndarray]:
    out = None
    for v in vs:
        if v is None:
            continue
        out = v if out is None else (out & v)
    return out


def _is_float(dt: T.DataType) -> bool:
    return isinstance(dt, (T.FloatType, T.DoubleType))


# ---------------------------------------------------------------------------
# TypeSig: per-expression declared type support [REF: TypeChecks.scala ::
# TypeSig/ExprChecks].  Each expression class declares the type TAGS its
# device lowering accepts for inputs (``input_sig``) and produces
# (``type_sig``); the plan-rewrite engine checks both while tagging and
# docs_gen emits the per-type support matrix from the same declarations.
# ---------------------------------------------------------------------------

SIG_TAGS = ("boolean", "byte", "short", "int", "long", "float", "double",
            "decimal", "string", "binary", "date", "timestamp", "null",
            "array", "map", "struct")

SIG_ALL_SCALAR = frozenset(SIG_TAGS) - {"array", "map", "struct"}
SIG_NUMERIC = frozenset({"byte", "short", "int", "long", "float",
                         "double", "decimal", "null"})
SIG_INTEGRAL = frozenset({"byte", "short", "int", "long", "null"})
SIG_FLOATING = frozenset({"float", "double", "null"})
SIG_STRINGY = frozenset({"string", "binary", "null"})
SIG_BOOLEAN = frozenset({"boolean", "null"})
SIG_DATETIME = frozenset({"date", "timestamp", "null"})
SIG_ALL = frozenset(SIG_TAGS)


def sig_tag(dt: T.DataType) -> str:
    """Type tag of a dtype for TypeSig membership checks."""
    if isinstance(dt, T.DecimalType):
        return "decimal"
    if isinstance(dt, T.ArrayType):
        return "array"
    if isinstance(dt, T.MapType):
        return "map"
    if isinstance(dt, T.StructType):
        return "struct"
    return {T.BooleanType: "boolean", T.ByteType: "byte",
            T.ShortType: "short", T.IntegerType: "int",
            T.LongType: "long", T.FloatType: "float",
            T.DoubleType: "double", T.StringType: "string",
            T.BinaryType: "binary", T.DateType: "date",
            T.TimestampType: "timestamp",
            T.NullType: "null"}.get(type(dt), dt.simple_name)


class Expression:
    """Base expression.  Subclasses are dataclasses with typed children."""

    dtype: T.DataType
    # TypeSig declarations; tagging checks result dtype against
    # ``type_sig`` and every child dtype against ``input_sig`` (None =
    # same as type_sig).  Default = every scalar type; classes narrow.
    type_sig: frozenset = SIG_ALL_SCALAR
    input_sig: Optional[frozenset] = None

    @property
    def children(self) -> Sequence["Expression"]:
        return ()

    @property
    def name(self) -> str:
        return type(self).__name__

    def eval_tpu(self, batch: DeviceBatch) -> DeviceColumn:
        raise NotImplementedError(f"{self.name}.eval_tpu")

    def eval_cpu(self, batch: HostBatch) -> HostCol:
        raise NotImplementedError(f"{self.name}.eval_cpu")

    def __str__(self):
        cs = ", ".join(str(c) for c in self.children)
        return f"{self.name}({cs})"


@dataclasses.dataclass
class InputFileName(Expression):
    """Marker for input_file_name() — the optimizer rewrites it to a
    BoundReference over the scan's appended file-name column
    [REF: GpuFileSourceScanExec.scala :: InputFileName handling]."""

    dtype: T.DataType = dataclasses.field(
        default_factory=lambda: T.StringT)

    def eval_tpu(self, batch):
        raise RuntimeError(
            "input_file_name() was not bound to a file scan — it is only "
            "valid directly above a file source")

    eval_cpu = eval_tpu

    def __str__(self):
        return "input_file_name()"


@dataclasses.dataclass
class BoundReference(Expression):
    index: int
    dtype: T.DataType
    nullable: bool = True

    def eval_tpu(self, batch):
        return batch.columns[self.index]

    def eval_cpu(self, batch):
        return batch.columns[self.index]

    def __str__(self):
        return f"input[{self.index}]"


@dataclasses.dataclass
class Literal(Expression):
    value: Any
    dtype: T.DataType

    def eval_tpu(self, batch):
        b = batch.capacity
        if self.value is None:
            if isinstance(self.dtype, (T.StringType, T.BinaryType)):
                return DeviceColumn(self.dtype,
                                    jnp.zeros((b, 1), jnp.uint8),
                                    jnp.zeros((b,), jnp.bool_),
                                    jnp.zeros((b,), jnp.int32))
            from spark_rapids_tpu.ops import decimal128 as D128
            if D128.is128(self.dtype):
                return DeviceColumn(self.dtype,
                                    jnp.zeros((b, 2), jnp.int64),
                                    jnp.zeros((b,), jnp.bool_))
            npdt = (np.int32 if isinstance(self.dtype, T.NullType)
                    else T.to_numpy_dtype(self.dtype))
            data = jnp.zeros((b,), npdt)
            return DeviceColumn(self.dtype, data,
                                jnp.zeros((b,), jnp.bool_))
        if isinstance(self.dtype, T.StringType):
            bs = str(self.value).encode()
            w = max(len(bs), 1)
            mat = jnp.broadcast_to(
                jnp.asarray(np.frombuffer(bs.ljust(w, b"\0"), np.uint8)),
                (b, w))
            return DeviceColumn(self.dtype, mat, None,
                                jnp.full((b,), len(bs), jnp.int32))
        v = self.value
        if isinstance(self.dtype, T.DecimalType):
            import decimal as _d
            from spark_rapids_tpu.ops import decimal128 as D128
            v = D128.py_unscaled(_d.Decimal(str(v)), self.dtype.scale)
            if D128.is128(self.dtype):
                pair = D128.np_pack([v])
                return DeviceColumn(self.dtype, jnp.broadcast_to(
                    jnp.asarray(pair), (b, 2)))
        data = jnp.full((b,), v, T.to_numpy_dtype(self.dtype))
        return DeviceColumn(self.dtype, data)

    def eval_cpu(self, batch):
        n = batch.num_rows
        if self.value is None:
            if isinstance(self.dtype, (T.StringType, T.BinaryType)):
                return HostCol(self.dtype, np.full(n, "", object),
                               np.zeros(n, bool))
            npdt = (np.int32 if isinstance(self.dtype, T.NullType)
                    else T.to_numpy_dtype(self.dtype))
            return HostCol(self.dtype, np.zeros(n, npdt), np.zeros(n, bool))
        if isinstance(self.dtype, T.StringType):
            return HostCol(self.dtype, np.array([self.value] * n, object))
        v = self.value
        if isinstance(self.dtype, T.DecimalType):
            import decimal as _d
            from spark_rapids_tpu.ops import decimal128 as D128
            v = D128.py_unscaled(_d.Decimal(str(v)), self.dtype.scale)
            if self.dtype.precision > T.DecimalType.MAX_LONG_DIGITS:
                out = np.empty(n, dtype=object)
                out[:] = v
                return HostCol(self.dtype, out)
        return HostCol(self.dtype, np.full(n, v, T.to_numpy_dtype(self.dtype)))

    def __str__(self):
        return repr(self.value)


@dataclasses.dataclass
class Alias(Expression):
    child: Expression
    alias_name: str

    @property
    def dtype(self):
        return self.child.dtype

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        return self.child.eval_tpu(batch)

    def eval_cpu(self, batch):
        return self.child.eval_cpu(batch)

    def __str__(self):
        return f"{self.child} AS {self.alias_name}"


# ---------------------------------------------------------------------------
# arithmetic  [REF: arithmetic.scala :: GpuAdd, GpuSubtract, ...]
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _BinaryArith(Expression):
    # device lowering implements NON-ANSI Spark semantics (overflow
    # wraps, invalid ops null); under spark.sql.ansi.enabled the planner
    # keeps these on CPU [REF: GpuOverrides ANSI checks]
    ansi_sensitive = True
    left: Expression
    right: Expression
    # decimal arithmetic result type (precision/scale bookkeeping lives
    # in the analyzer); None = operand type passes through
    forced_dtype: Optional[T.DataType] = None

    @property
    def dtype(self):
        return self.forced_dtype or self.left.dtype

    @property
    def children(self):
        return (self.left, self.right)

    def _op_d(self, a, b):
        raise NotImplementedError

    def _op_h(self, a, b):
        raise NotImplementedError

    # decimal128 lowering: ops/decimal128 int32-limb kernels (values
    # wrap mod 2^128, the non-ANSI container behavior) [REF:
    # spark-rapids-jni decimal128 kernels]
    _d128_op = None

    def eval_tpu(self, batch):
        from spark_rapids_tpu.ops import decimal128 as D128
        l = self.left.eval_tpu(batch)
        r = self.right.eval_tpu(batch)
        if D128.is128(self.dtype):
            op = type(self)._d128_op
            if op is None:
                raise NotImplementedError(
                    f"decimal128 {type(self).__name__}")

            def to128(c):
                return (c.data if D128.is128(c.dtype)
                        else D128.from_i64(c.data))

            data, ok = op(to128(l), to128(r))
            validity = merge_validity_d(l.validity, r.validity)
            # Spark non-ANSI: overflow beyond the result precision (or
            # the 128-bit container) nulls the row
            fits = ok & D128.fits_precision(data, self.dtype.precision)
            validity = fits if validity is None else validity & fits
            return DeviceColumn(self.dtype, data, validity)
        data = self._op_d(l.data, r.data)
        return DeviceColumn(self.dtype, data,
                            merge_validity_d(l.validity, r.validity))

    def eval_cpu(self, batch):
        from spark_rapids_tpu.ops import decimal128 as D128
        l = self.left.eval_cpu(batch)
        r = self.right.eval_cpu(batch)
        if D128.is128(self.dtype):
            la = np.array([int(v) for v in l.data], dtype=object)
            ra = np.array([int(v) for v in r.data], dtype=object)
            data = self._op_h(la, ra)
            # exact python-int result; overflow beyond the declared
            # precision nulls the row (values stored 0 to stay in the
            # arrow container)
            fits = np.array([D128.py_fits(v, self.dtype.precision)
                             for v in data], dtype=bool)
            out = np.empty(len(data), dtype=object)
            for i, v in enumerate(data):
                out[i] = int(v) if fits[i] else 0
            validity = merge_validity_h(l.validity, r.validity)
            validity = fits if validity is None else validity & fits
            return HostCol(self.dtype, out, validity)
        with np.errstate(all="ignore"):
            data = self._op_h(l.data, r.data)
        return HostCol(self.dtype, data,
                       merge_validity_h(l.validity, r.validity))


class Add(_BinaryArith):
    from spark_rapids_tpu.ops import decimal128 as _D
    _d128_op = staticmethod(_D.add_checked)

    def _op_d(self, a, b):
        return a + b

    def _op_h(self, a, b):
        return a + b


class Subtract(_BinaryArith):
    from spark_rapids_tpu.ops import decimal128 as _D
    _d128_op = staticmethod(_D.sub_checked)

    def _op_d(self, a, b):
        return a - b

    def _op_h(self, a, b):
        return a - b


class Multiply(_BinaryArith):
    from spark_rapids_tpu.ops import decimal128 as _D
    _d128_op = staticmethod(_D.mul_checked)

    def _op_d(self, a, b):
        return a * b

    def _op_h(self, a, b):
        return a * b


@dataclasses.dataclass
class Divide(Expression):
    """Double (or decimal) division; x/0 -> null (non-ANSI Spark)."""

    ansi_sensitive = True

    left: Expression
    right: Expression

    @property
    def dtype(self):
        return self.left.dtype  # planner coerces both sides to double

    @property
    def children(self):
        return (self.left, self.right)

    def eval_tpu(self, batch):
        l = self.left.eval_tpu(batch)
        r = self.right.eval_tpu(batch)
        zero = r.data == 0.0
        data = l.data / jnp.where(zero, 1.0, r.data)
        validity = merge_validity_d(l.validity, r.validity, ~zero)
        return DeviceColumn(self.dtype, jnp.where(zero, 0.0, data), validity)

    def eval_cpu(self, batch):
        l = self.left.eval_cpu(batch)
        r = self.right.eval_cpu(batch)
        zero = r.data == 0.0
        with np.errstate(all="ignore"):
            data = np.where(zero, 0.0, l.data / np.where(zero, 1.0, r.data))
        return HostCol(self.dtype, data,
                       merge_validity_h(l.validity, r.validity, ~zero))


@dataclasses.dataclass
class IntegralDivide(Expression):
    """``div``: long division truncating toward zero; x div 0 -> null."""

    ansi_sensitive = True

    left: Expression
    right: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.LongType)

    @property
    def children(self):
        return (self.left, self.right)

    def eval_tpu(self, batch):
        l = self.left.eval_tpu(batch)
        r = self.right.eval_tpu(batch)
        zero = r.data == 0
        den = jnp.where(zero, 1, r.data)
        data = lax.div(l.data.astype(jnp.int64), den.astype(jnp.int64))
        return DeviceColumn(self.dtype, jnp.where(zero, 0, data),
                            merge_validity_d(l.validity, r.validity, ~zero))

    def eval_cpu(self, batch):
        l = self.left.eval_cpu(batch)
        r = self.right.eval_cpu(batch)
        zero = r.data == 0
        den = np.where(zero, 1, r.data).astype(np.int64)
        num = l.data.astype(np.int64)
        with np.errstate(all="ignore"):
            q = np.abs(num) // np.abs(den)
            data = np.where((num < 0) != (den < 0), -q, q)
        return HostCol(self.dtype, np.where(zero, 0, data),
                       merge_validity_h(l.validity, r.validity, ~zero))


@dataclasses.dataclass
class Remainder(Expression):
    """``%``: sign follows dividend (java); x % 0 -> null."""

    ansi_sensitive = True

    left: Expression
    right: Expression

    @property
    def dtype(self):
        return self.left.dtype

    @property
    def children(self):
        return (self.left, self.right)

    def eval_tpu(self, batch):
        l = self.left.eval_tpu(batch)
        r = self.right.eval_tpu(batch)
        if _is_float(self.dtype):
            data = lax.rem(l.data, r.data)
            return DeviceColumn(self.dtype, data,
                                merge_validity_d(l.validity, r.validity))
        zero = r.data == 0
        den = jnp.where(zero, 1, r.data)
        data = lax.rem(l.data, den)
        return DeviceColumn(self.dtype, jnp.where(zero, 0, data),
                            merge_validity_d(l.validity, r.validity, ~zero))

    def eval_cpu(self, batch):
        l = self.left.eval_cpu(batch)
        r = self.right.eval_cpu(batch)
        with np.errstate(all="ignore"):
            if _is_float(self.dtype):
                return HostCol(self.dtype, np.fmod(l.data, r.data),
                               merge_validity_h(l.validity, r.validity))
            zero = r.data == 0
            den = np.where(zero, 1, r.data)
            data = np.fmod(l.data, den)
        return HostCol(self.dtype, np.where(zero, 0, data),
                       merge_validity_h(l.validity, r.validity, ~zero))


@dataclasses.dataclass
class UnaryMinus(Expression):
    ansi_sensitive = True
    child: Expression

    @property
    def dtype(self):
        return self.child.dtype

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        return DeviceColumn(self.dtype, -c.data, c.validity)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        with np.errstate(all="ignore"):
            return HostCol(self.dtype, -c.data, c.validity)


@dataclasses.dataclass
class Abs(Expression):
    ansi_sensitive = True
    child: Expression

    @property
    def dtype(self):
        return self.child.dtype

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        return DeviceColumn(self.dtype, jnp.abs(c.data), c.validity)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        with np.errstate(all="ignore"):
            return HostCol(self.dtype, np.abs(c.data), c.validity)


# ---------------------------------------------------------------------------
# comparisons  [REF: predicates.scala] — Spark NaN semantics: NaN == NaN,
# NaN greater than every other value.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _BinaryComparison(Expression):
    left: Expression
    right: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.BooleanType)

    @property
    def children(self):
        return (self.left, self.right)

    def _cmp(self, a, b, an, bn, xp):
        raise NotImplementedError

    # decimal128 comparisons in terms of the limb-pair primitives
    # (device) or exact python ints (host)
    _D128_CMPS = {
        "EqualTo": lambda lt, eq, a, b: eq(a, b),
        "EqualNullSafe": lambda lt, eq, a, b: eq(a, b),
        "LessThan": lambda lt, eq, a, b: lt(a, b),
        "LessThanOrEqual": lambda lt, eq, a, b: ~lt(b, a),
        "GreaterThan": lambda lt, eq, a, b: lt(b, a),
        "GreaterThanOrEqual": lambda lt, eq, a, b: ~lt(a, b),
    }

    def _eval(self, l, r, xp, validity):
        if isinstance(self.left.dtype, T.StringType):
            raise NotImplementedError("string comparison handled in strings.py")
        from spark_rapids_tpu.ops import decimal128 as D128
        if D128.is128(self.left.dtype) or D128.is128(self.right.dtype):
            f = self._D128_CMPS[type(self).__name__]
            if xp is np:
                la = np.array([int(v) for v in l], dtype=object)
                ra = np.array([int(v) for v in r], dtype=object)
                return f(lambda a, b: a < b, lambda a, b: a == b,
                         la, ra).astype(bool)

            def to128(c, dt):
                return c if D128.is128(dt) else D128.from_i64(c)

            return f(D128.cmp_lt, D128.cmp_eq,
                     to128(l, self.left.dtype),
                     to128(r, self.right.dtype))
        if _is_float(self.left.dtype):
            an, bn = xp.isnan(l), xp.isnan(r)
        else:
            zeros = xp.zeros(l.shape if hasattr(l, "shape") else len(l), bool)
            an = bn = zeros
        return self._cmp(l, r, an, bn, xp)

    def eval_tpu(self, batch):
        l = self.left.eval_tpu(batch)
        r = self.right.eval_tpu(batch)
        data = self._eval(l.data, r.data, jnp, None)
        return DeviceColumn(self.dtype, data,
                            merge_validity_d(l.validity, r.validity))

    def eval_cpu(self, batch):
        l = self.left.eval_cpu(batch)
        r = self.right.eval_cpu(batch)
        with np.errstate(all="ignore"):
            data = self._eval(l.data, r.data, np, None)
        return HostCol(self.dtype, data,
                       merge_validity_h(l.validity, r.validity))


class EqualTo(_BinaryComparison):
    def _cmp(self, a, b, an, bn, xp):
        return xp.where(an & bn, True, a == b)


class LessThan(_BinaryComparison):
    # NaN is greater than everything: a < b is True when b is NaN and a isn't
    def _cmp(self, a, b, an, bn, xp):
        return xp.where(bn & ~an, True, xp.where(an, False, a < b))


class LessThanOrEqual(_BinaryComparison):
    def _cmp(self, a, b, an, bn, xp):
        return xp.where(bn, True, xp.where(an, False, a <= b))


class GreaterThan(_BinaryComparison):
    def _cmp(self, a, b, an, bn, xp):
        return xp.where(an & ~bn, True, xp.where(bn, False, a > b))


class GreaterThanOrEqual(_BinaryComparison):
    def _cmp(self, a, b, an, bn, xp):
        return xp.where(an, True, xp.where(bn, False, a >= b))


@dataclasses.dataclass
class Not(Expression):
    child: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.BooleanType)

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        return DeviceColumn(self.dtype, ~c.data, c.validity)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        return HostCol(self.dtype, ~c.data.astype(bool), c.validity)


@dataclasses.dataclass
class EqualNullSafe(Expression):
    """``<=>``: never null; null <=> null is true."""

    left: Expression
    right: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.BooleanType)

    @property
    def children(self):
        return (self.left, self.right)

    def eval_tpu(self, batch):
        l = self.left.eval_tpu(batch)
        r = self.right.eval_tpu(batch)
        lv = l.valid_mask()
        rv = r.valid_mask()
        if _is_float(self.left.dtype):
            eq = jnp.where(jnp.isnan(l.data) & jnp.isnan(r.data), True,
                           l.data == r.data)
        else:
            eq = l.data == r.data
        data = jnp.where(lv & rv, eq, ~lv & ~rv)
        return DeviceColumn(self.dtype, data, None)

    def eval_cpu(self, batch):
        l = self.left.eval_cpu(batch)
        r = self.right.eval_cpu(batch)
        lv = l.valid_mask()
        rv = r.valid_mask()
        with np.errstate(all="ignore"):
            if _is_float(self.left.dtype):
                eq = np.where(np.isnan(l.data) & np.isnan(r.data), True,
                              l.data == r.data)
            else:
                eq = l.data == r.data
        return HostCol(self.dtype, np.where(lv & rv, eq, ~lv & ~rv), None)


# ---------------------------------------------------------------------------
# three-valued logic  [REF: predicates.scala :: GpuAnd, GpuOr]
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class And(Expression):
    left: Expression
    right: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.BooleanType)

    @property
    def children(self):
        return (self.left, self.right)

    def eval_tpu(self, batch):
        l = self.left.eval_tpu(batch)
        r = self.right.eval_tpu(batch)
        lv, rv = l.valid_mask(), r.valid_mask()
        data = l.data & r.data
        # null unless: both valid, or either side is a valid False
        validity = (lv & rv) | (lv & ~l.data) | (rv & ~r.data)
        return DeviceColumn(self.dtype, data & validity, validity)

    def eval_cpu(self, batch):
        l = self.left.eval_cpu(batch)
        r = self.right.eval_cpu(batch)
        lv, rv = l.valid_mask(), r.valid_mask()
        ld, rd = l.data.astype(bool), r.data.astype(bool)
        validity = (lv & rv) | (lv & ~ld) | (rv & ~rd)
        return HostCol(self.dtype, ld & rd & validity, validity)


@dataclasses.dataclass
class Or(Expression):
    left: Expression
    right: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.BooleanType)

    @property
    def children(self):
        return (self.left, self.right)

    def eval_tpu(self, batch):
        l = self.left.eval_tpu(batch)
        r = self.right.eval_tpu(batch)
        lv, rv = l.valid_mask(), r.valid_mask()
        data = (l.data & lv) | (r.data & rv)
        validity = (lv & rv) | (lv & l.data) | (rv & r.data)
        return DeviceColumn(self.dtype, data, validity)

    def eval_cpu(self, batch):
        l = self.left.eval_cpu(batch)
        r = self.right.eval_cpu(batch)
        lv, rv = l.valid_mask(), r.valid_mask()
        ld, rd = l.data.astype(bool), r.data.astype(bool)
        validity = (lv & rv) | (lv & ld) | (rv & rd)
        return HostCol(self.dtype, (ld & lv) | (rd & rv), validity)


# ---------------------------------------------------------------------------
# null handling  [REF: nullExpressions.scala]
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class IsNull(Expression):
    child: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.BooleanType)

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        return DeviceColumn(self.dtype, ~c.valid_mask(), None)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        return HostCol(self.dtype, ~c.valid_mask(), None)


@dataclasses.dataclass
class IsNotNull(Expression):
    child: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.BooleanType)

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        return DeviceColumn(self.dtype, c.valid_mask(), None)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        return HostCol(self.dtype, c.valid_mask(), None)


@dataclasses.dataclass
class IsNaN(Expression):
    child: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.BooleanType)

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        return DeviceColumn(self.dtype,
                            jnp.isnan(c.data) & c.valid_mask(), None)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        return HostCol(self.dtype, np.isnan(c.data) & c.valid_mask(), None)



def device_select(cond1d, a: "DeviceColumn", b: "DeviceColumn",
                  dtype) -> "DeviceColumn":
    """Row-wise select between two device columns (string-aware).

    cond1d: bool[B]; takes a where True else b.  Validity NOT handled here
    (callers own null semantics).  For strings, pads byte matrices to the
    common width so shapes align.
    """
    if a.lengths is not None or b.lengths is not None:
        wa = a.data.shape[1]
        wb = b.data.shape[1]
        w = max(wa, wb)
        da = jnp.pad(a.data, ((0, 0), (0, w - wa))) if wa < w else a.data
        db = jnp.pad(b.data, ((0, 0), (0, w - wb))) if wb < w else b.data
        data = jnp.where(cond1d[:, None], da, db)
        lengths = jnp.where(cond1d, a.lengths, b.lengths)
        return DeviceColumn(dtype, data, None, lengths)
    if a.data.ndim == 2:  # decimal128 (hi, lo) lanes
        return DeviceColumn(
            dtype, jnp.where(cond1d[:, None], a.data, b.data), None)
    return DeviceColumn(dtype, jnp.where(cond1d, a.data, b.data), None)


@dataclasses.dataclass
class Coalesce(Expression):
    exprs: List[Expression]

    @property
    def dtype(self):
        return self.exprs[0].dtype

    @property
    def children(self):
        return tuple(self.exprs)

    def eval_tpu(self, batch):
        cols = [e.eval_tpu(batch) for e in self.exprs]
        acc = cols[-1]
        validity = cols[-1].valid_mask()
        for c in reversed(cols[:-1]):
            cv = c.valid_mask()
            acc = device_select(cv, c, acc, self.dtype)
            validity = cv | validity
        return DeviceColumn(self.dtype, acc.data, validity, acc.lengths)

    def eval_cpu(self, batch):
        cols = [e.eval_cpu(batch) for e in self.exprs]
        data = cols[-1].data.copy()
        validity = cols[-1].valid_mask().copy()
        for c in reversed(cols[:-1]):
            cv = c.valid_mask()
            data = np.where(cv, c.data, data)
            validity = cv | validity
        return HostCol(self.dtype, data, validity)


# ---------------------------------------------------------------------------
# conditionals  [REF: conditionalExpressions.scala :: GpuIf, GpuCaseWhen]
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class If(Expression):
    pred: Expression
    true_value: Expression
    false_value: Expression

    @property
    def dtype(self):
        return self.true_value.dtype

    @property
    def children(self):
        return (self.pred, self.true_value, self.false_value)

    def eval_tpu(self, batch):
        p = self.pred.eval_tpu(batch)
        t = self.true_value.eval_tpu(batch)
        f = self.false_value.eval_tpu(batch)
        cond = p.data & p.valid_mask()  # null predicate -> false branch
        data = jnp.where(cond, t.data, f.data)
        validity = jnp.where(cond, t.valid_mask(), f.valid_mask())
        return DeviceColumn(self.dtype, data, validity)

    def eval_cpu(self, batch):
        p = self.pred.eval_cpu(batch)
        t = self.true_value.eval_cpu(batch)
        f = self.false_value.eval_cpu(batch)
        cond = p.data.astype(bool) & p.valid_mask()
        data = np.where(cond, t.data, f.data)
        validity = np.where(cond, t.valid_mask(), f.valid_mask())
        return HostCol(self.dtype, data, validity)


@dataclasses.dataclass
class CaseWhen(Expression):
    branches: List[Tuple[Expression, Expression]]
    else_value: Optional[Expression] = None

    @property
    def dtype(self):
        return self.branches[0][1].dtype

    @property
    def children(self):
        cs = []
        for p, v in self.branches:
            cs += [p, v]
        if self.else_value is not None:
            cs.append(self.else_value)
        return tuple(cs)

    def eval_tpu(self, batch):
        if self.else_value is not None:
            acc = self.else_value.eval_tpu(batch)
            validity = acc.valid_mask()
        else:
            first = self.branches[0][1].eval_tpu(batch)
            acc = DeviceColumn(
                self.dtype, jnp.zeros_like(first.data), None,
                None if first.lengths is None
                else jnp.zeros_like(first.lengths))
            validity = jnp.zeros((batch.capacity,), jnp.bool_)
        for pred, val in reversed(self.branches):
            p = pred.eval_tpu(batch)
            v = val.eval_tpu(batch)
            cond = p.data & p.valid_mask()
            acc = device_select(cond, v, acc, self.dtype)
            validity = jnp.where(cond, v.valid_mask(), validity)
        return DeviceColumn(self.dtype, acc.data, validity, acc.lengths)

    def eval_cpu(self, batch):
        n = batch.num_rows
        if self.else_value is not None:
            e = self.else_value.eval_cpu(batch)
            data, validity = e.data.copy(), e.valid_mask().copy()
        else:
            first = self.branches[0][1].eval_cpu(batch)
            data = np.zeros_like(first.data)
            validity = np.zeros(n, bool)
        for pred, val in reversed(self.branches):
            p = pred.eval_cpu(batch)
            v = val.eval_cpu(batch)
            cond = p.data.astype(bool) & p.valid_mask()
            data = np.where(cond, v.data, data)
            validity = np.where(cond, v.valid_mask(), validity)
        return HostCol(self.dtype, data, validity)


# ---------------------------------------------------------------------------
# math  [REF: mathExpressions.scala]
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _UnaryMath(Expression):
    child: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.DoubleType)

    @property
    def children(self):
        return (self.child,)

    def _op_d(self, a):
        raise NotImplementedError

    def _op_h(self, a):
        raise NotImplementedError

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        return DeviceColumn(self.dtype, self._op_d(c.data), c.validity)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        with np.errstate(all="ignore"):
            return HostCol(self.dtype, self._op_h(c.data), c.validity)


class Sqrt(_UnaryMath):
    def _op_d(self, a):
        return jnp.sqrt(a)

    def _op_h(self, a):
        return np.sqrt(a)


class Exp(_UnaryMath):
    def _op_d(self, a):
        return jnp.exp(a)

    def _op_h(self, a):
        return np.exp(a)


@dataclasses.dataclass
class Log(Expression):
    """Spark ``ln``: null for x <= 0."""

    child: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.DoubleType)

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        bad = c.data <= 0.0
        data = jnp.log(jnp.where(bad, 1.0, c.data))
        return DeviceColumn(self.dtype, data,
                            merge_validity_d(c.validity, ~bad))

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        bad = c.data <= 0.0
        with np.errstate(all="ignore"):
            data = np.log(np.where(bad, 1.0, c.data))
        return HostCol(self.dtype, data, merge_validity_h(c.validity, ~bad))


@dataclasses.dataclass
class Pow(Expression):
    left: Expression
    right: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.DoubleType)

    @property
    def children(self):
        return (self.left, self.right)

    def eval_tpu(self, batch):
        l = self.left.eval_tpu(batch)
        r = self.right.eval_tpu(batch)
        return DeviceColumn(self.dtype, jnp.power(l.data, r.data),
                            merge_validity_d(l.validity, r.validity))

    def eval_cpu(self, batch):
        l = self.left.eval_cpu(batch)
        r = self.right.eval_cpu(batch)
        with np.errstate(all="ignore"):
            return HostCol(self.dtype, np.power(l.data, r.data),
                           merge_validity_h(l.validity, r.validity))


@dataclasses.dataclass
class Floor(Expression):
    """Spark floor(double) -> long."""

    child: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.LongType)

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        return DeviceColumn(self.dtype,
                            jnp.floor(c.data).astype(jnp.int64), c.validity)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        with np.errstate(all="ignore"):
            return HostCol(self.dtype, np.floor(c.data).astype(np.int64),
                           c.validity)


@dataclasses.dataclass
class Ceil(Expression):
    child: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.LongType)

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        return DeviceColumn(self.dtype,
                            jnp.ceil(c.data).astype(jnp.int64), c.validity)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        with np.errstate(all="ignore"):
            return HostCol(self.dtype, np.ceil(c.data).astype(np.int64),
                           c.validity)


@dataclasses.dataclass
class Round(Expression):
    """Spark ``round``: HALF_UP at the given scale (numpy rounds HALF_EVEN,
    so both paths implement HALF_UP by hand)."""

    child: Expression
    scale: int = 0

    @property
    def dtype(self):
        return self.child.dtype

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        if not _is_float(self.dtype):
            return c
        m = 10.0 ** self.scale
        data = jnp.sign(c.data) * jnp.floor(jnp.abs(c.data) * m + 0.5) / m
        data = jnp.where(jnp.isfinite(c.data), data, c.data)
        return DeviceColumn(self.dtype, data, c.validity)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        if not _is_float(self.dtype):
            return c
        m = 10.0 ** self.scale
        with np.errstate(all="ignore"):
            data = np.sign(c.data) * np.floor(np.abs(c.data) * m + 0.5) / m
            data = np.where(np.isfinite(c.data), data, c.data)
        return HostCol(self.dtype, data, c.validity)


# ---------------------------------------------------------------------------
# cast  [REF: GpuCast.scala]
# ---------------------------------------------------------------------------

_INT_RANGES = {
    T.ByteType: (-128, 127),
    T.ShortType: (-(1 << 15), (1 << 15) - 1),
    T.IntegerType: (-(1 << 31), (1 << 31) - 1),
    T.LongType: (-(1 << 63), (1 << 63) - 1),
}


@dataclasses.dataclass
class Cast(Expression):
    ansi_sensitive = True
    child: Expression
    dtype: T.DataType

    @property
    def children(self):
        return (self.child,)

    def _cast(self, data, xp):
        src = self.child.dtype
        dst = self.dtype
        npdt = T.to_numpy_dtype(dst)
        if isinstance(dst, T.BooleanType):
            return data != 0
        if isinstance(src, T.BooleanType):
            return data.astype(npdt)
        if _is_float(src) and T.is_integral(dst):
            # java (T) cast: NaN -> 0, saturate at bounds, truncate toward 0
            lo, hi = _INT_RANGES[type(dst)]
            d = xp.where(xp.isnan(data), 0.0, data)
            d = xp.clip(d, lo, hi)
            return xp.trunc(d).astype(npdt)
        if T.is_integral(src) and T.is_integral(dst):
            return data.astype(npdt)  # java narrowing wraps
        return data.astype(npdt)

    def device_support_reason(self, conf):
        """Per-combination device support (tagging hook).  None = ok."""
        from spark_rapids_tpu import conf as C
        src, dst = self.child.dtype, self.dtype
        if self._decimal_combo() is not None:
            if isinstance(dst, T.DecimalType):
                down = (src.scale - dst.scale
                        if isinstance(src, T.DecimalType) else -1)
                if down > 9:
                    return ("decimal scale-down beyond 10^9 not on "
                            "device (single-step rounded division cap)")
                if (isinstance(src, T.DecimalType)
                        or T.is_integral(src)):
                    return None
                return (f"cast {src.simple_name}→{dst.simple_name} "
                        "not yet on device")
            if isinstance(dst, T.DoubleType):
                return None
            return (f"cast {src.simple_name}→{dst.simple_name} not "
                    "yet on device")
        src_s = isinstance(src, T.StringType)
        dst_s = isinstance(dst, T.StringType)
        if not (src_s or dst_s):
            return None
        if src_s and (T.is_integral(dst) or isinstance(dst, T.BooleanType)):
            return None
        if src_s and isinstance(dst, (T.FloatType, T.DoubleType)):
            if conf.get(C.CAST_STRING_TO_FLOAT):
                return None
            return ("cast string→floating can differ from Java by 1 ulp "
                    "beyond 15 significant digits; set spark.rapids.sql."
                    "castStringToFloat.enabled=true to run on device")
        if dst_s and (T.is_integral(src) or isinstance(src, T.BooleanType)):
            return None
        if dst_s and isinstance(src, (T.FloatType, T.DoubleType)):
            return ("cast floating→string not on device (Java "
                    "shortest-round-trip formatting)")
        return (f"cast {src.simple_name}→{dst.simple_name} not yet on "
                "device")

    def _decimal_combo(self):
        """Non-string decimal cast combo, else None (string<->decimal
        dispatches through the string paths)."""
        src, dst = self.child.dtype, self.dtype
        if isinstance(src, T.StringType) or isinstance(dst, T.StringType):
            return None
        if not (isinstance(src, T.DecimalType)
                or isinstance(dst, T.DecimalType)):
            return None
        return (src, dst)

    def _cast_decimal_tpu(self, c):
        from spark_rapids_tpu.ops import decimal128 as D128
        src, dst = self.child.dtype, self.dtype
        if isinstance(dst, T.DecimalType):
            # EVERY cast to decimal runs through the 128-bit container:
            # the rescale cannot wrap int64, and the overflow-to-null
            # check applies uniformly (Spark non-ANSI)
            big_dst = D128.is128(dst)
            if isinstance(src, T.DecimalType):
                k = dst.scale - src.scale
                d = (c.data if D128.is128(src)
                     else D128.from_i64(c.data))
                if k >= 0:
                    # checked: overflow decided BEFORE the multiply so a
                    # wrap mod 2^128 can't return a plausible wrong value
                    d, fits = D128.scale_up_checked(d, k, dst.precision)
                else:
                    d = D128.scale_down_round(d, -k)
                    fits = D128.fits_precision(d, dst.precision)
            elif T.is_integral(src):
                d, fits = D128.scale_up_checked(
                    D128.from_i64(c.data.astype(jnp.int64)),
                    dst.scale, dst.precision)
            else:
                raise NotImplementedError(f"cast {src}→{dst} on device")
            validity = (fits if c.validity is None
                        else c.validity & fits)
            if not big_dst:
                d = D128.lo(d)
            return DeviceColumn(dst, d, validity)
        # src is decimal
        if isinstance(dst, T.DoubleType):
            from spark_rapids_tpu.ops import decimal128 as D128
            if D128.is128(src):
                return DeviceColumn(
                    dst, D128.to_double(c.data, src.scale), c.validity)
            return DeviceColumn(
                dst, c.data.astype(jnp.float64)
                / jnp.float64(10.0 ** src.scale), c.validity)
        raise NotImplementedError(f"cast {src}→{dst} on device")

    def _cast_decimal_cpu(self, c):
        from spark_rapids_tpu.ops.decimal128 import py_rescale_half_up
        src, dst = self.child.dtype, self.dtype
        n = len(c.data)
        if isinstance(dst, T.DecimalType):
            k = (dst.scale - src.scale
                 if isinstance(src, T.DecimalType) else dst.scale)
            out = np.empty(n, dtype=object)
            for i in range(n):
                out[i] = py_rescale_half_up(int(c.data[i]), k)
            bound = 10 ** dst.precision
            fits = np.array([abs(int(v)) < bound for v in out], bool)
            validity = (fits if c.validity is None
                        else c.validity & fits)
            if dst.precision <= T.DecimalType.MAX_LONG_DIGITS:
                # overflowed rows are already null — zero their payload
                # so the int64 narrowing can't raise
                out = np.array([int(v) if f else 0
                                for v, f in zip(out, fits)],
                               dtype=np.int64)
            return HostCol(dst, out, validity)
        if isinstance(dst, T.DoubleType):
            out = np.array([int(v) / (10.0 ** src.scale)
                            for v in c.data], dtype=np.float64)
            return HostCol(dst, out, c.validity)
        raise NotImplementedError(f"cast {src}→{dst} on cpu")

    def eval_tpu(self, batch):
        from spark_rapids_tpu.ops import strings as S
        c = self.child.eval_tpu(batch)
        src, dst = self.child.dtype, self.dtype
        if self._decimal_combo() is not None:
            return self._cast_decimal_tpu(c)
        if isinstance(dst, T.StringType):
            if isinstance(src, T.BooleanType):
                return S.cast_bool_to_string_device(c)
            if T.is_integral(src):
                return S.cast_int_to_string_device(c)
            raise NotImplementedError(f"cast {src}→string on device")
        if isinstance(src, T.StringType):
            if isinstance(dst, T.BooleanType):
                return S.cast_string_to_bool_device(c)
            if T.is_integral(dst):
                return S.cast_string_to_int_device(c, dst)
            if isinstance(dst, (T.FloatType, T.DoubleType)):
                return S.cast_string_to_float_device(c, dst)
            raise NotImplementedError(f"cast string→{dst} on device")
        return DeviceColumn(self.dtype, self._cast(c.data, jnp), c.validity)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        src, dst = self.child.dtype, self.dtype
        if self._decimal_combo() is not None:
            return self._cast_decimal_cpu(c)
        if isinstance(src, T.StringType) or isinstance(dst, T.StringType):
            return self._cast_string_cpu(c)
        with np.errstate(all="ignore"):
            return HostCol(self.dtype, self._cast(c.data, np), c.validity)

    def _cast_string_cpu(self, c: HostCol) -> HostCol:
        src, dst = self.child.dtype, self.dtype
        n = len(c.data)
        if isinstance(dst, T.StringType):
            out = np.empty(n, object)
            for i in range(n):
                v = c.data[i]
                if isinstance(src, T.BooleanType):
                    out[i] = "true" if v else "false"
                elif isinstance(src, (T.FloatType, T.DoubleType)):
                    out[i] = repr(float(v))
                elif isinstance(src, T.DecimalType):
                    u = int(v)
                    sc = src.scale
                    sign = "-" if u < 0 else ""
                    m = str(abs(u))
                    if sc == 0:
                        out[i] = sign + m
                    else:
                        m = m.rjust(sc + 1, "0")
                        out[i] = sign + m[:-sc] + "." + m[-sc:]
                else:
                    out[i] = str(v)
            return HostCol(dst, out, c.validity)
        if isinstance(dst, T.DecimalType):
            # string -> decimal: parse exactly, HALF_UP to the target
            # scale, overflow/invalid -> null (non-ANSI)
            import decimal as _d
            from spark_rapids_tpu.ops import decimal128 as D128
            big = dst.precision > T.DecimalType.MAX_LONG_DIGITS
            out = (np.empty(n, object) if big
                   else np.zeros(n, np.int64))
            validity = c.valid_mask().copy()
            for i in range(n):
                if not validity[i]:
                    if big:
                        out[i] = 0
                    continue
                try:
                    dec = _d.Decimal(str(c.data[i]).strip())
                    if not dec.is_finite():
                        raise _d.InvalidOperation
                except _d.InvalidOperation:
                    validity[i] = False
                    if big:
                        out[i] = 0
                    continue
                u = D128.py_unscaled(dec, dst.scale)
                if not D128.py_fits(u, dst.precision):
                    validity[i] = False
                    u = 0
                out[i] = u
            return HostCol(dst, out, validity)
        # string -> numeric: invalid -> null (non-ANSI).  Integral casts
        # accept decimal strings truncated toward zero ('3.7' -> 3) and
        # null out-of-range values, matching Spark (and the device
        # kernels in ops/strings.py).
        import re as _re
        data = np.zeros(n, T.to_numpy_dtype(dst))
        validity = c.valid_mask().copy()
        int_pat = _re.compile(r"^([+-]?)(\d*)(?:\.(\d*))?$")
        lo_hi = _INT_RANGES.get(type(dst))
        if isinstance(dst, T.BooleanType):
            for i in range(n):
                if not validity[i]:
                    continue
                s = str(c.data[i]).strip().lower()
                if s in ("true", "t", "yes", "y", "1"):
                    data[i] = True
                elif s in ("false", "f", "no", "n", "0"):
                    data[i] = False
                else:
                    validity[i] = False
            return HostCol(dst, data, validity)
        for i in range(n):
            if not validity[i]:
                continue
            s = str(c.data[i]).strip()
            try:
                if T.is_integral(dst):
                    m = int_pat.match(s)
                    if (not m or not (m.group(2) or m.group(3))):
                        validity[i] = False
                        continue
                    v = int(m.group(2) or "0")
                    if m.group(1) == "-":
                        v = -v
                    if not (lo_hi[0] <= v <= lo_hi[1]):
                        validity[i] = False
                        continue
                    data[i] = v
                else:
                    if "_" in s:  # Python float() accepts these; Java no
                        validity[i] = False
                        continue
                    data[i] = float(s)
            except (ValueError, OverflowError):
                validity[i] = False
        return HostCol(dst, data, validity)

    def __str__(self):
        return f"cast({self.child} as {self.dtype.simple_name})"


# ---------------------------------------------------------------------------
# TypeSig declarations [REF: TypeChecks.scala — per-op type signatures].
# ``input_sig`` applies to every child uniformly (a per-parameter split
# like the reference's ExprChecks is future work), so mixed-arity
# expressions declare the union of their parameter sigs.
# ---------------------------------------------------------------------------

for _cls in (Add, Subtract, Multiply, Divide, IntegralDivide, Remainder,
             UnaryMinus, Abs, Round):
    _cls.type_sig = SIG_NUMERIC
for _cls in (Sqrt, Exp, Log, Pow):
    _cls.type_sig = SIG_FLOATING
    _cls.input_sig = SIG_NUMERIC
for _cls in (Floor, Ceil):
    _cls.type_sig = SIG_NUMERIC
for _cls in (EqualTo, LessThan, LessThanOrEqual, GreaterThan,
             GreaterThanOrEqual, EqualNullSafe):
    _cls.type_sig = SIG_BOOLEAN
    _cls.input_sig = SIG_ALL_SCALAR
for _cls in (Not, And, Or):
    _cls.type_sig = SIG_BOOLEAN
for _cls in (IsNull, IsNotNull):
    _cls.type_sig = SIG_BOOLEAN
    _cls.input_sig = SIG_ALL_SCALAR | frozenset({"array"})
IsNaN.type_sig = SIG_BOOLEAN
IsNaN.input_sig = SIG_FLOATING
# column pass-through carries everything a batch can hold
BoundReference.type_sig = SIG_ALL


# ---------------------------------------------------------------------------
# Columnar device UDF — the RapidsUDF hook, TPU-first
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class DeviceUDF(Expression):
    """User function over RAW column arrays, run INSIDE the fused XLA
    program [REF: spark-rapids RapidsUDF — there a JNI hook handing the
    user cuDF columns; here the user writes jax and XLA fuses it with
    the surrounding expression tree, which is strictly stronger: no
    kernel-launch boundary at all].

    Contract: ``fn(*arrays) -> array`` must be pure, shape-preserving
    jax (traceable; no host syncs); nulls propagate as the intersection
    of input validities (Spark null-safe semantics); numeric/boolean/
    datetime columns only (strings ride byte matrices whose layout is
    not a stable public surface yet)."""

    fn: object
    args: Tuple[Expression, ...]
    dtype: T.DataType
    fname: str = "device_udf"

    type_sig = SIG_ALL_SCALAR - SIG_STRINGY | frozenset({"null"})
    input_sig = SIG_ALL_SCALAR - SIG_STRINGY | frozenset({"null"})

    @property
    def children(self):
        return tuple(self.args)

    def eval_tpu(self, batch: DeviceBatch) -> DeviceColumn:
        cols = [a.eval_tpu(batch) for a in self.args]
        out = self.fn(*[c.data for c in cols])
        out = jnp.asarray(out).astype(T.to_numpy_dtype(self.dtype))
        validity = merge_validity_d(*[c.validity for c in cols])
        return DeviceColumn(self.dtype, out, validity)

    def eval_cpu(self, batch: HostBatch) -> HostCol:
        # the same jax fn runs on host arrays (jax.numpy accepts numpy;
        # on the CPU backend this IS the oracle of the device run)
        cols = [a.eval_cpu(batch) for a in self.args]
        out = np.asarray(self.fn(*[c.data for c in cols])).astype(
            T.to_numpy_dtype(self.dtype))
        validity = merge_validity_h(*[c.validity for c in cols])
        return HostCol(self.dtype, out, validity)

    def __str__(self):
        args = ", ".join(str(a) for a in self.args)
        return f"{self.fname}({args})"
