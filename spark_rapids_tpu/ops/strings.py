"""String expressions over padded byte matrices.

[REF: sql-plugin/../stringFunctions.scala] — re-designed for TPU: strings
are ``uint8[B, W]`` matrices + lengths (columnar/column.py), so substring/
compare/search vectorize on the VPU instead of walking cuDF offset buffers.

Caveats vs Spark (documented incompat, mirroring the reference's own
incompat flags):
* Lexicographic compare is bytewise (equals UTF-8 codepoint order, which
  matches Spark's UTF8String binary ordering) but strings containing NUL
  bytes compare equal to their NUL-padded prefixes.
* upper/lower are ASCII-only on device (non-ASCII passes through).
* substring on device is byte-indexed; Spark indexes by codepoint.  ASCII
  data behaves identically; the CPU path is codepoint-correct.
``length`` counts UTF-8 codepoints correctly on both paths.
"""

from __future__ import annotations

import dataclasses
from typing import List

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.column import DeviceColumn, round_up_pow2
from spark_rapids_tpu.columnar.host import HostCol
from spark_rapids_tpu.ops.expressions import (
    Expression, merge_validity_d, merge_validity_h)


# ---------------------------------------------------------------------------
# device helpers
# ---------------------------------------------------------------------------

def _pad_to(col: DeviceColumn, w: int) -> jnp.ndarray:
    """Pad/truncate a string column's byte matrix to width w."""
    cur = col.data.shape[1]
    if cur == w:
        return col.data
    if cur < w:
        return jnp.pad(col.data, ((0, 0), (0, w - cur)))
    return col.data[:, :w]


def _lex_lt_le(a: DeviceColumn, b: DeviceColumn):
    """(a < b, a <= b) bytewise-lexicographic on device."""
    w = max(a.data.shape[1], b.data.shape[1])
    am = _pad_to(a, w).astype(jnp.int32)
    bm = _pad_to(b, w).astype(jnp.int32)
    diff = am != bm
    any_diff = diff.any(axis=1)
    first = jnp.argmax(diff, axis=1)
    rows = jnp.arange(am.shape[0])
    ab = am[rows, first]
    bb = bm[rows, first]
    lt = jnp.where(any_diff, ab < bb, a.lengths < b.lengths)
    eq = ~any_diff & (a.lengths == b.lengths)
    return lt, lt | eq


@dataclasses.dataclass
class StringComparison(Expression):
    op: str  # eq, lt, le, gt, ge, eqns
    left: Expression
    right: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.BooleanType)

    @property
    def name(self):
        return {"eq": "EqualTo", "lt": "LessThan", "le": "LessThanOrEqual",
                "gt": "GreaterThan", "ge": "GreaterThanOrEqual",
                "eqns": "EqualNullSafe"}[self.op]

    @property
    def children(self):
        return (self.left, self.right)

    def eval_tpu(self, batch):
        l = self.left.eval_tpu(batch)
        r = self.right.eval_tpu(batch)
        if self.op in ("eq", "eqns"):
            w = max(l.data.shape[1], r.data.shape[1])
            eq = (_pad_to(l, w) == _pad_to(r, w)).all(axis=1) & (
                l.lengths == r.lengths)
            if self.op == "eq":
                return DeviceColumn(self.dtype, eq,
                                    merge_validity_d(l.validity, r.validity))
            lv, rv = l.valid_mask(), r.valid_mask()
            return DeviceColumn(self.dtype,
                                jnp.where(lv & rv, eq, ~lv & ~rv), None)
        lt, le = _lex_lt_le(l, r)
        data = {"lt": lt, "le": le, "gt": ~le, "ge": ~lt}[self.op]
        return DeviceColumn(self.dtype, data,
                            merge_validity_d(l.validity, r.validity))

    def eval_cpu(self, batch):
        l = self.left.eval_cpu(batch)
        r = self.right.eval_cpu(batch)
        n = len(l.data)
        la = np.array([s.encode() if isinstance(s, str) else s
                       for s in l.data], object)
        ra = np.array([s.encode() if isinstance(s, str) else s
                       for s in r.data], object)
        if self.op == "eq":
            data = np.array([la[i] == ra[i] for i in range(n)])
            return HostCol(self.dtype, data,
                           merge_validity_h(l.validity, r.validity))
        if self.op == "eqns":
            lv, rv = l.valid_mask(), r.valid_mask()
            eq = np.array([la[i] == ra[i] for i in range(n)])
            return HostCol(self.dtype, np.where(lv & rv, eq, ~lv & ~rv), None)
        cmp = {"lt": lambda x, y: x < y, "le": lambda x, y: x <= y,
               "gt": lambda x, y: x > y, "ge": lambda x, y: x >= y}[self.op]
        data = np.array([cmp(la[i], ra[i]) for i in range(n)])
        return HostCol(self.dtype, data,
                       merge_validity_h(l.validity, r.validity))


def string_comparison(op: str, l: Expression, r: Expression) -> Expression:
    return StringComparison(op, l, r)


@dataclasses.dataclass
class Length(Expression):
    """char length (UTF-8 codepoints)."""

    child: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.IntegerType)

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        w = c.data.shape[1]
        in_str = jnp.arange(w)[None, :] < c.lengths[:, None]
        cont = (c.data & 0xC0) == 0x80
        data = jnp.sum(in_str & ~cont, axis=1).astype(jnp.int32)
        return DeviceColumn(self.dtype, data, c.validity)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        data = np.array([len(s) for s in c.data], np.int32)
        return HostCol(self.dtype, data, c.validity)


@dataclasses.dataclass
class _CaseMap(Expression):
    child: Expression
    UPPER = True
    # per-op incompat gate [REF: GpuOverrides incompat + RapidsConf
    # isIncompatEnabled]: honest about the device semantics difference —
    # requires spark.rapids.sql.incompatibleOps.enabled=true
    incompat = ("ASCII-only case mapping on device; non-ASCII bytes pass "
                "through unchanged")

    @property
    def dtype(self):
        return T.StringT

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        d = c.data
        if self.UPPER:
            is_target = (d >= ord("a")) & (d <= ord("z"))
            out = jnp.where(is_target, d - 32, d)
        else:
            is_target = (d >= ord("A")) & (d <= ord("Z"))
            out = jnp.where(is_target, d + 32, d)
        return DeviceColumn(T.StringT, out.astype(jnp.uint8), c.validity,
                            c.lengths)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        f = str.upper if self.UPPER else str.lower
        data = np.array([f(s) for s in c.data], object)
        return HostCol(T.StringT, data, c.validity)


class Upper(_CaseMap):
    UPPER = True


class Lower(_CaseMap):
    UPPER = False


def string_unary(op: str, child: Expression) -> Expression:
    if op == "length":
        return Length(child)
    if op == "upper":
        return Upper(child)
    if op == "lower":
        return Lower(child)
    raise ValueError(op)


@dataclasses.dataclass
class Substring(Expression):
    """substring(str, pos, len) — 1-based, negative pos counts from end.
    Device path is byte-indexed (exact for ASCII)."""

    child: Expression
    pos: int
    length: int

    @property
    def dtype(self):
        return T.StringT

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        b, w = c.data.shape
        slen = c.lengths
        if self.pos > 0:
            start = jnp.full_like(slen, self.pos - 1)
        elif self.pos == 0:
            start = jnp.zeros_like(slen)
        else:
            start = jnp.maximum(slen + self.pos, 0)
        out_len = jnp.clip(slen - start, 0, max(self.length, 0)).astype(jnp.int32)
        ow = round_up_pow2(min(max(self.length, 1), w), 8)
        idx = start[:, None] + jnp.arange(ow)[None, :]
        gathered = jnp.take_along_axis(
            c.data, jnp.clip(idx, 0, w - 1), axis=1)
        mask = jnp.arange(ow)[None, :] < out_len[:, None]
        return DeviceColumn(T.StringT,
                            jnp.where(mask, gathered, 0).astype(jnp.uint8),
                            c.validity, out_len)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        out = np.empty(len(c.data), object)
        for i, s in enumerate(c.data):
            p = self.pos
            if p > 0:
                st = p - 1
            elif p == 0:
                st = 0
            else:
                st = max(len(s) + p, 0)
            out[i] = s[st:st + max(self.length, 0)]
        return HostCol(T.StringT, out, c.validity)


@dataclasses.dataclass
class StringPredicate(Expression):
    """startswith / endswith / contains with a literal pattern."""

    op: str
    left: Expression
    right: Expression  # must be a Literal on the TPU path
    dtype: T.DataType = dataclasses.field(default_factory=T.BooleanType)

    @property
    def name(self):
        return {"startswith": "StartsWith", "endswith": "EndsWith",
                "contains": "Contains"}[self.op]

    @property
    def children(self):
        return (self.left, self.right)

    def _pattern(self) -> bytes:
        from spark_rapids_tpu.ops.expressions import Literal
        if not isinstance(self.right, Literal):
            raise NotImplementedError(
                f"{self.name} on TPU requires a literal pattern")
        return str(self.right.value).encode()

    def eval_tpu(self, batch):
        c = self.left.eval_tpu(batch)
        pat = self._pattern()
        p = len(pat)
        b, w = c.data.shape
        validity = merge_validity_d(c.validity,
                                    self.right.eval_tpu(batch).validity)
        if p == 0:
            return DeviceColumn(self.dtype, jnp.ones((b,), jnp.bool_), validity)
        if p > w:
            return DeviceColumn(self.dtype, jnp.zeros((b,), jnp.bool_), validity)
        pv = jnp.asarray(np.frombuffer(pat, np.uint8))
        if self.op == "startswith":
            data = (c.data[:, :p] == pv[None, :]).all(axis=1) & (c.lengths >= p)
        elif self.op == "endswith":
            idx = jnp.clip(c.lengths[:, None] - p + jnp.arange(p)[None, :], 0, w - 1)
            tail = jnp.take_along_axis(c.data, idx, axis=1)
            data = (tail == pv[None, :]).all(axis=1) & (c.lengths >= p)
        else:  # contains: compare at every shift (static small loop)
            hits = jnp.zeros((b,), jnp.bool_)
            for s in range(w - p + 1):
                m = (c.data[:, s:s + p] == pv[None, :]).all(axis=1)
                hits = hits | (m & (c.lengths >= s + p))
            data = hits
        return DeviceColumn(self.dtype, data, validity)

    def eval_cpu(self, batch):
        l = self.left.eval_cpu(batch)
        r = self.right.eval_cpu(batch)
        f = {"startswith": str.startswith, "endswith": str.endswith,
             "contains": str.__contains__}[self.op]
        data = np.array([f(l.data[i], r.data[i]) for i in range(len(l.data))])
        return HostCol(self.dtype, data,
                       merge_validity_h(l.validity, r.validity))


def string_predicate(op, l, r) -> Expression:
    return StringPredicate(op, l, r)


@dataclasses.dataclass
class Concat(Expression):
    exprs: List[Expression]

    @property
    def dtype(self):
        return T.StringT

    @property
    def children(self):
        return tuple(self.exprs)

    def eval_tpu(self, batch):
        cols = [e.eval_tpu(batch) for e in self.exprs]
        total_w = sum(c.data.shape[1] for c in cols)
        ow = round_up_pow2(total_w, 8)
        b = batch.capacity
        out = jnp.zeros((b, ow), jnp.uint8)
        pos = jnp.zeros((b,), jnp.int32)
        # place each piece via gather from a concatenated source
        # simple approach: iteratively scatter with take_along_axis writes
        col_idx = jnp.arange(ow)[None, :]
        for c in cols:
            w = c.data.shape[1]
            rel = col_idx - pos[:, None]
            in_piece = (rel >= 0) & (rel < c.lengths[:, None])
            src = jnp.take_along_axis(
                jnp.pad(c.data, ((0, 0), (0, max(ow - w, 0)))),
                jnp.clip(rel, 0, ow - 1), axis=1)
            out = jnp.where(in_piece, src, out)
            pos = pos + c.lengths
        validity = merge_validity_d(*[c.validity for c in cols])
        return DeviceColumn(T.StringT, out, validity, pos)

    def eval_cpu(self, batch):
        cols = [e.eval_cpu(batch) for e in self.exprs]
        n = len(cols[0].data)
        data = np.array(["".join(str(c.data[i]) for c in cols)
                         for i in range(n)], object)
        return HostCol(T.StringT, data,
                       merge_validity_h(*[c.validity for c in cols]))
