"""String expressions over padded byte matrices.

[REF: sql-plugin/../stringFunctions.scala] — re-designed for TPU: strings
are ``uint8[B, W]`` matrices + lengths (columnar/column.py), so substring/
compare/search vectorize on the VPU instead of walking cuDF offset buffers.

Caveats vs Spark (documented incompat, mirroring the reference's own
incompat flags):
* Lexicographic compare is bytewise (equals UTF-8 codepoint order, which
  matches Spark's UTF8String binary ordering) but strings containing NUL
  bytes compare equal to their NUL-padded prefixes.
* upper/lower are ASCII-only on device (non-ASCII passes through).
* substring on device is byte-indexed; Spark indexes by codepoint.  ASCII
  data behaves identically; the CPU path is codepoint-correct.
``length`` counts UTF-8 codepoints correctly on both paths.
"""

from __future__ import annotations

import dataclasses
from typing import List

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.column import DeviceColumn, round_up_pow2
from spark_rapids_tpu.columnar.host import HostCol
from spark_rapids_tpu.ops.expressions import (
    Expression, merge_validity_d, merge_validity_h)


# ---------------------------------------------------------------------------
# device helpers
# ---------------------------------------------------------------------------

def _pad_to(col: DeviceColumn, w: int) -> jnp.ndarray:
    """Pad/truncate a string column's byte matrix to width w."""
    cur = col.data.shape[1]
    if cur == w:
        return col.data
    if cur < w:
        return jnp.pad(col.data, ((0, 0), (0, w - cur)))
    return col.data[:, :w]


def _lex_lt_le(a: DeviceColumn, b: DeviceColumn):
    """(a < b, a <= b) bytewise-lexicographic on device."""
    w = max(a.data.shape[1], b.data.shape[1])
    am = _pad_to(a, w).astype(jnp.int32)
    bm = _pad_to(b, w).astype(jnp.int32)
    diff = am != bm
    any_diff = diff.any(axis=1)
    first = jnp.argmax(diff, axis=1)
    rows = jnp.arange(am.shape[0])
    ab = am[rows, first]
    bb = bm[rows, first]
    lt = jnp.where(any_diff, ab < bb, a.lengths < b.lengths)
    eq = ~any_diff & (a.lengths == b.lengths)
    return lt, lt | eq


@dataclasses.dataclass
class StringComparison(Expression):
    op: str  # eq, lt, le, gt, ge, eqns
    left: Expression
    right: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.BooleanType)

    @property
    def name(self):
        return {"eq": "EqualTo", "lt": "LessThan", "le": "LessThanOrEqual",
                "gt": "GreaterThan", "ge": "GreaterThanOrEqual",
                "eqns": "EqualNullSafe"}[self.op]

    @property
    def children(self):
        return (self.left, self.right)

    def eval_tpu(self, batch):
        l = self.left.eval_tpu(batch)
        r = self.right.eval_tpu(batch)
        if self.op in ("eq", "eqns"):
            w = max(l.data.shape[1], r.data.shape[1])
            eq = (_pad_to(l, w) == _pad_to(r, w)).all(axis=1) & (
                l.lengths == r.lengths)
            if self.op == "eq":
                return DeviceColumn(self.dtype, eq,
                                    merge_validity_d(l.validity, r.validity))
            lv, rv = l.valid_mask(), r.valid_mask()
            return DeviceColumn(self.dtype,
                                jnp.where(lv & rv, eq, ~lv & ~rv), None)
        lt, le = _lex_lt_le(l, r)
        data = {"lt": lt, "le": le, "gt": ~le, "ge": ~lt}[self.op]
        return DeviceColumn(self.dtype, data,
                            merge_validity_d(l.validity, r.validity))

    def eval_cpu(self, batch):
        l = self.left.eval_cpu(batch)
        r = self.right.eval_cpu(batch)
        n = len(l.data)
        la = np.array([s.encode() if isinstance(s, str) else s
                       for s in l.data], object)
        ra = np.array([s.encode() if isinstance(s, str) else s
                       for s in r.data], object)
        if self.op == "eq":
            data = np.array([la[i] == ra[i] for i in range(n)])
            return HostCol(self.dtype, data,
                           merge_validity_h(l.validity, r.validity))
        if self.op == "eqns":
            lv, rv = l.valid_mask(), r.valid_mask()
            eq = np.array([la[i] == ra[i] for i in range(n)])
            return HostCol(self.dtype, np.where(lv & rv, eq, ~lv & ~rv), None)
        cmp = {"lt": lambda x, y: x < y, "le": lambda x, y: x <= y,
               "gt": lambda x, y: x > y, "ge": lambda x, y: x >= y}[self.op]
        data = np.array([cmp(la[i], ra[i]) for i in range(n)])
        return HostCol(self.dtype, data,
                       merge_validity_h(l.validity, r.validity))


def string_comparison(op: str, l: Expression, r: Expression) -> Expression:
    return StringComparison(op, l, r)


@dataclasses.dataclass
class Length(Expression):
    """char length (UTF-8 codepoints)."""

    child: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.IntegerType)

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        w = c.data.shape[1]
        in_str = jnp.arange(w)[None, :] < c.lengths[:, None]
        cont = (c.data & 0xC0) == 0x80
        data = jnp.sum(in_str & ~cont, axis=1).astype(jnp.int32)
        return DeviceColumn(self.dtype, data, c.validity)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        data = np.array([len(s) for s in c.data], np.int32)
        return HostCol(self.dtype, data, c.validity)


@dataclasses.dataclass
class _CaseMap(Expression):
    child: Expression
    UPPER = True
    # per-op incompat gate [REF: GpuOverrides incompat + RapidsConf
    # isIncompatEnabled]: honest about the device semantics difference —
    # requires spark.rapids.sql.incompatibleOps.enabled=true
    incompat = ("ASCII-only case mapping on device; non-ASCII bytes pass "
                "through unchanged")

    @property
    def dtype(self):
        return T.StringT

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        d = c.data
        if self.UPPER:
            is_target = (d >= ord("a")) & (d <= ord("z"))
            out = jnp.where(is_target, d - 32, d)
        else:
            is_target = (d >= ord("A")) & (d <= ord("Z"))
            out = jnp.where(is_target, d + 32, d)
        return DeviceColumn(T.StringT, out.astype(jnp.uint8), c.validity,
                            c.lengths)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        f = str.upper if self.UPPER else str.lower
        data = np.array([f(s) for s in c.data], object)
        return HostCol(T.StringT, data, c.validity)


class Upper(_CaseMap):
    UPPER = True


class Lower(_CaseMap):
    UPPER = False


def string_unary(op: str, child: Expression) -> Expression:
    if op == "length":
        return Length(child)
    if op == "upper":
        return Upper(child)
    if op == "lower":
        return Lower(child)
    raise ValueError(op)


@dataclasses.dataclass
class Substring(Expression):
    """substring(str, pos, len) — 1-based, negative pos counts from end.
    Device path is byte-indexed (exact for ASCII)."""

    child: Expression
    pos: int
    length: int

    @property
    def dtype(self):
        return T.StringT

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        b, w = c.data.shape
        slen = c.lengths
        if self.pos > 0:
            start = jnp.full_like(slen, self.pos - 1)
        elif self.pos == 0:
            start = jnp.zeros_like(slen)
        else:
            start = jnp.maximum(slen + self.pos, 0)
        out_len = jnp.clip(slen - start, 0, max(self.length, 0)).astype(jnp.int32)
        ow = round_up_pow2(min(max(self.length, 1), w), 8)
        idx = start[:, None] + jnp.arange(ow)[None, :]
        gathered = jnp.take_along_axis(
            c.data, jnp.clip(idx, 0, w - 1), axis=1)
        mask = jnp.arange(ow)[None, :] < out_len[:, None]
        return DeviceColumn(T.StringT,
                            jnp.where(mask, gathered, 0).astype(jnp.uint8),
                            c.validity, out_len)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        out = np.empty(len(c.data), object)
        for i, s in enumerate(c.data):
            p = self.pos
            if p > 0:
                st = p - 1
            elif p == 0:
                st = 0
            else:
                st = max(len(s) + p, 0)
            out[i] = s[st:st + max(self.length, 0)]
        return HostCol(T.StringT, out, c.validity)


@dataclasses.dataclass
class StringPredicate(Expression):
    """startswith / endswith / contains with a literal pattern."""

    op: str
    left: Expression
    right: Expression  # must be a Literal on the TPU path
    dtype: T.DataType = dataclasses.field(default_factory=T.BooleanType)

    @property
    def name(self):
        return {"startswith": "StartsWith", "endswith": "EndsWith",
                "contains": "Contains"}[self.op]

    @property
    def children(self):
        return (self.left, self.right)

    def _pattern(self) -> bytes:
        from spark_rapids_tpu.ops.expressions import Literal
        if not isinstance(self.right, Literal):
            raise NotImplementedError(
                f"{self.name} on TPU requires a literal pattern")
        return str(self.right.value).encode()

    def eval_tpu(self, batch):
        c = self.left.eval_tpu(batch)
        pat = self._pattern()
        p = len(pat)
        b, w = c.data.shape
        validity = merge_validity_d(c.validity,
                                    self.right.eval_tpu(batch).validity)
        if p == 0:
            return DeviceColumn(self.dtype, jnp.ones((b,), jnp.bool_), validity)
        if p > w:
            return DeviceColumn(self.dtype, jnp.zeros((b,), jnp.bool_), validity)
        pv = jnp.asarray(np.frombuffer(pat, np.uint8))
        if self.op == "startswith":
            data = (c.data[:, :p] == pv[None, :]).all(axis=1) & (c.lengths >= p)
        elif self.op == "endswith":
            idx = jnp.clip(c.lengths[:, None] - p + jnp.arange(p)[None, :], 0, w - 1)
            tail = jnp.take_along_axis(c.data, idx, axis=1)
            data = (tail == pv[None, :]).all(axis=1) & (c.lengths >= p)
        else:  # contains: compare at every shift (static small loop)
            hits = jnp.zeros((b,), jnp.bool_)
            for s in range(w - p + 1):
                m = (c.data[:, s:s + p] == pv[None, :]).all(axis=1)
                hits = hits | (m & (c.lengths >= s + p))
            data = hits
        return DeviceColumn(self.dtype, data, validity)

    def eval_cpu(self, batch):
        l = self.left.eval_cpu(batch)
        r = self.right.eval_cpu(batch)
        f = {"startswith": str.startswith, "endswith": str.endswith,
             "contains": str.__contains__}[self.op]
        data = np.array([f(l.data[i], r.data[i]) for i in range(len(l.data))])
        return HostCol(self.dtype, data,
                       merge_validity_h(l.validity, r.validity))


def string_predicate(op, l, r) -> Expression:
    return StringPredicate(op, l, r)


@dataclasses.dataclass
class Trim(Expression):
    """trim/ltrim/rtrim — space (0x20) removal, Spark defaults.

    [REF: stringFunctions.scala :: GpuStringTrim/TrimLeft/TrimRight]
    Device: shift-gather like substring, start/length from leading and
    trailing space counts — no data-dependent shapes."""

    child: Expression
    side: str = "both"  # both | leading | trailing

    @property
    def name(self):
        return {"both": "StringTrim", "leading": "StringTrimLeft",
                "trailing": "StringTrimRight"}[self.side]

    @property
    def dtype(self):
        return T.StringT

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        b, w = c.data.shape
        pos = jnp.arange(w)[None, :]
        in_str = pos < c.lengths[:, None]
        is_sp = (c.data == 0x20) & in_str
        nonsp = in_str & ~is_sp
        any_nonsp = nonsp.any(axis=1)
        # all-spaces rows: first = length, last = 0 → empty result
        first = jnp.where(any_nonsp, jnp.argmax(nonsp, axis=1),
                          c.lengths).astype(jnp.int32)
        last = jnp.where(
            any_nonsp,
            w - jnp.argmax(jnp.flip(nonsp, axis=1), axis=1), 0
        ).astype(jnp.int32)
        if self.side == "both":
            start, end = first, last
        elif self.side == "leading":
            start, end = first, c.lengths
        else:
            start, end = jnp.zeros_like(first), last
        out_len = jnp.maximum(end - start, 0).astype(jnp.int32)
        idx = start[:, None] + jnp.arange(w)[None, :]
        g = jnp.take_along_axis(c.data, jnp.clip(idx, 0, w - 1), axis=1)
        mask = jnp.arange(w)[None, :] < out_len[:, None]
        return DeviceColumn(T.StringT,
                            jnp.where(mask, g, 0).astype(jnp.uint8),
                            c.validity, out_len)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        f = {"both": lambda s: s.strip(" "),
             "leading": lambda s: s.lstrip(" "),
             "trailing": lambda s: s.rstrip(" ")}[self.side]
        data = np.array([f(s) for s in c.data], object)
        return HostCol(T.StringT, data, c.validity)


@dataclasses.dataclass
class StringLocate(Expression):
    """locate(substr, str, pos) / instr — 1-based first occurrence, 0 if
    absent, null pattern/input → null.  [REF: GpuStringLocate]"""

    substr: Expression  # literal on the device path
    child: Expression
    start: int = 1
    dtype: T.DataType = dataclasses.field(default_factory=T.IntegerType)

    @property
    def children(self):
        return (self.substr, self.child)

    def _pattern(self) -> bytes:
        from spark_rapids_tpu.ops.expressions import Literal
        if not isinstance(self.substr, Literal):
            raise NotImplementedError("locate on TPU needs literal substr")
        return str(self.substr.value).encode()

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        pat = self._pattern()
        p = len(pat)
        b, w = c.data.shape
        validity = merge_validity_d(
            c.validity, self.substr.eval_tpu(batch).validity)
        if self.start < 1:
            # Spark: pos < 1 → 0 (no match semantics)
            return DeviceColumn(self.dtype, jnp.zeros((b,), jnp.int32),
                                validity)
        if p == 0:
            # Spark: empty needle → pos (when pos <= len+1), else 0
            data = jnp.where(jnp.int32(self.start) <= c.lengths + 1,
                             jnp.int32(self.start), 0)
            return DeviceColumn(self.dtype, data.astype(jnp.int32),
                                validity)
        pv = jnp.asarray(np.frombuffer(pat, np.uint8))
        hits = jnp.zeros((b, max(w - p + 1, 1)), jnp.bool_)
        if p <= w:
            cols = []
            for s in range(w - p + 1):
                m = (c.data[:, s:s + p] == pv[None, :]).all(axis=1)
                cols.append(m & (c.lengths >= s + p)
                            & (s >= self.start - 1))
            hits = jnp.stack(cols, axis=1)
        found = hits.any(axis=1)
        first = jnp.argmax(hits, axis=1)
        data = jnp.where(found, first + 1, 0).astype(jnp.int32)
        return DeviceColumn(self.dtype, data, validity)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        s_ = self.substr.eval_cpu(batch)
        out = np.zeros(len(c.data), np.int32)
        for i in range(len(c.data)):
            if self.start < 1:
                out[i] = 0
                continue
            out[i] = str(c.data[i]).find(str(s_.data[i]),
                                         self.start - 1) + 1
        return HostCol(self.dtype, out,
                       merge_validity_h(c.validity, s_.validity))


def _parse_like(pattern: str, escape: str = "\\"):
    """LIKE pattern → list of segments; a segment is a list of
    (byte | None) where None = '_' (any byte).  Segments are the literal
    runs between '%'s."""
    segs: List[List] = [[]]
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == escape and i + 1 < len(pattern):
            for by in pattern[i + 1].encode():
                segs[-1].append(by)
            i += 2
            continue
        if ch == "%":
            segs.append([])
        elif ch == "_":
            segs[-1].append(None)
        else:
            for by in ch.encode():
                segs[-1].append(by)
        i += 1
    return segs


@dataclasses.dataclass
class Like(Expression):
    """SQL LIKE with literal pattern ('%', '_', backslash escape).

    [REF: GpuLike] — device matching is greedy leftmost per '%'-separated
    segment: anchored head, searched middles, end-anchored tail; each
    segment's match-at-shift matrix is one vectorized compare.  Byte-wise
    ('_' matches one BYTE, exact for ASCII; the reference's cuDF like is
    byte-wise too)."""

    child: Expression
    pattern: str
    dtype: T.DataType = dataclasses.field(default_factory=T.BooleanType)

    @property
    def children(self):
        return (self.child,)

    def _seg_match(self, c: DeviceColumn, seg) -> jnp.ndarray:
        """[B, w+1] — segment matches starting at shift s (s ≤ len-p)."""
        b, w = c.data.shape
        p = len(seg)
        if p == 0:
            return jnp.arange(w + 1)[None, :] <= c.lengths[:, None]
        if p > w:
            return jnp.zeros((b, w + 1), jnp.bool_)
        fixed = np.array([by if by is not None else 0 for by in seg],
                         np.uint8)
        wild = np.array([by is None for by in seg])
        pv = jnp.asarray(fixed)
        wv = jnp.asarray(wild)
        cols = []
        for s in range(w + 1):
            if s + p <= w:
                m = ((c.data[:, s:s + p] == pv[None, :]) | wv[None, :]
                     ).all(axis=1) & (c.lengths >= s + p)
            else:
                m = jnp.zeros((b,), jnp.bool_)
            cols.append(m)
        return jnp.stack(cols, axis=1)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        b, w = c.data.shape
        segs = _parse_like(self.pattern)
        shifts = jnp.arange(w + 1)[None, :]
        if len(segs) == 1:
            seg = segs[0]
            m = self._seg_match(c, seg)
            data = m[:, 0] & (c.lengths == len(seg))
            return DeviceColumn(self.dtype, data, c.validity)
        head, mids, tail = segs[0], segs[1:-1], segs[-1]
        ok = jnp.ones((b,), jnp.bool_)
        pos = jnp.zeros((b,), jnp.int32)
        if head:
            m = self._seg_match(c, head)
            ok = ok & m[:, 0]
            pos = jnp.full((b,), len(head), jnp.int32)
        for seg in mids:
            if not seg:
                continue
            m = self._seg_match(c, seg) & (shifts >= pos[:, None])
            found = m.any(axis=1)
            ok = ok & found
            pos = jnp.where(found,
                            jnp.argmax(m, axis=1).astype(jnp.int32)
                            + len(seg), pos)
        end_shift = c.lengths - len(tail)
        if tail:
            m = self._seg_match(c, tail)
            at_end = jnp.take_along_axis(
                m, jnp.clip(end_shift, 0, w)[:, None].astype(jnp.int32),
                axis=1)[:, 0]
            ok = ok & at_end & (end_shift >= pos)
        return DeviceColumn(self.dtype, ok, c.validity)

    def eval_cpu(self, batch):
        import re as _re
        c = self.child.eval_cpu(batch)
        # translate LIKE → regex (escape-aware)
        rx = ""
        i = 0
        pat = self.pattern
        while i < len(pat):
            ch = pat[i]
            if ch == "\\" and i + 1 < len(pat):
                rx += _re.escape(pat[i + 1])
                i += 2
                continue
            if ch == "%":
                rx += "(?s:.*)"
            elif ch == "_":
                rx += "(?s:.)"
            else:
                rx += _re.escape(ch)
            i += 1
        prog = _re.compile(rx)
        data = np.array([prog.fullmatch(str(s)) is not None
                         for s in c.data])
        return HostCol(self.dtype, data, c.validity)


@dataclasses.dataclass
class StringReplace(Expression):
    """replace(str, search, replace) with literal search/replace.

    [REF: GpuStringReplace] — device algorithm, scatter-free:
    greedy non-overlapping match selection (one static pass over the
    width carrying a 'next free position' vector), per-input-byte emit
    counts, exclusive scan for output offsets, then every OUTPUT byte
    binary-searches its source segment (vmapped searchsorted)."""

    child: Expression
    search: str
    replace: str

    @property
    def dtype(self):
        return T.StringT

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        import jax
        c = self.child.eval_tpu(batch)
        b, w = c.data.shape
        sb = self.search.encode()
        rb = self.replace.encode()
        ls, lr = len(sb), len(rb)
        if ls == 0 or ls > w:
            return c
        pv = jnp.asarray(np.frombuffer(sb, np.uint8))
        rv = (jnp.asarray(np.frombuffer(rb, np.uint8)) if lr
              else jnp.zeros((1,), jnp.uint8))
        # match-at-shift
        mats = []
        for s in range(w):
            if s + ls <= w:
                m = (c.data[:, s:s + ls] == pv[None, :]).all(axis=1) & (
                    c.lengths >= s + ls)
            else:
                m = jnp.zeros((b,), jnp.bool_)
            mats.append(m)
        match = jnp.stack(mats, axis=1)  # [B, w]
        # greedy leftmost non-overlapping selection
        chosen_cols = []
        next_free = jnp.zeros((b,), jnp.int32)
        for j in range(w):
            ch = match[:, j] & (next_free <= j)
            next_free = jnp.where(ch, j + ls, next_free)
            chosen_cols.append(ch)
        chosen = jnp.stack(chosen_cols, axis=1)  # [B, w]
        # covered = inside a chosen span but not its start
        cover_cols = []
        cov_until = jnp.zeros((b,), jnp.int32)
        for j in range(w):
            cov_until = jnp.where(chosen[:, j], j + ls, cov_until)
            cover_cols.append((cov_until > j) & ~chosen[:, j])
        covered = jnp.stack(cover_cols, axis=1)
        in_str = jnp.arange(w)[None, :] < c.lengths[:, None]
        emit = jnp.where(chosen, lr,
                         jnp.where(covered | ~in_str, 0, 1)
                         ).astype(jnp.int32)
        off = jnp.cumsum(emit, axis=1) - emit  # exclusive
        out_len = (off[:, -1] + emit[:, -1]).astype(jnp.int32)
        wout = round_up_pow2(
            max(w if lr <= ls else w + (w // ls) * (lr - ls), 1), 8)
        ks = jnp.arange(wout, dtype=jnp.int32)

        def row(off_r, emit_r, chosen_r, data_r, n_r):
            j = jnp.searchsorted(off_r + emit_r, ks, side="right")
            j = jnp.clip(j, 0, w - 1).astype(jnp.int32)
            is_rep = jnp.take(chosen_r, j)
            rel = ks - jnp.take(off_r, j)
            rep_byte = jnp.take(rv, jnp.clip(rel, 0, max(lr - 1, 0)))
            src_byte = jnp.take(data_r, j)
            by = jnp.where(is_rep, rep_byte, src_byte)
            return jnp.where(ks < n_r, by, 0)

        out = jax.vmap(row)(off, emit, chosen, c.data, out_len)
        return DeviceColumn(T.StringT, out.astype(jnp.uint8),
                            c.validity, out_len)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        if not self.search:
            return HostCol(T.StringT, c.data, c.validity)
        data = np.array([str(s).replace(self.search, self.replace)
                         for s in c.data], object)
        return HostCol(T.StringT, data, c.validity)


@dataclasses.dataclass
class Concat(Expression):
    exprs: List[Expression]

    @property
    def dtype(self):
        return T.StringT

    @property
    def children(self):
        return tuple(self.exprs)

    def eval_tpu(self, batch):
        cols = [e.eval_tpu(batch) for e in self.exprs]
        total_w = sum(c.data.shape[1] for c in cols)
        ow = round_up_pow2(total_w, 8)
        b = batch.capacity
        out = jnp.zeros((b, ow), jnp.uint8)
        pos = jnp.zeros((b,), jnp.int32)
        # place each piece via gather from a concatenated source
        # simple approach: iteratively scatter with take_along_axis writes
        col_idx = jnp.arange(ow)[None, :]
        for c in cols:
            w = c.data.shape[1]
            rel = col_idx - pos[:, None]
            in_piece = (rel >= 0) & (rel < c.lengths[:, None])
            src = jnp.take_along_axis(
                jnp.pad(c.data, ((0, 0), (0, max(ow - w, 0)))),
                jnp.clip(rel, 0, ow - 1), axis=1)
            out = jnp.where(in_piece, src, out)
            pos = pos + c.lengths
        validity = merge_validity_d(*[c.validity for c in cols])
        return DeviceColumn(T.StringT, out, validity, pos)

    def eval_cpu(self, batch):
        cols = [e.eval_cpu(batch) for e in self.exprs]
        n = len(cols[0].data)
        data = np.array(["".join(str(c.data[i]) for c in cols)
                         for i in range(n)], object)
        return HostCol(T.StringT, data,
                       merge_validity_h(*[c.validity for c in cols]))


# ---------------------------------------------------------------------------
# Device string casts [REF: GpuCast.scala — castToString / castStringToInt /
# castStringToBool / castStringToFloat]
# ---------------------------------------------------------------------------

_LONG_STR_W = 24  # "-9223372036854775808" fits with room, pow-2-ish pad


def cast_int_to_string_device(c: DeviceColumn) -> DeviceColumn:
    """int family → decimal string (exact, device-side digit extraction)."""
    v = c.data.astype(jnp.int64)
    neg = v < 0
    # |Long.MIN| overflows int64: compute magnitude in uint64
    mag = jnp.where(neg, (-(v + 1)).astype(jnp.uint64) + jnp.uint64(1),
                    v.astype(jnp.uint64))
    digs = []
    m = mag
    for _ in range(19):
        digs.append((m % jnp.uint64(10)).astype(jnp.uint8))
        m = m // jnp.uint64(10)
    dig = jnp.stack(digs[::-1], axis=1)  # [B,19] most-significant first
    nz = dig != 0
    any_nz = nz.any(axis=1)
    lead = jnp.where(any_nz, jnp.argmax(nz, axis=1), 18).astype(jnp.int32)
    ndig = 19 - lead
    out_len = (ndig + neg.astype(jnp.int32)).astype(jnp.int32)
    w = _LONG_STR_W
    posn = jnp.arange(w, dtype=jnp.int32)[None, :]
    src = lead[:, None] + posn - neg.astype(jnp.int32)[:, None]
    ch = jnp.take_along_axis(dig, jnp.clip(src, 0, 18), axis=1) + ord("0")
    out = jnp.where(neg[:, None] & (posn == 0), ord("-"), ch)
    out = jnp.where(posn < out_len[:, None], out, 0)
    return DeviceColumn(T.StringT, out.astype(jnp.uint8), c.validity,
                        out_len)


def cast_bool_to_string_device(c: DeviceColumn) -> DeviceColumn:
    tv = np.zeros((1, 8), np.uint8)
    tv[0, :4] = np.frombuffer(b"true", np.uint8)
    fv = np.zeros((1, 8), np.uint8)
    fv[0, :5] = np.frombuffer(b"false", np.uint8)
    cond = c.data.astype(jnp.bool_)[:, None]
    out = jnp.where(cond, jnp.asarray(tv), jnp.asarray(fv))
    lengths = jnp.where(c.data.astype(jnp.bool_), 4, 5).astype(jnp.int32)
    return DeviceColumn(T.StringT, out.astype(jnp.uint8), c.validity,
                        lengths)


def _trim_bounds(c: DeviceColumn):
    """(start, end) of the whitespace-trimmed span per row."""
    b, w = c.data.shape
    pos = jnp.arange(w)[None, :]
    in_str = pos < c.lengths[:, None]
    # Spark trims ASCII control+space like Java trim (chars <= 0x20)
    is_sp = (c.data <= 0x20) & in_str
    nonsp = in_str & ~is_sp
    any_nonsp = nonsp.any(axis=1)
    first = jnp.where(any_nonsp, jnp.argmax(nonsp, axis=1),
                      c.lengths).astype(jnp.int32)
    last = jnp.where(any_nonsp,
                     w - jnp.argmax(jnp.flip(nonsp, axis=1), axis=1),
                     0).astype(jnp.int32)
    return first, last


_INT_DST_RANGE = {
    "byte": (-(1 << 7), (1 << 7) - 1),
    "short": (-(1 << 15), (1 << 15) - 1),
    "int": (-(1 << 31), (1 << 31) - 1),
    "long": (-(1 << 63), (1 << 63) - 1),
}


def cast_string_to_int_device(c: DeviceColumn, dst: T.DataType
                              ) -> DeviceColumn:
    """string → integral (Spark non-ANSI): trim, [+-], digits, optional
    fraction truncated toward zero; invalid/overflow → null."""
    b, w = c.data.shape
    start, end = _trim_bounds(c)
    acc = jnp.zeros((b,), jnp.uint64)
    neg = jnp.zeros((b,), jnp.bool_)
    seen_digit = jnp.zeros((b,), jnp.bool_)
    seen_dot = jnp.zeros((b,), jnp.bool_)
    bad = jnp.zeros((b,), jnp.bool_)
    overflow = jnp.zeros((b,), jnp.bool_)
    lim = jnp.uint64((1 << 64) - 1) // jnp.uint64(10)
    for j in range(w):
        by = c.data[:, j].astype(jnp.int32)
        active = (jnp.int32(j) >= start) & (jnp.int32(j) < end) & ~bad
        is_digit = (by >= ord("0")) & (by <= ord("9"))
        is_sign = ((by == ord("+")) | (by == ord("-"))) & (
            jnp.int32(j) == start)
        is_dot = (by == ord(".")) & ~seen_dot
        d = (by - ord("0")).astype(jnp.uint64)
        do_acc = active & is_digit & ~seen_dot
        # 2^64-1 = lim*10 + 5: acc == lim with digit > 5 also overflows
        overflow = overflow | (do_acc & (
            (acc > lim) | ((acc == lim) & (d > jnp.uint64(5)))))
        acc = jnp.where(do_acc, acc * jnp.uint64(10) + d, acc)
        seen_digit = seen_digit | (active & is_digit)
        neg = jnp.where(active & is_sign & (by == ord("-")), True, neg)
        seen_dot = seen_dot | (active & is_dot)
        bad = bad | (active & ~(is_digit | is_sign | is_dot))
    # 2^63 magnitude allowed only for Long.MIN
    max_mag = jnp.where(neg, jnp.uint64(1) << jnp.uint64(63),
                        (jnp.uint64(1) << jnp.uint64(63))
                        - jnp.uint64(1))
    overflow = overflow | (acc > max_mag)
    signed = jnp.where(
        neg, (~acc + jnp.uint64(1)).astype(jnp.int64),
        acc.astype(jnp.int64))
    lo, hi = _INT_DST_RANGE[dst.simple_name.replace("integer", "int")
                            if dst.simple_name == "integer"
                            else dst.simple_name]
    in_range = (signed >= lo) & (signed <= hi)
    valid = seen_digit & ~bad & ~overflow & in_range
    validity = (valid if c.validity is None else (c.validity & valid))
    npdt = T.to_numpy_dtype(dst)
    return DeviceColumn(dst, signed.astype(npdt), validity)


_TRUE_WORDS = [b"true", b"t", b"yes", b"y", b"1"]
_FALSE_WORDS = [b"false", b"f", b"no", b"n", b"0"]


def cast_string_to_bool_device(c: DeviceColumn) -> DeviceColumn:
    b, w = c.data.shape
    start, end = _trim_bounds(c)
    tlen = end - start
    # lowercase a shifted copy
    idx = start[:, None] + jnp.arange(w)[None, :]
    g = jnp.take_along_axis(c.data, jnp.clip(idx, 0, w - 1), axis=1)
    in_t = jnp.arange(w)[None, :] < tlen[:, None]
    low = jnp.where((g >= ord("A")) & (g <= ord("Z")), g + 32, g)
    low = jnp.where(in_t, low, 0)

    def match(word: bytes) -> jnp.ndarray:
        p = len(word)
        if p > w:
            return jnp.zeros((b,), jnp.bool_)
        pv = jnp.asarray(np.frombuffer(word, np.uint8))
        return (low[:, :p] == pv[None, :]).all(axis=1) & (tlen == p)

    is_true = jnp.zeros((b,), jnp.bool_)
    for word in _TRUE_WORDS:
        is_true = is_true | match(word)
    is_false = jnp.zeros((b,), jnp.bool_)
    for word in _FALSE_WORDS:
        is_false = is_false | match(word)
    valid = is_true | is_false
    validity = valid if c.validity is None else (c.validity & valid)
    return DeviceColumn(T.BooleanT, is_true, validity)


def cast_string_to_float_device(c: DeviceColumn, dst: T.DataType
                                ) -> DeviceColumn:
    """string → float/double: sign, digits, '.', digits, [eE][sign]digits,
    'inf'/'infinity'/'nan' (case-insensitive).

    Correctly rounded when the mantissa fits 2^53 and |10-exponent| ≤ 22
    (exact f64 intermediate); beyond that may differ from Java's
    parseDouble by 1 ulp — gated by
    spark.rapids.sql.castStringToFloat.enabled, like the reference."""
    b, w = c.data.shape
    start, end = _trim_bounds(c)
    idx = start[:, None] + jnp.arange(w)[None, :]
    g = jnp.take_along_axis(c.data, jnp.clip(idx, 0, w - 1), axis=1)
    tlen = end - start
    in_t = jnp.arange(w)[None, :] < tlen[:, None]
    low = jnp.where((g >= ord("A")) & (g <= ord("Z")), g + 32, g)
    low = jnp.where(in_t, low, 0).astype(jnp.int32)

    def word_eq(word: bytes, off_sign: bool):
        p = len(word)
        if p > w:
            return jnp.zeros((b,), jnp.bool_)
        pv = jnp.asarray(np.frombuffer(word, np.uint8), dtype=jnp.int32)
        base = (low[:, :p] == pv[None, :]).all(axis=1) & (tlen == p)
        return base

    is_nan = word_eq(b"nan", False)
    inf_pat = jnp.zeros((b,), jnp.bool_)
    sign_inf = jnp.zeros((b,), jnp.bool_)
    for word in (b"inf", b"infinity", b"+inf", b"-inf", b"+infinity",
                 b"-infinity"):
        m = word_eq(word, False)
        inf_pat = inf_pat | m
        if word[0:1] == b"-":
            sign_inf = sign_inf | m
    # general numeric parse
    mant = jnp.zeros((b,), jnp.float64)
    frac_digits = jnp.zeros((b,), jnp.int32)
    exp_acc = jnp.zeros((b,), jnp.int32)
    neg = jnp.zeros((b,), jnp.bool_)
    eneg = jnp.zeros((b,), jnp.bool_)
    seen_digit = jnp.zeros((b,), jnp.bool_)
    seen_dot = jnp.zeros((b,), jnp.bool_)
    in_exp = jnp.zeros((b,), jnp.bool_)
    seen_edigit = jnp.zeros((b,), jnp.bool_)
    seen_esign = jnp.zeros((b,), jnp.bool_)
    bad = jnp.zeros((b,), jnp.bool_)
    for j in range(w):
        by = low[:, j]
        active = (jnp.int32(j) < tlen) & ~bad
        is_digit = (by >= ord("0")) & (by <= ord("9"))
        d = (by - ord("0")).astype(jnp.float64)
        at_start = jnp.int32(j) == 0
        # ONE sign allowed, only directly after 'e'
        after_e = in_exp & ~seen_edigit & ~seen_esign
        is_sign = (by == ord("+")) | (by == ord("-"))
        sign_ok = is_sign & (at_start | after_e)
        seen_esign = seen_esign | (active & is_sign & after_e)
        is_dot = (by == ord(".")) & ~seen_dot & ~in_exp
        is_e = (by == ord("e")) & seen_digit & ~in_exp
        mant_step = active & is_digit & ~in_exp
        mant = jnp.where(mant_step, mant * 10.0 + d, mant)
        frac_digits = jnp.where(mant_step & seen_dot, frac_digits + 1,
                                frac_digits)
        exp_step = active & is_digit & in_exp
        exp_acc = jnp.where(
            exp_step,
            jnp.minimum(exp_acc * 10 + (by - ord("0")), 9999), exp_acc)
        seen_edigit = seen_edigit | exp_step
        seen_digit = seen_digit | (active & is_digit & ~in_exp)
        neg = jnp.where(active & sign_ok & at_start & (by == ord("-")),
                        True, neg)
        eneg = jnp.where(active & sign_ok & ~at_start & (by == ord("-")),
                         True, eneg)
        seen_dot = seen_dot | (active & is_dot)
        in_exp = in_exp | (active & is_e)
        bad = bad | (active & ~(is_digit | sign_ok | is_dot | is_e))
    exp = jnp.where(eneg, -exp_acc, exp_acc) - frac_digits
    # 10^exp via exact split: 10^|e| is exact for |e| ≤ 22
    ae = jnp.clip(jnp.abs(exp), 0, 350)
    p1 = jnp.power(10.0, jnp.minimum(ae, 22).astype(jnp.float64))
    p2 = jnp.power(10.0, jnp.maximum(ae - 22, 0).astype(jnp.float64))
    val = jnp.where(exp >= 0, mant * p1 * p2, mant / p1 / p2)
    val = jnp.where(neg, -val, val)
    ok_num = seen_digit & ~bad & (~in_exp | seen_edigit)
    val = jnp.where(is_nan, jnp.float64(np.nan), val)
    val = jnp.where(inf_pat,
                    jnp.where(sign_inf, -jnp.float64(np.inf),
                              jnp.float64(np.inf)), val)
    valid = ok_num | is_nan | inf_pat
    validity = valid if c.validity is None else (c.validity & valid)
    npdt = T.to_numpy_dtype(dst)
    return DeviceColumn(dst, val.astype(npdt), validity)


# ---------------------------------------------------------------------------
# Regular expressions [REF: RegexParser/CudfRegexTranspiler,
# stringFunctions.scala :: GpuRLike/GpuRegExpExtract/GpuRegExpReplace]
#
# The reference ships a full Java-regex → cuDF transpiler.  The TPU story
# (SURVEY §2.2 N5): simple patterns transpile to the device LIKE /
# predicate kernels at analysis time (plan/analysis.py), everything else
# evaluates host-side through Python's ``re`` (close to Java regex for
# the common syntax; known divergences: possessive quantifiers and
# \p{...} classes are unsupported and raise at analysis).
# ---------------------------------------------------------------------------

_RE_META = set(".^$*+?{}[]|()\\")


def regex_as_simple(pattern: str):
    """(kind, literal) for patterns expressible as device predicates:
    'eq' (^lit$), 'startswith' (^lit), 'endswith' (lit$), 'contains'
    (bare literal) — else None."""
    if any(ch in _RE_META for ch in
           pattern.replace("^", "", 1).rstrip("$")
           if ch not in "^$") or "\\" in pattern:
        return None
    anchored_l = pattern.startswith("^")
    anchored_r = pattern.endswith("$") and not pattern.endswith("\\$")
    body = pattern[1 if anchored_l else 0:
                   -1 if anchored_r else len(pattern)]
    if any(ch in _RE_META for ch in body):
        return None
    if anchored_l and anchored_r:
        return ("eq", body)
    if anchored_l:
        return ("startswith", body)
    if anchored_r:
        return ("endswith", body)
    return ("contains", body)


def check_regex_supported(pattern: str) -> None:
    """Reject Java-only constructs python `re` would misinterpret:
    possessive quantifiers (a*+) and \\p{...} classes — scanned with
    escape/char-class awareness so '[*+]' or '\\*+' stay legal."""
    import re as _re
    from spark_rapids_tpu.plan.analysis import AnalysisException
    i, in_class = 0, False
    while i < len(pattern):
        ch = pattern[i]
        if ch == "\\":
            if i + 1 < len(pattern) and pattern[i + 1] in "pP":
                raise AnalysisException(
                    f"regex construct \\{pattern[i + 1]}{{...}} "
                    f"(Java-only) is not supported: {pattern!r}")
            i += 2
            continue
        if in_class:
            in_class = ch != "]"
            i += 1
            continue
        if ch == "[":
            in_class = True
        elif (ch in "*+?" and i + 1 < len(pattern)
                and pattern[i + 1] == "+"):
            # '{n}+' needs no special case: re.compile rejects it below
            raise AnalysisException(
                f"possessive quantifier '{ch}+' (Java-only) is not "
                f"supported: {pattern!r}")
        i += 1
    try:
        _re.compile(pattern)
    except _re.error as e:
        raise AnalysisException(f"invalid regex {pattern!r}: {e}")


def _host_to_matrix(data):
    """Host object-array of strings -> (uint8[n, W] matrix, int32[n])."""
    enc = [(v.encode() if isinstance(v, str) else bytes(v))
           for v in data]
    w = max((len(e) for e in enc), default=1) or 1
    mat = np.zeros((len(enc), w), np.uint8)
    lens = np.zeros(len(enc), np.int32)
    for i, e in enumerate(enc):
        mat[i, :len(e)] = np.frombuffer(e, np.uint8)
        lens[i] = len(e)
    return mat, lens


def _matrix_to_host(mat, lens) -> np.ndarray:
    out = np.empty(mat.shape[0], object)
    for i in range(mat.shape[0]):
        out[i] = bytes(mat[i, :int(lens[i])]).decode("utf-8", "replace")
    return out


def _host_regex_apply(data, fn):
    mat, lens = _host_to_matrix(data)
    return fn(mat, lens)


def _has_group_ref(repl: str) -> bool:
    """True when the replacement is NOT a plain literal ($n refs or any
    backslash escaping — those stay on the python re path)."""
    return "\\" in repl or any(
        repl[i] == "$" and i + 1 < len(repl) and repl[i + 1].isdigit()
        for i in range(len(repl)))


@dataclasses.dataclass
class RLike(Expression):
    """Regex match (Java Pattern.find semantics).

    Device path: DFA tables interpreted over the byte matrix
    (ops/regex_device.py — the CudfRegexTranspiler analog); the CPU
    oracle runs the SAME DFA for device-eligible patterns so both
    paths agree byte-for-byte.  Patterns outside the subset stay on
    python ``re`` with a tag reason."""

    child: Expression
    pattern: str
    dtype: T.DataType = dataclasses.field(default_factory=T.BooleanType)

    @property
    def children(self):
        return (self.child,)

    def _rx(self):
        from spark_rapids_tpu.ops.regex_device import compile_regex
        if not hasattr(self, "_rx_cache"):
            object.__setattr__(self, "_rx_cache",
                               compile_regex(self.pattern))
        return self._rx_cache

    def device_support_reason(self, conf):
        if self._rx() is None:
            return (f"regex {self.pattern!r} outside the device DFA "
                    "subset (lazy/possessive quantifiers, backrefs, "
                    "lookaround, \\b, mid-pattern anchors, non-ASCII)")
        return None

    def eval_tpu(self, batch):
        import jax.numpy as jnp
        from spark_rapids_tpu.ops import regex_device as RX
        c = self.child.eval_tpu(batch)
        got = RX.match_any(c.data, c.lengths, self._rx(), jnp)
        return DeviceColumn(self.dtype, got, c.validity)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        rx = self._rx()
        if rx is not None:
            from spark_rapids_tpu.ops import regex_device as RX
            mat, lens = _host_to_matrix(c.data)
            got = RX.match_any(mat, lens, rx, np)
            return HostCol(self.dtype, got, c.validity)
        import re as _re
        crx = _re.compile(self.pattern)
        out = np.fromiter((crx.search(str(v)) is not None for v in c.data),
                          bool, len(c.data))
        return HostCol(self.dtype, out, c.validity)


@dataclasses.dataclass
class RegexpExtract(Expression):
    """regexp_extract: group ``idx`` of the first match, '' if none.

    Device path (idx=0, no alternation): leftmost-longest DFA match +
    substring gather; the CPU oracle runs the same DFA when eligible."""

    child: Expression
    pattern: str
    idx: int
    dtype: T.DataType = dataclasses.field(default_factory=T.StringType)

    @property
    def children(self):
        return (self.child,)

    def _rx(self):
        from spark_rapids_tpu.ops.regex_device import compile_regex
        if not hasattr(self, "_rx_cache"):
            rx = compile_regex(self.pattern)
            if rx is not None and (self.idx != 0 or rx.has_alternation):
                rx = None  # group capture / greedy-vs-longest traps
            object.__setattr__(self, "_rx_cache", rx)
        return self._rx_cache

    def device_support_reason(self, conf):
        if self._rx() is None:
            if self.idx != 0:
                return ("regexp_extract group index > 0 needs capture "
                        "groups — not in the device DFA engine")
            return (f"regex {self.pattern!r} outside the device DFA "
                    "subset (or alternation, where greedy != longest)")
        return None

    def eval_tpu(self, batch):
        import jax.numpy as jnp
        from spark_rapids_tpu.ops import regex_device as RX
        c = self.child.eval_tpu(batch)
        mat, lens, _has = RX.extract_first(c.data, c.lengths, self._rx(),
                                           jnp)
        return DeviceColumn(self.dtype, mat, c.validity, lens)

    def eval_cpu(self, batch):
        rx = self._rx()
        if rx is not None:
            from spark_rapids_tpu.ops import regex_device as RX
            c = self.child.eval_cpu(batch)
            mat, lens = _host_regex_apply(
                c.data, lambda m, ln: RX.extract_first(m, ln, rx, np)[:2])
            return HostCol(self.dtype, _matrix_to_host(mat, lens),
                           c.validity)
        return self._eval_cpu_re(batch)

    def _eval_cpu_re(self, batch):
        import re as _re
        rx = _re.compile(self.pattern)
        c = self.child.eval_cpu(batch)
        out = np.empty(len(c.data), object)
        for i, v in enumerate(c.data):
            m = rx.search(str(v))
            out[i] = (m.group(self.idx) or "") if m else ""
            if out[i] is None:
                out[i] = ""
        return HostCol(self.dtype, out, c.validity)


def _java_repl_to_py(repl: str) -> str:
    """Translate Java's $1 group references to python's \\1."""
    out = []
    i = 0
    while i < len(repl):
        ch = repl[i]
        if ch == "$" and i + 1 < len(repl) and repl[i + 1].isdigit():
            out.append("\\" + repl[i + 1])
            i += 2
            continue
        if ch == "\\" and i + 1 < len(repl):
            out.append("\\\\" if repl[i + 1] == "\\" else repl[i + 1])
            i += 2
            continue
        out.append(ch)
        i += 1
    return "".join(out)


@dataclasses.dataclass
class RegexpReplace(Expression):
    """regexp_replace with Java $n references in the replacement.

    Device path (literal replacement, no alternation, pattern cannot
    match empty): leftmost non-overlapping DFA matches rebuilt through
    prefix-sum byte layout; CPU oracle shares the DFA when eligible."""

    child: Expression
    pattern: str
    replacement: str
    dtype: T.DataType = dataclasses.field(default_factory=T.StringType)

    @property
    def children(self):
        return (self.child,)

    def _rx(self):
        from spark_rapids_tpu.ops.regex_device import compile_regex
        if not hasattr(self, "_rx_cache"):
            rx = compile_regex(self.pattern)
            if rx is not None and (
                    rx.has_alternation or rx.matches_empty
                    or _has_group_ref(self.replacement)
                    or any(ord(ch) > 127 for ch in self.replacement)):
                rx = None
            object.__setattr__(self, "_rx_cache", rx)
        return self._rx_cache

    def device_support_reason(self, conf):
        if self._rx() is None:
            return (f"regexp_replace({self.pattern!r}) outside the "
                    "device DFA subset (alternation, empty-matching "
                    "patterns, $n group references, non-ASCII)")
        return None

    def eval_tpu(self, batch):
        import jax.numpy as jnp
        from spark_rapids_tpu.ops import regex_device as RX
        c = self.child.eval_tpu(batch)
        mat, lens = RX.replace_all(c.data, c.lengths, self._rx(),
                                   self.replacement.encode(), jnp)
        return DeviceColumn(self.dtype, mat, c.validity, lens)

    def eval_cpu(self, batch):
        rx = self._rx()
        if rx is not None:
            from spark_rapids_tpu.ops import regex_device as RX
            c = self.child.eval_cpu(batch)
            mat, lens = _host_regex_apply(
                c.data, lambda m, ln: RX.replace_all(
                    m, ln, rx, self.replacement.encode(), np))
            return HostCol(self.dtype, _matrix_to_host(mat, lens),
                           c.validity)
        return self._eval_cpu_re(batch)

    def _eval_cpu_re(self, batch):
        import re as _re
        rx = _re.compile(self.pattern)
        repl = _java_repl_to_py(self.replacement)
        c = self.child.eval_cpu(batch)
        out = np.empty(len(c.data), object)
        for i, v in enumerate(c.data):
            out[i] = rx.sub(repl, str(v))
        return HostCol(self.dtype, out, c.validity)


@dataclasses.dataclass
class Split(Expression):
    """split(str, regex, limit) → array<string> (host; array<string> has
    no device representation)."""

    child: Expression
    pattern: str
    limit: int = -1

    @property
    def dtype(self):
        return T.ArrayType(T.StringT)

    @property
    def children(self):
        return (self.child,)

    def eval_cpu(self, batch):
        import re as _re
        rx = _re.compile(self.pattern)
        c = self.child.eval_cpu(batch)
        out = np.empty(len(c.data), object)
        for i, v in enumerate(c.data):
            s = str(v)
            if self.limit > 0:
                out[i] = rx.split(s, maxsplit=self.limit - 1)
            else:
                parts = rx.split(s)
                if self.limit == 0:
                    # Java Pattern.split(limit=0) drops trailing empties
                    while parts and parts[-1] == "":
                        parts.pop()
                out[i] = parts
        return HostCol(self.dtype, out, c.validity)


# ---------------------------------------------------------------------------
# reverse / lpad / rpad — device kernels on the byte matrix
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class StringReverse(Expression):
    """Byte-wise reverse (matches Spark for ASCII; multi-byte UTF-8
    sequences reverse bytewise on device — CPU path is char-correct)."""

    child: Expression
    dtype: T.DataType = dataclasses.field(default_factory=T.StringType)
    incompat = "byte-based reverse differs from Spark on non-ASCII"

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        b, w = c.data.shape
        j = jnp.arange(w, dtype=jnp.int32)[None, :]
        src = jnp.clip(c.lengths[:, None] - 1 - j, 0, max(w - 1, 0))
        out = jnp.take_along_axis(c.data, src, axis=1)
        out = jnp.where(j < c.lengths[:, None], out, 0).astype(jnp.uint8)
        return DeviceColumn(self.dtype, out, c.validity, c.lengths)

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        out = np.array([str(v)[::-1] for v in c.data], object)
        return HostCol(self.dtype, out, c.validity)


@dataclasses.dataclass
class StringPad(Expression):
    """lpad/rpad to ``target`` bytes with a cyclic pad string.

    [REF: stringFunctions.scala :: GpuStringLPad/GpuStringRPad] —
    byte-indexed on device (ASCII-exact; the CPU oracle is also
    byte-based so both paths agree)."""

    child: Expression
    target: int
    pad: str
    left: bool
    dtype: T.DataType = dataclasses.field(default_factory=T.StringType)
    incompat = "byte-based padding differs from Spark on non-ASCII"

    @property
    def name(self):
        return "Lpad" if self.left else "Rpad"

    @property
    def children(self):
        return (self.child,)

    def eval_tpu(self, batch):
        c = self.child.eval_tpu(batch)
        b, w = c.data.shape
        L = max(int(self.target), 0)
        width = max(L, 1)
        pad_b = self.pad.encode()
        padv = jnp.asarray(np.frombuffer(pad_b or b"\0", np.uint8))
        plen = max(len(pad_b), 1)
        j = jnp.arange(width, dtype=jnp.int32)[None, :]
        ln = jnp.minimum(c.lengths, L)[:, None]  # kept source bytes
        # empty pad: truncation-only (result = str[:L]); else result is
        # exactly L bytes with the pad cycling through the gap
        out_len = jnp.full((b, 1), L, jnp.int32) if pad_b else ln
        grown = jnp.pad(c.data, ((0, 0), (0, max(width - w, 0)))) \
            if width > w else c.data
        if self.left:
            shift = out_len - ln
            src = jnp.clip(j - shift, 0, grown.shape[1] - 1)
            data_part = jnp.take_along_axis(grown, src, axis=1)
            pad_part = padv[(j % plen).astype(jnp.int32)]
            out = jnp.where(j < shift, pad_part, data_part)
        else:
            data_part = grown[:, :width]
            pad_part = padv[((j - ln) % plen).astype(jnp.int32)]
            out = jnp.where(j < ln, data_part, pad_part)
        out = jnp.where(j < out_len, out, 0).astype(jnp.uint8)
        return DeviceColumn(self.dtype, out, c.validity,
                            out_len[:, 0])

    def eval_cpu(self, batch):
        c = self.child.eval_cpu(batch)
        L = max(int(self.target), 0)
        pad_b = self.pad.encode()
        out = np.empty(len(c.data), object)
        for i, v in enumerate(c.data):
            sb = str(v).encode()
            if len(sb) >= L or not pad_b:
                r = sb[:L]
            else:
                fill = (pad_b * ((L - len(sb)) // len(pad_b) + 1))[
                    :L - len(sb)]
                r = (fill + sb) if self.left else (sb + fill)
            out[i] = r.decode(errors="replace")
        return HostCol(self.dtype, out, c.validity)


# -- TypeSig declarations (see expressions.py) ------------------------------
from spark_rapids_tpu.ops import expressions as E  # noqa: E402

_STR_INT = E.SIG_STRINGY | E.SIG_INTEGRAL
for _cls in (Upper, Lower, Trim, StringReverse, Concat, StringReplace,
             RegexpExtract, RegexpReplace):
    _cls.type_sig = E.SIG_STRINGY
Length.type_sig = E.SIG_INTEGRAL
Length.input_sig = E.SIG_STRINGY
for _cls in (StringComparison, StringPredicate, Like, RLike):
    _cls.type_sig = E.SIG_BOOLEAN
    _cls.input_sig = E.SIG_STRINGY
for _cls in (Substring, StringPad):
    _cls.type_sig = E.SIG_STRINGY
    _cls.input_sig = _STR_INT
StringLocate.type_sig = E.SIG_INTEGRAL
StringLocate.input_sig = _STR_INT
Split.type_sig = frozenset({"array"})
Split.input_sig = E.SIG_STRINGY
