"""Device columnar data model — the ``GpuColumnVector`` analog.

[REF: sql-plugin/../GpuColumnVector.java :: GpuColumnVector,
 RapidsHostColumnVector] — but re-designed TPU-first instead of mirroring
cuDF's pointer-based layout:

* Every device column is a set of **fixed-shape** jax arrays padded to a
  power-of-two row bucket, so each (op, schema, bucket) pair compiles once
  and the XLA executable cache stays hot.  This is THE core TPU-idiom
  decision (SURVEY.md §7): cuDF kernels handle dynamic sizes natively, XLA
  wants static shapes.
* Row liveness is a boolean ``sel`` mask on the batch (covers both padding
  and not-yet-compacted filter results).  Data-dependent row counts never
  escape into shapes; compaction happens at deliberate points (shuffle,
  join build, host transfer) via a stable sort on the mask.
* Strings/binary are padded byte matrices ``uint8[B, W]`` + ``lengths
  int32[B]`` rather than cuDF's offset+chars layout — irregular layouts are
  hostile to the MXU/VPU; a padded matrix vectorizes substring/compare/hash.
* Decimals (precision <= 18) are scaled int64.
* Null validity is a separate ``bool[B]`` mask (True = valid), independent
  of ``sel``.

Host representation is a ``pyarrow.Table`` — the host mirror / transfer
format (the JCudf/host-column analog), and what the CPU-fallback operators
consume.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.runtime.device import ensure_initialized


def round_up_pow2(n: int, min_bucket: int = 1024) -> int:
    """Row bucket for n rows: next power of two, floored at min_bucket."""
    b = max(int(min_bucket), 1)
    while b < n:
        b <<= 1
    return b


@dataclasses.dataclass
class DeviceColumn:
    """One SQL column on device.

    data:     jnp array [B] (fixed width types) or uint8 [B, W]
              (string/binary) or elem[B, W] (array<numeric>)
    validity: jnp bool [B], True = valid; None = all valid
    lengths:  jnp int32 [B] for string/binary/array; None otherwise
    evalid:   jnp bool [B, W] element validity for array columns whose
              elements may be null; None = all elements valid
    """

    dtype: T.DataType
    data: jax.Array
    validity: Optional[jax.Array] = None
    lengths: Optional[jax.Array] = None
    evalid: Optional[jax.Array] = None

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    @property
    def is_string(self) -> bool:
        return self.lengths is not None

    def valid_mask(self) -> jax.Array:
        if self.validity is None:
            return jnp.ones((self.capacity,), dtype=jnp.bool_)
        return self.validity

    def with_validity(self, validity: Optional[jax.Array]) -> "DeviceColumn":
        return DeviceColumn(self.dtype, self.data, validity, self.lengths,
                            self.evalid)

    def gather(self, idx: jax.Array) -> "DeviceColumn":
        """Row gather (used by compaction, sort, join)."""
        data = jnp.take(self.data, idx, axis=0)
        validity = None if self.validity is None else jnp.take(self.validity, idx)
        lengths = None if self.lengths is None else jnp.take(self.lengths, idx)
        evalid = None if self.evalid is None else jnp.take(
            self.evalid, idx, axis=0)
        return DeviceColumn(self.dtype, data, validity, lengths, evalid)

    def nbytes(self) -> int:
        n = self.data.size * self.data.dtype.itemsize
        if self.validity is not None:
            n += self.validity.size
        if self.lengths is not None:
            n += self.lengths.size * 4
        if self.evalid is not None:
            n += self.evalid.size
        return n


def _col_flatten(c: DeviceColumn):
    return (c.data, c.validity, c.lengths, c.evalid), c.dtype


def _col_unflatten(dtype, children):
    data, validity, lengths, evalid = children
    return DeviceColumn(dtype, data, validity, lengths, evalid)


jax.tree_util.register_pytree_node(DeviceColumn, _col_flatten, _col_unflatten)


@dataclasses.dataclass
class DeviceBatch:
    """A columnar batch on device — the ``ColumnarBatch`` of this engine.

    columns are positional; ``schema`` carries names/types (static metadata).
    ``sel`` is the live-row mask: padding rows and filtered-out rows are
    False.  All operators consume/produce ``sel`` instead of changing shapes.

    ``compacted`` (static metadata) promises live rows sit at the front
    (sel == arange < n) — lets consumers skip the compaction kernel.
    """

    schema: T.StructType
    columns: Tuple[DeviceColumn, ...]
    sel: jax.Array  # bool[B]
    compacted: bool = False

    @property
    def capacity(self) -> int:
        return int(self.sel.shape[0])

    def num_rows(self) -> jax.Array:
        """Live row count (device scalar)."""
        return jnp.sum(self.sel.astype(jnp.int32))

    def num_rows_host(self) -> int:
        return int(self.num_rows())

    def column(self, i: int) -> DeviceColumn:
        return self.columns[i]

    def column_by_name(self, name: str) -> DeviceColumn:
        return self.columns[self.schema.field_index(name)]

    def with_columns(self, cols, schema=None) -> "DeviceBatch":
        return DeviceBatch(schema or self.schema, tuple(cols), self.sel)

    def with_sel(self, sel: jax.Array) -> "DeviceBatch":
        return DeviceBatch(self.schema, self.columns, sel)

    def nbytes(self) -> int:
        return sum(c.nbytes() for c in self.columns) + self.sel.size


def _batch_flatten(b: DeviceBatch):
    return (b.columns, b.sel), (b.schema, b.compacted)


def _batch_unflatten(aux, children):
    columns, sel = children
    schema, compacted = aux
    return DeviceBatch(schema, tuple(columns), sel, compacted)


jax.tree_util.register_pytree_node(DeviceBatch, _batch_flatten, _batch_unflatten)


# ---------------------------------------------------------------------------
# Compaction: gather live rows to the front (stable).  The deliberate
# dynamic→static boundary; called before shuffle/join-build/host transfer.
# ---------------------------------------------------------------------------

def _compact_impl(batch: DeviceBatch) -> DeviceBatch:
    # Stable argsort on "dead" flag moves live rows to the front preserving
    # order.  One lax.sort; vectorizes fine on TPU.
    from spark_rapids_tpu.shims import get_shim
    order = get_shim().stable_argsort((~batch.sel).astype(jnp.int8))
    cols = tuple(c.gather(order) for c in batch.columns)
    count = jnp.sum(batch.sel.astype(jnp.int32))
    sel = jnp.arange(batch.capacity, dtype=jnp.int32) < count
    return DeviceBatch(batch.schema, cols, sel, compacted=True)


def compact(batch: DeviceBatch) -> DeviceBatch:
    if batch.compacted:
        return batch
    from spark_rapids_tpu.runtime.kernel_cache import (
        cached_kernel, fingerprint)
    return cached_kernel(("compact", fingerprint(batch.schema)),
                         lambda: _compact_impl)(batch)


# ---------------------------------------------------------------------------
# Host (pyarrow) <-> device conversion — the Row/ColumnarToRow analog pair
# [REF: GpuRowToColumnarExec.scala, GpuColumnarToRowExec.scala]
# ---------------------------------------------------------------------------

def _string_to_matrix(arr: pa.Array) -> Tuple[np.ndarray, np.ndarray]:
    """Arrow string/binary array -> (uint8[B,W] matrix, int32 lengths)."""
    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    if pa.types.is_large_string(arr.type) or pa.types.is_large_binary(arr.type):
        arr = arr.cast(pa.string() if pa.types.is_large_string(arr.type) else pa.binary())
    n = len(arr)
    # offsets/data straight from arrow buffers; nulls handled via validity
    buffers = arr.buffers()
    offs = np.frombuffer(buffers[1], dtype=np.int32, count=n + 1, offset=arr.offset * 4)
    data = np.frombuffer(buffers[2], dtype=np.uint8) if buffers[2] is not None else np.zeros(0, np.uint8)
    lengths = (offs[1:] - offs[:-1]).astype(np.int32)
    null_mask = np.asarray(arr.is_null())
    lengths = np.where(null_mask, 0, lengths).astype(np.int32)
    w = round_up_pow2(int(lengths.max()) if n else 1, 8)
    mat = np.zeros((n, w), dtype=np.uint8)
    total = int(lengths.sum())
    if total:
        starts = np.concatenate([[0], np.cumsum(lengths)[:-1]]).astype(np.int64)
        row_idx = np.repeat(np.arange(n), lengths)
        col_idx = np.arange(total) - np.repeat(starts, lengths)
        src_pos = np.repeat(offs[:-1].astype(np.int64), lengths) + col_idx
        mat[row_idx, col_idx] = data[src_pos]
    return mat, lengths


def _matrix_to_string(mat: np.ndarray, lengths: np.ndarray,
                      validity: Optional[np.ndarray], binary: bool) -> pa.Array:
    n, w = mat.shape
    lengths = lengths.astype(np.int64)
    col = np.arange(w)[None, :]
    mask2d = col < lengths[:, None]
    flat = mat[mask2d]
    offsets = np.zeros(n + 1, dtype=np.int32)
    np.cumsum(lengths, out=offsets[1:])
    typ = pa.binary() if binary else pa.string()
    null_buf = None
    if validity is not None and not validity.all():
        null_buf = pa.py_buffer(np.packbits(validity, bitorder="little").tobytes())
    return pa.Array.from_buffers(
        typ, n,
        [null_buf, pa.py_buffer(offsets.tobytes()), pa.py_buffer(flat.tobytes())],
        null_count=-1 if null_buf is not None else 0,
    )


def _list_to_matrix(arr: pa.Array, elem_dt: T.DataType):
    """Arrow list array -> (elem[B, W] padded matrix, int32 lengths,
    optional bool[B, W] element validity)."""
    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    if pa.types.is_large_list(arr.type):
        arr = arr.cast(pa.list_(arr.type.value_type))
    n = len(arr)
    offs = np.asarray(arr.offsets)
    lengths = (offs[1:] - offs[:-1]).astype(np.int32)
    lengths = np.where(np.asarray(arr.is_null()), 0, lengths)
    npdt = T.to_numpy_dtype(elem_dt)
    fill = pa.scalar(False if isinstance(elem_dt, T.BooleanType) else 0,
                     type=arr.type.value_type)
    values = np.asarray(arr.values.fill_null(fill)).astype(npdt, copy=False)
    evalues = None
    if arr.values.null_count:
        evalues = ~np.asarray(arr.values.is_null())
    w = round_up_pow2(int(lengths.max()) if n else 1, 1)
    mat = np.zeros((n, w), dtype=npdt)
    emask = None if evalues is None else np.ones((n, w), dtype=bool)
    total = int(lengths.sum())
    if total:
        row_idx = np.repeat(np.arange(n), lengths)
        col_idx = (np.arange(total)
                   - np.repeat(np.cumsum(lengths) - lengths, lengths))
        src = (np.repeat(offs[:-1].astype(np.int64), lengths)
               + col_idx)
        mat[row_idx, col_idx] = values[src]
        if emask is not None:
            emask[row_idx, col_idx] = evalues[src]
    return mat, lengths, emask


def _decimal_to_int64(arr: pa.Array) -> np.ndarray:
    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    buf = arr.buffers()[1]
    raw = np.frombuffer(buf, dtype=np.int64, count=2 * len(arr),
                        offset=arr.offset * 16)
    low, high = raw[0::2].copy(), raw[1::2]
    # precision<=18 fits in the low limb; high must be sign extension
    if not np.array_equal(high, low >> 63):
        raise OverflowError("decimal value exceeds 18 digits")
    return low


def _decimal_to_hilo(arr: pa.Array) -> np.ndarray:
    """decimal128 arrow column -> int64[n, 2] (hi, lo bit patterns)."""
    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    buf = arr.buffers()[1]
    raw = np.frombuffer(buf, dtype=np.int64, count=2 * len(arr),
                        offset=arr.offset * 16)
    out = np.empty((len(arr), 2), dtype=np.int64)
    out[:, 0] = raw[1::2]  # hi
    out[:, 1] = raw[0::2]  # lo (bit pattern)
    return out


def arrow_column_to_device(arr, dt: T.DataType) -> DeviceColumn:
    ensure_initialized()
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if pa.types.is_dictionary(arr.type) and (
            not isinstance(dt, (T.StringType, T.BinaryType))
            or len(arr.dictionary) == 0
            or arr.dictionary.null_count > 0):
        # device dict decode handles only string dictionaries with no
        # null VALUES (index-level nulls are fine); everything else
        # decodes to plain first — is_null() on a DictionaryArray does
        # NOT see nulls stored in the dictionary values
        arr = arr.cast(arr.type.value_type)
    null_mask = np.asarray(arr.is_null())
    validity_np = ~null_mask if null_mask.any() else None

    if (pa.types.is_dictionary(arr.type)
            and isinstance(dt, (T.StringType, T.BinaryType))):
        # device dictionary DECODE [REF: SURVEY N6 phase-2]: transfer
        # int32 indices + the (small) dictionary byte matrix and expand
        # with a device gather — H2D bytes drop from n*W to n*4 + D*W
        idx = np.asarray(arr.indices.fill_null(0)).astype(np.int32)
        dmat, dlens = _string_to_matrix(arr.dictionary)
        d_idx = jnp.asarray(idx)
        data = jnp.take(jnp.asarray(dmat), d_idx, axis=0)
        lengths = jnp.take(jnp.asarray(dlens), d_idx)
        return DeviceColumn(
            dt, data,
            None if validity_np is None else jnp.asarray(validity_np),
            lengths)

    if isinstance(dt, (T.StringType, T.BinaryType)):
        mat, lengths = _string_to_matrix(arr)
        return DeviceColumn(
            dt, jnp.asarray(mat),
            None if validity_np is None else jnp.asarray(validity_np),
            jnp.asarray(lengths),
        )
    if isinstance(dt, T.ArrayType):
        # padded element matrix [B, W] + lengths — the same layout
        # collect_list produces and Generate/explode consumes.  Element
        # nulls ride in an optional [B, W] evalid plane.
        mat, lengths, emask = _list_to_matrix(arr, dt.element_type)
        return DeviceColumn(
            dt, jnp.asarray(mat),
            None if validity_np is None else jnp.asarray(validity_np),
            jnp.asarray(lengths),
            None if emask is None else jnp.asarray(emask),
        )
    if isinstance(dt, T.DecimalType):
        if dt.precision > T.DecimalType.MAX_LONG_DIGITS:
            data = _decimal_to_hilo(arr)
            data[null_mask] = 0
            return DeviceColumn(
                dt, jnp.asarray(data),
                None if validity_np is None else jnp.asarray(validity_np))
        data = _decimal_to_int64(arr)
        data = np.where(null_mask, 0, data)
    else:
        npdt = T.to_numpy_dtype(dt)
        if isinstance(dt, T.DateType):
            if not pa.types.is_date32(arr.type):
                arr = arr.cast(pa.date32())
            casted = arr.cast(pa.int32())
        elif isinstance(dt, T.TimestampType):
            # normalize any unit/tz to the device rep: micros since epoch UTC
            if arr.type.unit != "us":
                arr = arr.cast(pa.timestamp("us", tz=arr.type.tz))
            casted = arr.cast(pa.int64())
        elif isinstance(dt, T.BooleanType):
            casted = arr.cast(pa.int8())
        else:
            casted = arr
        data = np.asarray(casted.fill_null(0))
        if isinstance(dt, T.BooleanType):
            data = data.astype(np.bool_)
        data = data.astype(npdt, copy=False)
    return DeviceColumn(
        dt, jnp.asarray(data),
        None if validity_np is None else jnp.asarray(validity_np),
    )


def _pad_col(c: DeviceColumn, bucket: int) -> DeviceColumn:
    n = c.capacity
    if n == bucket:
        return c
    pad = bucket - n
    if c.data.ndim == 2:
        data = jnp.pad(c.data, ((0, pad), (0, 0)))
    else:
        data = jnp.pad(c.data, (0, pad))
    validity = c.validity
    if validity is not None:
        validity = jnp.pad(validity, (0, pad))
    lengths = c.lengths
    if lengths is not None:
        lengths = jnp.pad(lengths, (0, pad))
    evalid = c.evalid
    if evalid is not None:
        evalid = jnp.pad(evalid, ((0, pad), (0, 0)), constant_values=True)
    return DeviceColumn(c.dtype, data, validity, lengths, evalid)


def pad_batch(batch: DeviceBatch, capacity: int) -> DeviceBatch:
    """Grow a batch's row capacity (pad rows are dead)."""
    if batch.capacity >= capacity:
        return batch
    cols = tuple(_pad_col(c, capacity) for c in batch.columns)
    sel = jnp.pad(batch.sel, (0, capacity - batch.capacity))
    return DeviceBatch(batch.schema, cols, sel)


def host_to_device(table: pa.Table, bucket: Optional[int] = None,
                   min_bucket: int = 1024) -> DeviceBatch:
    """pyarrow.Table -> padded DeviceBatch."""
    n = table.num_rows
    b = bucket or round_up_pow2(max(n, 1), min_bucket)
    fields = []
    cols = []
    for name, col in zip(table.column_names, table.columns):
        dt = T.from_arrow(col.type)
        dc = arrow_column_to_device(col, dt)
        cols.append(_pad_col(dc, b))
        fields.append(T.StructField(name, dt))
    sel = jnp.arange(b, dtype=jnp.int32) < n
    return DeviceBatch(T.StructType(tuple(fields)), tuple(cols), sel)


def device_to_host(batch: DeviceBatch, already_compact: bool = False) -> pa.Table:
    """DeviceBatch -> pyarrow.Table (compacts first).

    The ``transfer`` failure domain wraps the WHOLE transfer body, so a
    transient injected fault retries the actual D2H — the recovery the
    shim exists to prove [REF: faultinj analog, N15].  Retry exhaustion
    degrades to the plain synchronous pull path (no overlapped async
    prefetch)."""
    from spark_rapids_tpu.runtime import resilience as R
    if R.active():
        def attempt():
            R.INJECTOR.on("transfer")
            return _device_to_host_impl(batch, already_compact)

        def degrade():
            return _device_to_host_impl(batch, already_compact,
                                        prefetch=False)

        return R.run_guarded("transfer", attempt, op="device_to_host",
                             degrade=degrade)
    return _device_to_host_impl(batch, already_compact)


def _device_to_host_impl(batch: DeviceBatch, already_compact: bool,
                         prefetch: bool = True) -> pa.Table:
    """All device buffers are pulled with ONE overlapped transfer round
    trip: sequential ``np.asarray`` pulls cost a full device round trip
    EACH (measured ~40-90 ms per pull through the axon tunnel), so every
    buffer is prefetched with ``copy_to_host_async`` first and the row
    count comes from the host copy of ``sel`` instead of a device
    reduction."""
    if not already_compact:
        batch = compact(batch)
    bufs = [batch.sel]
    for c in batch.columns:
        bufs.append(c.data)
        if c.validity is not None:
            bufs.append(c.validity)
        if c.lengths is not None:
            bufs.append(c.lengths)
        if c.evalid is not None:
            bufs.append(c.evalid)
    if prefetch:
        from spark_rapids_tpu.shims import get_shim
        shim = get_shim()
        for b in bufs:
            if not shim.async_copy_to_host(b):
                break
    n = int(np.count_nonzero(np.asarray(batch.sel)))
    arrays = []
    names = []
    for f, c in zip(batch.schema.fields, batch.columns):
        names.append(f.name)
        validity = None
        if c.validity is not None:
            validity = np.asarray(c.validity)[:n]
        if isinstance(f.dtype, T.ArrayType):
            # padded element matrix [B, L] + lengths → arrow list array
            mat = np.asarray(c.data)[:n]
            lengths = np.asarray(c.lengths)[:n].astype(np.int64)
            offsets = np.zeros(n + 1, np.int32)
            np.cumsum(lengths, out=offsets[1:])
            total = int(offsets[-1])
            emask_flat = None
            if total:
                ii = np.repeat(np.arange(n), lengths)
                jj = (np.arange(total)
                      - np.repeat(offsets[:-1].astype(np.int64), lengths))
                values = mat[ii, jj]
                if c.evalid is not None:
                    emask_flat = ~np.asarray(c.evalid)[:n][ii, jj]
            else:
                values = np.zeros(0, mat.dtype)
            elem = pa.array(values,
                            type=T.to_arrow(f.dtype.element_type),
                            mask=emask_flat)
            arr = pa.ListArray.from_arrays(pa.array(offsets), elem)
            if validity is not None and not validity.all():
                arr = pa.ListArray.from_arrays(
                    pa.array(offsets), elem,
                    mask=pa.array(~validity))
            arrays.append(arr)
            continue
        if c.is_string:
            mat = np.asarray(c.data)[:n]
            lengths = np.asarray(c.lengths)[:n]
            arrays.append(_matrix_to_string(
                mat, lengths, validity, isinstance(f.dtype, T.BinaryType)))
            continue
        data = np.asarray(c.data)[:n]
        if isinstance(f.dtype, T.DecimalType):
            # build decimal128 buffers directly: 16-byte little-endian
            # two's complement = (low=int64 unscaled, high=sign extension
            # for <=18; real hi lane for decimal128)
            raw = np.empty(2 * n, dtype=np.int64)
            if data.ndim == 2:
                raw[0::2] = data[:, 1]
                raw[1::2] = data[:, 0]
            else:
                low = data.astype(np.int64)
                raw[0::2] = low
                raw[1::2] = low >> 63
            null_buf = None
            if validity is not None and not validity.all():
                null_buf = pa.py_buffer(
                    np.packbits(validity, bitorder="little").tobytes())
            arrays.append(pa.Array.from_buffers(
                T.to_arrow(f.dtype), n,
                [null_buf, pa.py_buffer(raw.tobytes())],
                null_count=-1 if null_buf is not None else 0))
            continue
        if isinstance(f.dtype, T.DateType):
            base = pa.array(data.astype(np.int32), type=pa.int32())
            arr = base.cast(pa.date32())
        elif isinstance(f.dtype, T.TimestampType):
            base = pa.array(data.astype(np.int64), type=pa.int64())
            arr = base.cast(pa.timestamp("us", tz="UTC"))
        else:
            arr = pa.array(data, type=T.to_arrow(f.dtype))
        if validity is not None and not validity.all():
            arr = pa.Array.from_buffers(
                arr.type, n,
                [pa.py_buffer(np.packbits(validity, bitorder="little").tobytes())]
                + list(arr.buffers()[1:]),
                null_count=-1,
            )
        arrays.append(arr)
    return pa.table(arrays, names=names)


def empty_batch(schema: T.StructType, bucket: int = 1024) -> DeviceBatch:
    ensure_initialized()
    cols = []
    for f in schema.fields:
        if isinstance(f.dtype, (T.StringType, T.BinaryType)):
            cols.append(DeviceColumn(
                f.dtype, jnp.zeros((bucket, 8), jnp.uint8),
                None, jnp.zeros((bucket,), jnp.int32)))
        elif (isinstance(f.dtype, T.DecimalType)
              and f.dtype.precision > T.DecimalType.MAX_LONG_DIGITS):
            cols.append(DeviceColumn(
                f.dtype, jnp.zeros((bucket, 2), jnp.int64)))
        else:
            npdt = T.to_numpy_dtype(f.dtype)
            cols.append(DeviceColumn(f.dtype, jnp.zeros((bucket,), npdt)))
    sel = jnp.zeros((bucket,), jnp.bool_)
    return DeviceBatch(schema, tuple(cols), sel)
