"""Spark-compatible SQL type system.

Mirrors Spark's ``org.apache.spark.sql.types`` lattice as consumed by the
reference's TypeSig machinery [REF: sql-plugin/../TypeChecks.scala :: TypeSig].
Physical mapping is TPU-first: every type maps to fixed-width device arrays
(strings become padded uint8 byte matrices; decimals become scaled int64 —
see ``columnar/column.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class DataType:
    """Base class for SQL data types."""

    @property
    def simple_name(self) -> str:
        return type(self).__name__.replace("Type", "").lower()

    def __str__(self) -> str:
        return self.simple_name


@dataclasses.dataclass(frozen=True)
class NumericType(DataType):
    pass


@dataclasses.dataclass(frozen=True)
class IntegralType(NumericType):
    pass


@dataclasses.dataclass(frozen=True)
class FractionalType(NumericType):
    pass


@dataclasses.dataclass(frozen=True)
class BooleanType(DataType):
    pass


@dataclasses.dataclass(frozen=True)
class ByteType(IntegralType):
    pass


@dataclasses.dataclass(frozen=True)
class ShortType(IntegralType):
    pass


@dataclasses.dataclass(frozen=True)
class IntegerType(IntegralType):
    pass


@dataclasses.dataclass(frozen=True)
class LongType(IntegralType):
    pass


@dataclasses.dataclass(frozen=True)
class FloatType(FractionalType):
    pass


@dataclasses.dataclass(frozen=True)
class DoubleType(FractionalType):
    pass


@dataclasses.dataclass(frozen=True)
class StringType(DataType):
    pass


@dataclasses.dataclass(frozen=True)
class BinaryType(DataType):
    pass


@dataclasses.dataclass(frozen=True)
class DateType(DataType):
    """Days since unix epoch, int32 on device (matches Spark physical rep)."""


@dataclasses.dataclass(frozen=True)
class TimestampType(DataType):
    """Microseconds since unix epoch UTC, int64 on device."""


@dataclasses.dataclass(frozen=True)
class DecimalType(FractionalType):
    """Exact decimal.  Device rep: scaled int64 for precision <= 18.

    precision > 18 (DECIMAL128) is represented as two int64 limbs — tracked
    but not yet enabled in TypeSig (mirrors the reference's staged decimal
    support [REF: spark-rapids-jni :: decimal128 kernels]).
    """

    precision: int = 10
    scale: int = 0

    MAX_PRECISION = 38
    MAX_LONG_DIGITS = 18

    @property
    def simple_name(self) -> str:
        return f"decimal({self.precision},{self.scale})"


@dataclasses.dataclass(frozen=True)
class NullType(DataType):
    pass


@dataclasses.dataclass(frozen=True)
class ArrayType(DataType):
    element_type: DataType = dataclasses.field(default_factory=NullType)
    contains_null: bool = True

    @property
    def simple_name(self) -> str:
        return f"array<{self.element_type.simple_name}>"


@dataclasses.dataclass(frozen=True)
class StructField:
    name: str
    dtype: DataType
    nullable: bool = True


@dataclasses.dataclass(frozen=True)
class StructType(DataType):
    fields: tuple = ()

    @property
    def simple_name(self) -> str:
        inner = ",".join(f"{f.name}:{f.dtype.simple_name}" for f in self.fields)
        return f"struct<{inner}>"

    def field_names(self):
        return [f.name for f in self.fields]

    def field_index(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def __len__(self):
        return len(self.fields)

    def add(self, name, dtype, nullable=True) -> "StructType":
        return StructType(self.fields + (StructField(name, dtype, nullable),))


@dataclasses.dataclass(frozen=True)
class MapType(DataType):
    key_type: DataType = dataclasses.field(default_factory=NullType)
    value_type: DataType = dataclasses.field(default_factory=NullType)

    @property
    def simple_name(self) -> str:
        return f"map<{self.key_type.simple_name},{self.value_type.simple_name}>"


# Singletons (Spark style)
BooleanT = BooleanType()
ByteT = ByteType()
ShortT = ShortType()
IntegerT = IntegerType()
LongT = LongType()
FloatT = FloatType()
DoubleT = DoubleType()
StringT = StringType()
BinaryT = BinaryType()
DateT = DateType()
TimestampT = TimestampType()
NullT = NullType()


_NUMPY_MAP = {
    BooleanType: np.bool_,
    ByteType: np.int8,
    ShortType: np.int16,
    IntegerType: np.int32,
    LongType: np.int64,
    FloatType: np.float32,
    DoubleType: np.float64,
    DateType: np.int32,
    TimestampType: np.int64,
}


def to_numpy_dtype(dt: DataType):
    """Physical numpy/device dtype for a SQL type's data buffer."""
    if isinstance(dt, DecimalType):
        # precision <= 18: scaled int64 [B]; > 18 (decimal128): two
        # int64 lanes [B, 2] (hi, lo) — see ops/decimal128.py
        return np.int64
    if isinstance(dt, (StringType, BinaryType)):
        return np.uint8  # byte-matrix payload
    t = _NUMPY_MAP.get(type(dt))
    if t is None:
        raise NotImplementedError(f"no physical dtype for {dt}")
    return t


def is_integral(dt: DataType) -> bool:
    return isinstance(dt, IntegralType)


def is_numeric(dt: DataType) -> bool:
    return isinstance(dt, NumericType)


def is_string(dt: DataType) -> bool:
    return isinstance(dt, StringType)


def is_orderable(dt: DataType) -> bool:
    return isinstance(
        dt,
        (NumericType, BooleanType, StringType, DateType, TimestampType),
    )


def numeric_widest(a: DataType, b: DataType) -> DataType:
    """Spark's findTightestCommonType for numeric binary ops (simplified)."""
    order = [ByteType, ShortType, IntegerType, LongType, FloatType, DoubleType]
    if isinstance(a, DecimalType) or isinstance(b, DecimalType):
        # Decimal promotion handled by the caller (operation-specific rules).
        raise NotImplementedError
    ia = order.index(type(a))
    ib = order.index(type(b))
    return (a, b)[ia < ib]


def from_arrow(at) -> DataType:
    """Map a pyarrow DataType to our SQL type."""
    import pyarrow as pa

    if pa.types.is_boolean(at):
        return BooleanT
    if pa.types.is_int8(at):
        return ByteT
    if pa.types.is_int16(at):
        return ShortT
    if pa.types.is_int32(at):
        return IntegerT
    if pa.types.is_int64(at):
        return LongT
    if pa.types.is_float32(at):
        return FloatT
    if pa.types.is_float64(at):
        return DoubleT
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return StringT
    if pa.types.is_binary(at) or pa.types.is_large_binary(at):
        return BinaryT
    if pa.types.is_date32(at):
        return DateT
    if pa.types.is_timestamp(at):
        return TimestampT
    if pa.types.is_decimal(at):
        return DecimalType(at.precision, at.scale)
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return ArrayType(from_arrow(at.value_type))
    if pa.types.is_struct(at):
        return StructType(
            tuple(StructField(f.name, from_arrow(f.type)) for f in at)
        )
    if pa.types.is_dictionary(at):
        # dictionary-encoded columns carry their VALUE type (the
        # encoding is a physical detail the device decode unwraps)
        return from_arrow(at.value_type)
    raise NotImplementedError(f"arrow type {at}")


def to_arrow(dt: DataType):
    import pyarrow as pa

    m = {
        BooleanType: pa.bool_(),
        ByteType: pa.int8(),
        ShortType: pa.int16(),
        IntegerType: pa.int32(),
        LongType: pa.int64(),
        FloatType: pa.float32(),
        DoubleType: pa.float64(),
        StringType: pa.string(),
        BinaryType: pa.binary(),
        DateType: pa.date32(),
    }
    if isinstance(dt, TimestampType):
        return pa.timestamp("us", tz="UTC")
    if isinstance(dt, DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, ArrayType):
        return pa.list_(to_arrow(dt.element_type))
    if isinstance(dt, StructType):
        return pa.struct([pa.field(f.name, to_arrow(f.dtype)) for f in dt.fields])
    t = m.get(type(dt))
    if t is None:
        raise NotImplementedError(f"arrow mapping for {dt}")
    return t
