"""Host column representation for the CPU fallback/oracle path.

The CPU analog of DeviceColumn: numpy data + validity mask.  CPU execs
evaluate expressions over these (the reference's CPU path is vanilla Spark;
here the CPU path is the from-spec numpy interpreter that doubles as the
correctness oracle in tests).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.columnar import dtypes as T


@dataclasses.dataclass
class HostCol:
    dtype: T.DataType
    data: np.ndarray            # object array for strings on host
    validity: Optional[np.ndarray] = None  # bool, True = valid; None = all

    def valid_mask(self, n=None) -> np.ndarray:
        if self.validity is None:
            return np.ones(len(self.data) if n is None else n, dtype=bool)
        return self.validity

    def __len__(self):
        return len(self.data)


@dataclasses.dataclass
class HostBatch:
    schema: T.StructType
    columns: List[HostCol]

    @property
    def num_rows(self):
        return len(self.columns[0]) if self.columns else 0


def from_arrow_table(tbl: pa.Table) -> HostBatch:
    fields = []
    cols = []
    for name, col in zip(tbl.column_names, tbl.columns):
        dt = T.from_arrow(col.type)
        fields.append(T.StructField(name, dt))
        cols.append(from_arrow_column(col, dt))
    return HostBatch(T.StructType(tuple(fields)), cols)


def from_arrow_column(col, dt: T.DataType) -> HostCol:
    if isinstance(col, pa.ChunkedArray):
        col = col.combine_chunks()
    nulls = np.asarray(col.is_null())
    validity = ~nulls if nulls.any() else None
    if isinstance(dt, T.ArrayType):
        data = np.empty(len(col), dtype=object)
        for i, v in enumerate(col.to_pylist()):
            data[i] = v if v is not None else []
        return HostCol(dt, data, validity)
    if isinstance(dt, (T.StringType, T.BinaryType)):
        data = np.array(
            ["" if v is None else v for v in col.to_pylist()], dtype=object)
    elif isinstance(dt, T.DecimalType):
        if dt.precision > T.DecimalType.MAX_LONG_DIGITS:
            # host rep for decimal128: object array of exact python
            # ints (unscaled) — CPU-oracle arithmetic stays bit-exact
            c = (col.combine_chunks()
                 if isinstance(col, pa.ChunkedArray) else col)
            from spark_rapids_tpu.ops.decimal128 import py_unscaled
            data = np.empty(len(c), dtype=object)
            for i, v in enumerate(c.to_pylist()):
                data[i] = 0 if v is None else py_unscaled(v, dt.scale)
        else:
            from spark_rapids_tpu.columnar.column import _decimal_to_int64
            data = np.where(nulls, 0, _decimal_to_int64(col))
    elif isinstance(dt, T.DateType):
        data = np.asarray(col.cast(pa.date32()).cast(pa.int32()).fill_null(0))
    elif isinstance(dt, T.TimestampType):
        c = col
        if c.type.unit != "us":
            c = c.cast(pa.timestamp("us", tz=c.type.tz))
        data = np.asarray(c.cast(pa.int64()).fill_null(0))
    elif isinstance(dt, T.BooleanType):
        data = np.asarray(col.cast(pa.int8()).fill_null(0)).astype(bool)
    else:
        data = np.asarray(col.fill_null(0)).astype(T.to_numpy_dtype(dt))
    return HostCol(dt, data, validity)


def to_arrow_table(batch: HostBatch) -> pa.Table:
    arrays = []
    for f, c in zip(batch.schema.fields, batch.columns):
        arrays.append(to_arrow_column(c))
    return pa.table(arrays, names=[f.name for f in batch.schema.fields])


def to_arrow_column(c: HostCol) -> pa.Array:
    n = len(c.data)
    mask = None
    if c.validity is not None:
        mask = ~c.validity
    if isinstance(c.dtype, T.ArrayType):
        vals = [None if (mask is not None and mask[i]) else list(c.data[i])
                for i in range(n)]
        return pa.array(vals, type=T.to_arrow(c.dtype))
    if isinstance(c.dtype, (T.StringType, T.BinaryType)):
        vals = [None if (mask is not None and mask[i]) else c.data[i]
                for i in range(n)]
        return pa.array(vals, type=T.to_arrow(c.dtype))
    if isinstance(c.dtype, T.DecimalType):
        import decimal as _d
        vals = [None if (mask is not None and mask[i])
                else _d.Decimal(int(c.data[i])).scaleb(-c.dtype.scale)
                for i in range(n)]
        return pa.array(vals, type=T.to_arrow(c.dtype))
    if isinstance(c.dtype, T.DateType):
        arr = pa.array(c.data.astype(np.int32), type=pa.int32(),
                       mask=mask).cast(pa.date32())
        return arr
    if isinstance(c.dtype, T.TimestampType):
        return pa.array(c.data.astype(np.int64), type=pa.int64(),
                        mask=mask).cast(pa.timestamp("us", tz="UTC"))
    return pa.array(c.data, type=T.to_arrow(c.dtype), mask=mask)
