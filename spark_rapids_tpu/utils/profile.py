"""Regression-diff profiler CLI over the engine's profile artifacts.

[REF: the reference ships a qualification/profiling tool that
post-processes event logs into per-query analyses and compares runs] —
this is that tool for this engine's three artifact kinds, auto-detected
per file:

* **profile store** (``spark.rapids.tpu.stats.storePath``): one JSONL
  record per query from the stats plane — per-op observed rows/bytes +
  traced self-time keyed by STABLE plan-node signatures, plus the
  exchange skew summary;
* **query event log** (``spark.rapids.sql.queryLog``): JSONL entries
  whose ``op_rollup``/``op_stats``/``telemetry`` fields carry the same
  signals (plus compile counters for the storm report);
* **bench scoreboard** (``BENCH_*.json``): one JSON object whose
  ``tpch_sf1_op_rollup``/``tpch_sf1_stats`` maps key per-op records by
  query name, plus the ``tpch_sf1_compile`` cold-vs-warm compile split
  the ``storms`` report reads;
* **black box** (``query-<id>.blackbox.json``): a single flight-
  recorder dump left by a query that died (timeout/cancel/error) —
  ``why`` renders its ledger, verdict and final ring events.

Usage::

    python -m spark_rapids_tpu.utils.profile top    <input> [--n N]
        [--adaptive] [--cache]
    python -m spark_rapids_tpu.utils.profile why    <input>
        [--query Q]
    python -m spark_rapids_tpu.utils.profile skew   <input>
    python -m spark_rapids_tpu.utils.profile storms <input>
    python -m spark_rapids_tpu.utils.profile diff   <a> <b>
        [--threshold R] [--min-self-s S]

``why`` answers "where did this query's wall time go": the attribution
plane's exclusive bucket ledger rendered as a ranked table with the
one-line verdict ("exchange-bound: 71% of 23.3 s in
exchange_collective"), over any of the four inputs — and for a
timed-out query, the black box's last spans and cancel/health events.

``top --adaptive`` additionally lists each query's adaptive-plane
decisions (broadcast/shuffled/skew-split/batch-retarget) with the
triggering stat.  ``top --cache`` adds the result-cache report:
per-signature hit rate, bytes saved, and device-seconds avoided from
the event log's ``cache`` records.  ``diff`` compares per-op self-times of two runs
(keys matched by plan signature when both sides have one) and exits
nonzero when any op regressed by >= the threshold ratio — the bench
gate's verdict; joins whose adaptive strategy flipped between the two
inputs are flagged as ``DECISION FLIP`` (informational).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

EXIT_OK = 0
EXIT_BAD_INPUT = 1
EXIT_REGRESSION = 2


# ---------------------------------------------------------------------------
# Input loading + normalization
# ---------------------------------------------------------------------------

def _load_json_lines(path: str) -> List[dict]:
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def detect_kind(records: List[dict]) -> str:
    """profile-store | event-log | bench | blackbox, from record shape
    alone."""
    if any(r.get("record") == "blackbox" for r in records):
        return "blackbox"
    if len(records) == 1 and ("tpch_sf1_op_rollup" in records[0]
                              or "tpch_sf1_stats" in records[0]
                              or "metric" in records[0]):
        return "bench"
    if any(r.get("record") == "profile" for r in records):
        return "profile-store"
    if any("op_rollup" in r or "op_stats" in r or "plan" in r
           for r in records):
        return "event-log"
    raise ValueError("unrecognized input: neither a profile store, a "
                     "query event log, a BENCH_*.json scoreboard, nor "
                     "a query-*.blackbox.json dump")


def _op_key(rec: dict) -> str:
    """Diff key of a per-op record: signature-qualified when the record
    carries a stable signature (profile store), bare op name otherwise
    (event-log rollups)."""
    sig = rec.get("sig")
    return f"{rec['op']}[{sig}]" if sig else str(rec["op"])


def _norm_op(rec: dict) -> dict:
    return {
        "op": rec.get("op"),
        "sig": rec.get("sig"),
        "self_s": rec.get("self_s"),
        "total_s": rec.get("total_s"),
        "rows_out": rec.get("rows_out"),
        "bytes_out": rec.get("bytes_out"),
        "batches_out": rec.get("batches_out"),
    }


def load_runs(path: str) -> List[dict]:
    """Normalize any input into runs of shape
    ``{label, ops: {key: oprec}, exchanges: [..], compiles, wall_s}``.
    One run per query (profile store / event log) or per bench query."""
    if path.endswith(".json"):
        try:
            with open(path) as f:
                records = [json.load(f)]
        except ValueError:
            records = _load_json_lines(path)
    else:
        records = _load_json_lines(path)
    if not records:
        raise ValueError(f"{path}: no records")
    kind = detect_kind(records)
    runs: List[dict] = []
    if kind == "blackbox":
        for r in records:
            if r.get("record") != "blackbox":
                continue
            runs.append({"label": f"query {r.get('query_id')}",
                         "ops": {}, "exchanges": [], "compiles": None,
                         "wall_s": None, "decisions": [],
                         "attribution": r.get("attribution"),
                         "blackbox": r,
                         "status": r.get("status")})
        return runs
    if kind == "bench":
        b = records[0]
        rollups = b.get("tpch_sf1_op_rollup") or {}
        statses = b.get("tpch_sf1_stats") or {}
        compile_recs = b.get("tpch_sf1_compile") or {}
        atts = b.get("tpch_sf1_attribution") or {}
        boxes = b.get("tpch_sf1_blackbox") or {}
        for q in sorted(set(rollups) | set(statses) | set(compile_recs)
                        | set(atts) | set(boxes)):
            ops: Dict[str, dict] = {}
            for op, r in (rollups.get(q) or {}).items():
                ops[f"{q}/{op}"] = {"op": op, "sig": None,
                                    "self_s": r.get("self_s"),
                                    "total_s": r.get("total_s")}
            st = statses.get(q) or {}
            for rec in st.get("ops") or []:
                ops[f"{q}/{_op_key(rec)}"] = _norm_op(rec)
            crec = compile_recs.get(q)
            runs.append({"label": q, "ops": ops,
                         "exchanges": (st.get("exchanges") or []),
                         "compiles": (crec or {}).get("cold_compiles"),
                         "compile_rec": crec, "wall_s": None,
                         "decisions": st.get("adaptive_decisions") or [],
                         "attribution": atts.get(q),
                         "blackbox": boxes.get(q)})
        return runs
    for r in records:
        if kind == "profile-store":
            if r.get("record") != "profile":
                continue
            ops = {_op_key(o): _norm_op(o) for o in r.get("ops", [])}
            runs.append({"label": f"query {r.get('query_id')}",
                         "ops": ops,
                         "exchanges": r.get("exchanges") or [],
                         "compiles": None,
                         "wall_s": r.get("wall_s"),
                         "decisions": r.get("adaptive_decisions") or [],
                         "attribution": r.get("attribution")})
            continue
        # event log: prefer the stats plane's op_stats, fall back to
        # the trace rollup alone
        ops = {}
        for o in r.get("op_stats") or []:
            ops[_op_key(o)] = _norm_op(o)
        if not ops:
            for op, ru in (r.get("op_rollup") or {}).items():
                ops[op] = {"op": op, "sig": None,
                           "self_s": ru.get("self_s"),
                           "total_s": ru.get("total_s")}
        compiles = None
        tel = r.get("telemetry")
        if isinstance(tel, dict):
            compiles = tel.get("tpuq_kernel_compile_total")
        runs.append({"label": f"query {r.get('query_id')}",
                     "ops": ops,
                     "exchanges": r.get("exchange_stats") or [],
                     "compiles": compiles,
                     "wall_s": r.get("wall_s"),
                     "health": r.get("health") or [],
                     "decisions": r.get("adaptive_decisions") or [],
                     "cache": r.get("cache"),
                     "attribution": r.get("attribution"),
                     "blackbox_file": r.get("blackbox"),
                     "status": r.get("status")})
    return runs


def merge_ops(runs: List[dict]) -> Dict[str, dict]:
    """Sum self/total time (and max rows/bytes) per op key across a
    run set — the per-input aggregate the reports and diff work on."""
    out: Dict[str, dict] = {}
    for run in runs:
        for key, rec in run["ops"].items():
            slot = out.setdefault(key, {
                "op": rec.get("op"), "self_s": 0.0, "total_s": 0.0,
                "timed": False, "rows_out": rec.get("rows_out"),
                "bytes_out": rec.get("bytes_out")})
            if rec.get("self_s") is not None:
                slot["self_s"] += float(rec["self_s"])
                slot["timed"] = True
            if rec.get("total_s") is not None:
                slot["total_s"] += float(rec["total_s"])
            for f in ("rows_out", "bytes_out"):
                if rec.get(f) is not None:
                    slot[f] = max(slot.get(f) or 0, rec[f])
    return out


# ---------------------------------------------------------------------------
# Reports
# ---------------------------------------------------------------------------

def report_top(runs: List[dict], n: int) -> List[str]:
    ops = merge_ops(runs)
    timed = {k: v for k, v in ops.items() if v["timed"]}
    lines = [f"top {n} ops by self time over {len(runs)} run(s):"]
    if not timed:
        lines.append("  (no traced self-times in this input — run with "
                     "spark.rapids.sql.trace.enabled)")
        ranked = sorted(ops.items(),
                        key=lambda kv: -(kv[1].get("rows_out") or 0))[:n]
        for key, v in ranked:
            lines.append(f"  {key}: rows={v.get('rows_out')} "
                         f"bytes={v.get('bytes_out')}")
        return lines
    ranked = sorted(timed.items(), key=lambda kv: -kv[1]["self_s"])[:n]
    for key, v in ranked:
        extra = ""
        if v.get("rows_out") is not None:
            extra = f" rows={v['rows_out']}"
            if v.get("bytes_out") is not None:
                extra += f" bytes={v['bytes_out']}"
        lines.append(f"  {key}: self={v['self_s']:.6f}s "
                     f"total={v['total_s']:.6f}s{extra}")
    return lines


def _fmt_decision(d: dict) -> str:
    """One adaptive decision with its triggering stat, one line."""
    kind = d.get("kind")
    where = f"{d.get('op')}[{d.get('sig', '')}]"
    if kind in ("broadcast", "shuffled"):
        return (f"{where}: {kind} (build_bytes={d.get('build_bytes')} "
                f"threshold={d.get('threshold')} "
                f"source={d.get('source')})")
    if kind == "skew-split":
        return (f"{where}: skew-split (partitions={d.get('partitions')} "
                f"splits={d.get('splits')} rows={d.get('rows')} "
                f"skew={d.get('skew_factor')} "
                f"threshold={d.get('threshold')})")
    if kind == "batch-retarget":
        return (f"{where}: batch-retarget "
                f"(target_rows={d.get('target_rows')} "
                f"observed_row_bytes={d.get('observed_row_bytes')} "
                f"static_row_bytes={d.get('static_row_bytes')})")
    return f"{where}: {kind} ({d})"


def report_adaptive(runs: List[dict]) -> List[str]:
    lines = [f"adaptive decisions over {len(runs)} run(s):"]
    found = False
    for run in runs:
        for d in run.get("decisions") or []:
            found = True
            lines.append(f"  {run['label']} {_fmt_decision(d)}")
    if not found:
        lines.append("  (no adaptive decisions in this input — run "
                     "with spark.rapids.tpu.adaptive.enabled)")
    return lines


def report_cache(runs: List[dict]) -> List[str]:
    """Result-cache effectiveness per plan signature, from the event
    log's ``entry["cache"]`` records: hit rate, bytes saved (hit bytes
    served from host), and device-seconds avoided (the cold runtime
    each hit skipped)."""
    per_sig: Dict[str, dict] = {}
    seen = False
    for run in runs:
        c = run.get("cache")
        if not isinstance(c, dict) or "status" not in c:
            continue
        seen = True
        slot = per_sig.setdefault(c.get("signature", "?"), {
            "hits": 0, "misses": 0, "bytes_saved": 0,
            "device_s_avoided": 0.0})
        if c["status"] == "hit":
            slot["hits"] += 1
            slot["bytes_saved"] += int(c.get("bytes") or 0)
            slot["device_s_avoided"] += float(c.get("saved_s") or 0.0)
        else:
            slot["misses"] += 1
    lines = [f"result cache over {len(runs)} run(s):"]
    if not seen:
        lines.append("  (no cache records in this input — run with "
                     "spark.rapids.tpu.cache.enabled)")
        return lines
    total_h = sum(s["hits"] for s in per_sig.values())
    total_m = sum(s["misses"] for s in per_sig.values())
    lines.append(
        f"  overall: {total_h} hit(s) / {total_m} miss(es) "
        f"(rate {total_h / max(1, total_h + total_m):.2%}), "
        f"{sum(s['bytes_saved'] for s in per_sig.values())} bytes "
        f"saved, "
        f"{sum(s['device_s_avoided'] for s in per_sig.values()):.3f} "
        f"device-seconds avoided")
    ranked = sorted(per_sig.items(),
                    key=lambda kv: -kv[1]["device_s_avoided"])
    for sig, s in ranked:
        n = s["hits"] + s["misses"]
        lines.append(f"  [{sig}]: {s['hits']}/{n} hits "
                     f"(rate {s['hits'] / max(1, n):.2%}) "
                     f"bytes_saved={s['bytes_saved']} "
                     f"device_s_avoided={s['device_s_avoided']:.3f}")
    return lines


def report_why(runs: List[dict],
               query: Optional[str] = None) -> Optional[List[str]]:
    """The attribution verdict per run: a ranked exclusive-bucket table
    under the one-line diagnosis, the black box's last ring events for
    a query that died.  ``query`` filters by run label substring.
    Returns None when no run in the input carries attribution (the
    caller exits EXIT_BAD_INPUT)."""
    lines: List[str] = []
    found = False
    for run in runs:
        if query is not None and query not in str(run["label"]):
            continue
        att = run.get("attribution")
        box = run.get("blackbox")
        if not isinstance(att, dict) and isinstance(box, dict):
            att = box.get("attribution")  # a query that died mid-flight
        if not isinstance(att, dict):
            continue
        found = True
        status = run.get("status")
        tag = f" [{status}]" if status and status != "ok" else ""
        lines.append(f"{run['label']}{tag}: {att.get('verdict')}")
        e2e = float(att.get("e2e_s") or 0.0)
        ranked = sorted((att.get("buckets") or {}).items(),
                        key=lambda kv: -float(kv[1] or 0.0))
        for b, s in ranked:
            s = float(s or 0.0)
            if s <= 0.0:
                continue
            share = s / e2e if e2e > 0 else 0.0
            lines.append(f"    {b:<20} {s:>10.3f} s  {share:>6.1%}")
        if not att.get("closed", True):
            lines.append(
                f"    NOT CLOSED: {att.get('unaccounted_s')} s "
                f"unaccounted exceeds the "
                f"{float(att.get('tolerance') or 0):.0%} tolerance")
        if isinstance(box, dict):
            lines.append(f"    black box: trigger={box.get('trigger')}")
            fr = box.get("flight_recorder") or {}
            for ev in list(fr.get("events") or [])[-5:]:
                rest = ", ".join(f"{k}={v}" for k, v in ev.items()
                                 if k not in ("kind", "t_s"))
                lines.append(f"      event {ev.get('kind')} "
                             f"@{ev.get('t_s')}s  {rest}")
            spans = list(fr.get("recent_spans") or [])
            if spans:
                lines.append("      last spans: " + ", ".join(
                    f"{sp.get('op')}:{sp.get('stage')}"
                    for sp in spans[-5:]))
        elif run.get("blackbox_file"):
            lines.append(f"    black box: {run['blackbox_file']}")
    return lines if found else None


def _join_decisions(runs: List[dict]) -> Dict[str, str]:
    """Latest join-strategy decision per join identity (build-side
    subtree signature when recorded, else op signature + path) — the
    diff side's flip detector input."""
    out: Dict[str, str] = {}
    for run in runs:
        for d in run.get("decisions") or []:
            if d.get("kind") not in ("broadcast", "shuffled"):
                continue
            key = (d.get("build_sig")
                   or f"{d.get('op')}[{d.get('sig', '')}]/"
                      f"{d.get('path', '')}")
            out[key] = d["kind"]
    return out


def report_decision_flips(a_runs: List[dict], b_runs: List[dict]
                          ) -> List[str]:
    """Joins whose adaptive strategy flipped between two runs —
    informational in diff output (a flip explains a self-time shift;
    it is not itself a regression)."""
    a_dec, b_dec = _join_decisions(a_runs), _join_decisions(b_runs)
    lines: List[str] = []
    for key in sorted(set(a_dec) & set(b_dec)):
        if a_dec[key] != b_dec[key]:
            lines.append(f"  DECISION FLIP {key}: "
                         f"{a_dec[key]} -> {b_dec[key]}")
    return lines


def report_skew(runs: List[dict]) -> List[str]:
    lines = [f"exchange skew over {len(runs)} run(s):"]
    found = False
    for run in runs:
        for ex in run["exchanges"]:
            found = True
            flag = "  SKEWED" if ex.get("skewed") else ""
            execs = (f" executors={ex['executors']}"
                     if ex.get("executors", 1) > 1 else "")
            lines.append(
                f"  {run['label']} {ex['op']}[{ex.get('sig', '')}]: "
                f"{ex.get('partitions')} parts "
                f"max={ex.get('max')} total={ex.get('total')} "
                f"({ex.get('unit')}) "
                f"skew={ex.get('skew_factor'):.2f}{execs}{flag}")
    if not found:
        lines.append("  (no exchange partition stats in this input)")
    return lines


def report_storms(runs: List[dict]) -> List[str]:
    lines = [f"compile activity over {len(runs)} run(s):"]
    found = False
    for run in runs:
        rec = run.get("compile_rec")
        if rec:
            # bench scoreboard: cold-vs-warm split from the shape plane
            found = True
            warm = rec.get("warm_compiles") or 0
            flag = "  WARM-PATH COMPILES" if warm else ""
            lines.append(
                f"  {run['label']}: cold {rec.get('cold_compiles', 0)} "
                f"compiles ({rec.get('cold_compile_s', 0.0):.1f}s), "
                f"warm {warm}, bucketing={rec.get('bucketing')} "
                f"hits/misses {rec.get('bucket_hits', 0)}/"
                f"{rec.get('bucket_misses', 0)}, "
                f"pad {rec.get('pad_rows', 0)} rows{flag}")
            continue
        storms = [h for h in run.get("health", [])
                  if h.get("check") == "compile_storm"]
        if run.get("compiles") or storms:
            found = True
            note = "".join(f"  WARN {h.get('detail', 'compile storm')}"
                           for h in storms)
            lines.append(f"  {run['label']}: "
                         f"{run.get('compiles') or 0} kernel "
                         f"compiles{note}")
    if not found:
        lines.append("  (no compile telemetry in this input — the "
                     "query event log carries it)")
    return lines


# ---------------------------------------------------------------------------
# diff — the regression gate
# ---------------------------------------------------------------------------

def diff_runs(a_runs: List[dict], b_runs: List[dict],
              threshold: float = 1.5, min_self_s: float = 0.005
              ) -> Tuple[List[str], List[dict]]:
    """Compare per-op self-times of run set b (candidate) against a
    (baseline).  A regression is an op whose summed self-time grew by
    >= ``threshold``x AND is >= ``min_self_s`` in b (absolute floor so
    microsecond noise on trivial ops never fails a gate).  Returns
    (report lines, regressions)."""
    a_ops, b_ops = merge_ops(a_runs), merge_ops(b_runs)
    lines: List[str] = []
    regressions: List[dict] = []
    improved = 0
    shared = sorted(set(a_ops) & set(b_ops))
    for key in shared:
        av, bv = a_ops[key], b_ops[key]
        if not (av["timed"] and bv["timed"]):
            continue
        a_s, b_s = av["self_s"], bv["self_s"]
        if b_s < min_self_s:
            continue
        ratio = b_s / a_s if a_s > 0 else float("inf")
        if ratio >= threshold:
            regressions.append({"op": key, "a_self_s": round(a_s, 6),
                                "b_self_s": round(b_s, 6),
                                "ratio": round(ratio, 2)})
        elif ratio <= 1.0 / threshold:
            improved += 1
    for key in sorted(set(b_ops) - set(a_ops)):
        bv = b_ops[key]
        if bv["timed"] and bv["self_s"] >= min_self_s:
            lines.append(f"  new op (no baseline): {key} "
                         f"self={bv['self_s']:.6f}s")
    lines.insert(0, f"compared {len(shared)} shared op(s); "
                    f"{len(regressions)} regression(s), "
                    f"{improved} improvement(s) at {threshold}x")
    for r in sorted(regressions, key=lambda r: -r["ratio"]):
        lines.append(f"  REGRESSION {r['op']}: "
                     f"{r['a_self_s']:.6f}s -> {r['b_self_s']:.6f}s "
                     f"({r['ratio']}x)")
    return lines, regressions


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.utils.profile",
        description="profile reports + regression diff over profile "
                    "stores, query event logs, and bench scoreboards")
    sub = p.add_subparsers(dest="cmd", required=True)
    for name, help_ in (("top", "slowest ops by traced self time"),
                        ("why", "attribution verdict: where the wall "
                                "time went, per query"),
                        ("skew", "exchange partition-skew report"),
                        ("storms", "kernel compile-storm report")):
        sp = sub.add_parser(name, help=help_)
        sp.add_argument("input")
        if name == "why":
            sp.add_argument("--query", default=None,
                            help="filter runs by label substring "
                                 "(e.g. 'q3' or a query id)")
        if name == "top":
            sp.add_argument("--n", type=int, default=10)
            sp.add_argument("--adaptive", action="store_true",
                            help="also list per-query adaptive-plane "
                                 "decisions with the triggering stat")
            sp.add_argument("--cache", action="store_true",
                            help="also report per-signature result-"
                                 "cache hit rate, bytes saved, and "
                                 "device-seconds avoided")
    dp = sub.add_parser("diff", help="regression diff: b vs baseline a "
                                     "(nonzero exit on regression)")
    dp.add_argument("a", help="baseline input")
    dp.add_argument("b", help="candidate input")
    dp.add_argument("--threshold", type=float, default=1.5,
                    help="self-time growth ratio that fails (default "
                         "1.5)")
    dp.add_argument("--min-self-s", type=float, default=0.005,
                    help="ignore ops below this candidate self time")
    args = p.parse_args(argv)

    def load(path: str) -> List[dict]:
        try:
            return load_runs(path)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            raise SystemExit(EXIT_BAD_INPUT)

    if args.cmd == "top":
        runs = load(args.input)
        print("\n".join(report_top(runs, args.n)))
        if args.adaptive:
            print("\n".join(report_adaptive(runs)))
        if args.cache:
            print("\n".join(report_cache(runs)))
        return EXIT_OK
    if args.cmd == "why":
        lines = report_why(load(args.input), query=args.query)
        if lines is None:
            print("error: no attribution records in this input — run "
                  "with spark.rapids.tpu.attribution.enabled (default "
                  "on), or point at a query-*.blackbox.json",
                  file=sys.stderr)
            return EXIT_BAD_INPUT
        print("\n".join(lines))
        return EXIT_OK
    if args.cmd == "skew":
        print("\n".join(report_skew(load(args.input))))
        return EXIT_OK
    if args.cmd == "storms":
        print("\n".join(report_storms(load(args.input))))
        return EXIT_OK
    a_runs, b_runs = load(args.a), load(args.b)
    lines, regressions = diff_runs(a_runs, b_runs,
                                   threshold=args.threshold,
                                   min_self_s=args.min_self_s)
    lines.extend(report_decision_flips(a_runs, b_runs))
    print("\n".join(lines))
    return EXIT_REGRESSION if regressions else EXIT_OK


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
