"""End-to-end CPU-vs-TPU oracle harness for DataFrame queries.

[REF: integration_tests/src/main/python/asserts.py ::
 assert_gpu_and_cpu_are_equal_collect, spark_session.py ::
 with_cpu_session/with_gpu_session] — the workhorse test pattern: build
the same query twice, once with ``spark.rapids.sql.enabled=false`` (the
numpy oracle path) and once ``=true`` with test mode on (any unexpected
fallback raises), and compare collected results.

Also home of the **chaos harness** (``run_chaos`` /
``assert_chaos_invariant``): run a query under a scheduled or
seed-randomized fault-injection schedule across the engine's failure
domains (runtime/resilience.py) and assert the engine-wide invariant —
transient faults are ridden out bit-identically, terminal faults either
degrade to a recorded host-path result or fail with a clean
domain-tagged error, and a bare ``InjectedDeviceError`` NEVER escapes.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, Optional, Tuple

from spark_rapids_tpu.sql.session import TpuSession


def tpu_session(conf: Optional[Dict] = None) -> TpuSession:
    base = {"spark.rapids.sql.enabled": True,
            "spark.rapids.sql.test.enabled": True}
    base.update(conf or {})
    return TpuSession(base)


def cpu_session(conf: Optional[Dict] = None) -> TpuSession:
    base = {"spark.rapids.sql.enabled": False}
    base.update(conf or {})
    return TpuSession(base)


def assert_tpu_and_cpu_are_equal_collect(
    df_builder: Callable[[TpuSession], "object"],
    conf: Optional[Dict] = None,
    ignore_order: bool = False,
    approx_float: bool = False,
    allow_non_tpu: Optional[list] = None,
):
    """df_builder: session -> DataFrame.  Runs both ways and compares."""
    from spark_rapids_tpu.utils.asserts import assert_tables_equal

    tconf = dict(conf or {})
    if allow_non_tpu:
        tconf["spark.rapids.sql.test.allowedNonGpu"] = ",".join(allow_non_tpu)
    t = df_builder(tpu_session(tconf)).toArrow()
    c = df_builder(cpu_session(conf)).toArrow()
    assert_tables_equal(c, t, ignore_order=ignore_order,
                        approx_float=approx_float)
    return c, t


def assert_tpu_fallback_collect(
    df_builder: Callable[[TpuSession], "object"],
    fallback_exec: str,
    conf: Optional[Dict] = None,
    ignore_order: bool = False,
):
    """Assert the query still works WITH the plugin on but the named exec
    falls back to CPU [REF: asserts.py :: assert_gpu_fallback_collect]."""
    from spark_rapids_tpu.plan.overrides import apply_overrides
    from spark_rapids_tpu.plan.planner import plan_physical
    from spark_rapids_tpu.utils.asserts import assert_tables_equal

    tconf = dict(conf or {})
    tconf["spark.rapids.sql.test.enabled"] = False
    s = tpu_session(tconf)
    df = df_builder(s)
    rc = s.rapids_conf()
    result = apply_overrides(plan_physical(df._plan, rc), rc)
    lines = [ln.strip() for ln in result.plan.tree_string().splitlines()]
    # CPU nodes print bare ("Project [...]"); TPU ones as "*TpuProject".
    assert any(ln.startswith(fallback_exec) for ln in lines), (
        f"expected {fallback_exec} to fall back to CPU; plan:\n"
        + "\n".join(lines))
    t = df.toArrow()
    c = df_builder(cpu_session(conf)).toArrow()
    assert_tables_equal(c, t, ignore_order=ignore_order)


# ---------------------------------------------------------------------------
# chaos harness: fault-injection schedules over the failure domains
# ---------------------------------------------------------------------------

def run_chaos(df_builder: Callable[[TpuSession], "object"],
              inject: Dict[str, Tuple[int, int]],
              conf: Optional[Dict] = None) -> dict:
    """Run one query with ``inject``'s failure domains armed.

    ``inject``: ``{domain: (at, transient_budget)}`` — the per-domain
    injection schedule (see runtime/resilience.py for the firing
    model).  Backoff is zeroed so soaks run at full speed.

    Returns a record::

        {"status": "ok" | "failed", "result": pa.Table | None,
         "error": TerminalDeviceError | None, "domain": str | None,
         "entry": the query's event-log entry (telemetry deltas,
                  health verdicts, resilience rollup)}

    ``"ok"`` covers both bit-identical recovery and host-degraded
    success (distinguish via ``entry["resilience"]["degraded_ops"]``).
    ``"failed"`` is a clean domain-tagged terminal failure.  A bare
    ``InjectedDeviceError`` escaping the engine violates the chaos
    invariant and raises ``AssertionError``.
    """
    from spark_rapids_tpu.runtime import resilience as R

    full: Dict = {"spark.rapids.tpu.retry.backoffBaseMs": 0}
    full.update(conf or {})
    for d, (at, budget) in inject.items():
        full[f"spark.rapids.tpu.test.inject.{d}.at"] = at
        full[f"spark.rapids.tpu.test.inject.{d}.transientCount"] = budget
    R.INJECTOR.reset()
    s = tpu_session(full)
    df = df_builder(s)
    rec = {"status": "ok", "result": None, "error": None,
           "domain": None, "entry": None}
    try:
        rec["result"] = df.toArrow()
    except R.InjectedDeviceError as e:  # pragma: no cover - invariant
        raise AssertionError(
            f"bare InjectedDeviceError escaped the engine: {e}") from e
    except R.TerminalDeviceError as e:
        rec["status"] = "failed"
        rec["error"] = e
        rec["domain"] = e.domain
    finally:
        R.INJECTOR.reset()
    rec["entry"] = getattr(df, "_last_query_entry", None)
    return rec


def assert_chaos_invariant(df_builder: Callable[[TpuSession], "object"],
                           inject: Dict[str, Tuple[int, int]],
                           conf: Optional[Dict] = None,
                           ignore_order: bool = True) -> dict:
    """Assert THE chaos invariant for one query + injection schedule:

    * transient faults (injector budget rode out by retries) → results
      **bit-identical** to a clean run of the same query;
    * terminal faults in a degradable domain → host-degraded result
      matching the clean run (approx float — the host path may order
      reductions differently), recorded in the event-log entry;
    * terminal faults elsewhere → a clean **domain-tagged** failure.

    One carve-out from bit-identity: ``alloc`` faults recover through
    the OOM retry framework, whose split-and-retry legitimately halves
    batches — float reductions then group differently (ULP-level
    drift), so alloc-retried runs also compare approx-float.

    The chaos run goes FIRST (fresh-compile domains like ``compile``
    would otherwise hit kernels the golden run already cached); the
    golden run happens after ``run_chaos`` disarmed the injector.
    Returns the ``run_chaos`` record (with ``rec["golden"]`` added).
    """
    from spark_rapids_tpu.runtime.resilience import DOMAINS
    from spark_rapids_tpu.utils.asserts import assert_tables_equal

    rec = run_chaos(df_builder, inject, conf)
    golden = df_builder(tpu_session(dict(conf or {}))).toArrow()
    rec["golden"] = golden
    if rec["status"] == "failed":
        assert rec["domain"] in DOMAINS, (
            f"terminal failure not domain-tagged: {rec['error']!r}")
        return rec
    entry = rec["entry"] or {}
    res = entry.get("resilience") or {}
    approx = (bool(res.get("degraded_ops"))
              or bool((res.get("retries") or {}).get("alloc")))
    assert_tables_equal(golden, rec["result"], ignore_order=ignore_order,
                        approx_float=approx)
    return rec


def random_chaos_schedule(seed: int, domains=None,
                          max_at: int = 6) -> Dict[str, Tuple[int, int]]:
    """A seed-deterministic injection schedule for soak tests: 1-2
    domains, each armed at a random call count with a random transient
    budget (0 = terminal)."""
    from spark_rapids_tpu.runtime.resilience import DOMAINS

    rnd = random.Random(seed)
    pool = list(domains if domains is not None else DOMAINS)
    picks = rnd.sample(pool, k=min(rnd.randint(1, 2), len(pool)))
    return {d: (rnd.randint(1, max_at), rnd.choice([0, 1, 1, 2, 3]))
            for d in picks}


# ---------------------------------------------------------------------------
# rendezvous chaos harness: the distributed failure domains
# ---------------------------------------------------------------------------

def run_rendezvous_chaos(inject: Dict[str, Tuple[int, int]],
                         nprocs: int = 3,
                         heartbeat_s: float = 0.05,
                         lease_s: float = 0.3,
                         stage_timeout: float = 5.0) -> dict:
    """Run an N-participant two-phase rendezvous stage (allgather +
    entry barrier through ``run_stage_epochs``) with the ``rendezvous``
    / ``peer_loss`` domains armed, one client thread per participant.

    The invariant the distributed tier owes its callers:

    * a **transient** ``rendezvous`` fault → every participant retries
      at a bumped epoch and completes with results identical to a clean
      run (the stage's inputs never change across epochs);
    * a ``peer_loss`` fault → the victim simulates death (heartbeat
      silenced, lease expires) and EVERY survivor raises the same
      peer-tagged ``TerminalDeviceError`` within ~2× the lease — no
      full-deadline waits, no hangs;
    * either way the coordinator's ``_stages`` table drains to empty
      (stage GC), and a bare ``InjectedDeviceError`` never escapes.

    Returns ``{"records": [per-pid record], "live_stages": {...},
    "expected": [the clean allgather result]}``.  Each record:
    ``{pid, status: ok|failed|bare_injected, result, error, domain,
    peer, died, elapsed}``.
    """
    import threading
    import time

    from spark_rapids_tpu.parallel import rendezvous as RD
    from spark_rapids_tpu.runtime import resilience as R

    R.INJECTOR.reset()
    R.INJECTOR.configure(inject)
    policy = R.RetryPolicy(backoff_base_ms=0)
    coord = RD.RendezvousCoordinator(nprocs, lease_s=lease_s)
    payloads = {pid: {"pid": pid, "v": pid * 11} for pid in range(nprocs)}
    records: list = [None] * nprocs

    def run(pid: int) -> None:
        client = RD.RendezvousClient(coord.address, pid,
                                     default_timeout=stage_timeout)
        rec = {"pid": pid, "status": "ok", "result": None, "error": None,
               "domain": None, "peer": None, "died": False,
               "elapsed": 0.0}
        t0 = time.monotonic()
        try:
            client.start_heartbeat(heartbeat_s)

            def attempt(epoch: int):
                vals = client.allgather("chaos:gather", payloads[pid],
                                        epoch=epoch)
                client.barrier("chaos:enter", epoch=epoch)
                return vals

            rec["result"] = RD.run_stage_epochs(
                client, "chaos", attempt, policy=policy)
        except R.TerminalDeviceError as e:
            rec["status"] = "failed"
            rec["error"] = e
            rec["domain"] = e.domain
            rec["peer"] = e.peer
            rec["died"] = isinstance(e.cause, R.InjectedDeviceError)
        except R.InjectedDeviceError as e:  # pragma: no cover - invariant
            rec["status"] = "bare_injected"
            rec["error"] = e
        finally:
            rec["elapsed"] = time.monotonic() - t0
            client.stop_heartbeat()
            records[pid] = rec

    threads = [threading.Thread(target=run, args=(pid,), daemon=True)
               for pid in range(nprocs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    hung = [i for i, r in enumerate(records) if r is None]
    live_stages = {k: st.waiters for k, st in coord._stages.items()}
    coord.shutdown()
    R.INJECTOR.reset()
    assert not hung, f"rendezvous chaos participants hung: {hung}"
    for rec in records:
        assert rec["status"] != "bare_injected", (
            f"bare InjectedDeviceError escaped: {rec['error']!r}")
    return {"records": records, "live_stages": live_stages,
            "expected": [payloads[i] for i in range(nprocs)]}
