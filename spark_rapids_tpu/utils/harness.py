"""End-to-end CPU-vs-TPU oracle harness for DataFrame queries.

[REF: integration_tests/src/main/python/asserts.py ::
 assert_gpu_and_cpu_are_equal_collect, spark_session.py ::
 with_cpu_session/with_gpu_session] — the workhorse test pattern: build
the same query twice, once with ``spark.rapids.sql.enabled=false`` (the
numpy oracle path) and once ``=true`` with test mode on (any unexpected
fallback raises), and compare collected results.

Also home of the **chaos harness** (``run_chaos`` /
``assert_chaos_invariant``): run a query under a scheduled or
seed-randomized fault-injection schedule across the engine's failure
domains (runtime/resilience.py) and assert the engine-wide invariant —
transient faults are ridden out bit-identically, terminal faults either
degrade to a recorded host-path result or fail with a clean
domain-tagged error, and a bare ``InjectedDeviceError`` NEVER escapes.

The cancel chaos harness (``run_cancel_chaos`` /
``assert_cancel_invariant`` / ``run_rendezvous_cancel_chaos``) is the
same idea for the cancellation layer: fire a cancel at a randomized
point while the query is provably inside an armed failure domain and
assert ``QueryCancelled`` surfaces within 2x ``cancelPollMs`` with
every resource reclaimed (zero leaked spillables, zero semaphore
holders, an empty spill directory).
"""

from __future__ import annotations

import random
from typing import Callable, Dict, Optional, Tuple

from spark_rapids_tpu.sql.session import TpuSession


def tpu_session(conf: Optional[Dict] = None) -> TpuSession:
    base = {"spark.rapids.sql.enabled": True,
            "spark.rapids.sql.test.enabled": True}
    base.update(conf or {})
    return TpuSession(base)


def cpu_session(conf: Optional[Dict] = None) -> TpuSession:
    base = {"spark.rapids.sql.enabled": False}
    base.update(conf or {})
    return TpuSession(base)


def assert_tpu_and_cpu_are_equal_collect(
    df_builder: Callable[[TpuSession], "object"],
    conf: Optional[Dict] = None,
    ignore_order: bool = False,
    approx_float: bool = False,
    allow_non_tpu: Optional[list] = None,
):
    """df_builder: session -> DataFrame.  Runs both ways and compares."""
    from spark_rapids_tpu.utils.asserts import assert_tables_equal

    tconf = dict(conf or {})
    if allow_non_tpu:
        tconf["spark.rapids.sql.test.allowedNonGpu"] = ",".join(allow_non_tpu)
    t = df_builder(tpu_session(tconf)).toArrow()
    c = df_builder(cpu_session(conf)).toArrow()
    assert_tables_equal(c, t, ignore_order=ignore_order,
                        approx_float=approx_float)
    return c, t


def assert_tpu_fallback_collect(
    df_builder: Callable[[TpuSession], "object"],
    fallback_exec: str,
    conf: Optional[Dict] = None,
    ignore_order: bool = False,
):
    """Assert the query still works WITH the plugin on but the named exec
    falls back to CPU [REF: asserts.py :: assert_gpu_fallback_collect]."""
    from spark_rapids_tpu.plan.overrides import apply_overrides
    from spark_rapids_tpu.plan.planner import plan_physical
    from spark_rapids_tpu.utils.asserts import assert_tables_equal

    tconf = dict(conf or {})
    tconf["spark.rapids.sql.test.enabled"] = False
    s = tpu_session(tconf)
    df = df_builder(s)
    rc = s.rapids_conf()
    result = apply_overrides(plan_physical(df._plan, rc), rc)
    lines = [ln.strip() for ln in result.plan.tree_string().splitlines()]
    # CPU nodes print bare ("Project [...]"); TPU ones as "*TpuProject".
    assert any(ln.startswith(fallback_exec) for ln in lines), (
        f"expected {fallback_exec} to fall back to CPU; plan:\n"
        + "\n".join(lines))
    t = df.toArrow()
    c = df_builder(cpu_session(conf)).toArrow()
    assert_tables_equal(c, t, ignore_order=ignore_order)


# ---------------------------------------------------------------------------
# chaos harness: fault-injection schedules over the failure domains
# ---------------------------------------------------------------------------

def run_chaos(df_builder: Callable[[TpuSession], "object"],
              inject: Dict[str, Tuple[int, int]],
              conf: Optional[Dict] = None) -> dict:
    """Run one query with ``inject``'s failure domains armed.

    ``inject``: ``{domain: (at, transient_budget)}`` — the per-domain
    injection schedule (see runtime/resilience.py for the firing
    model).  Backoff is zeroed so soaks run at full speed.

    Returns a record::

        {"status": "ok" | "failed", "result": pa.Table | None,
         "error": TerminalDeviceError | None, "domain": str | None,
         "entry": the query's event-log entry (telemetry deltas,
                  health verdicts, resilience rollup)}

    ``"ok"`` covers both bit-identical recovery and host-degraded
    success (distinguish via ``entry["resilience"]["degraded_ops"]``).
    ``"failed"`` is a clean domain-tagged terminal failure.  A bare
    ``InjectedDeviceError`` escaping the engine violates the chaos
    invariant and raises ``AssertionError``.
    """
    from spark_rapids_tpu.runtime import resilience as R

    full: Dict = {"spark.rapids.tpu.retry.backoffBaseMs": 0}
    full.update(conf or {})
    for d, (at, budget) in inject.items():
        full[f"spark.rapids.tpu.test.inject.{d}.at"] = at
        full[f"spark.rapids.tpu.test.inject.{d}.transientCount"] = budget
    R.INJECTOR.reset()
    s = tpu_session(full)
    df = df_builder(s)
    rec = {"status": "ok", "result": None, "error": None,
           "domain": None, "entry": None}
    try:
        rec["result"] = df.toArrow()
    except R.InjectedDeviceError as e:  # pragma: no cover - invariant
        raise AssertionError(
            f"bare InjectedDeviceError escaped the engine: {e}") from e
    except R.TerminalDeviceError as e:
        rec["status"] = "failed"
        rec["error"] = e
        rec["domain"] = e.domain
    finally:
        R.INJECTOR.reset()
    rec["entry"] = getattr(df, "_last_query_entry", None)
    return rec


def assert_chaos_invariant(df_builder: Callable[[TpuSession], "object"],
                           inject: Dict[str, Tuple[int, int]],
                           conf: Optional[Dict] = None,
                           ignore_order: bool = True) -> dict:
    """Assert THE chaos invariant for one query + injection schedule:

    * transient faults (injector budget rode out by retries) → results
      **bit-identical** to a clean run of the same query;
    * terminal faults in a degradable domain → host-degraded result
      matching the clean run (approx float — the host path may order
      reductions differently), recorded in the event-log entry;
    * terminal faults elsewhere → a clean **domain-tagged** failure.

    One carve-out from bit-identity: ``alloc`` faults recover through
    the OOM retry framework, whose split-and-retry legitimately halves
    batches — float reductions then group differently (ULP-level
    drift), so alloc-retried runs also compare approx-float.

    The chaos run goes FIRST (fresh-compile domains like ``compile``
    would otherwise hit kernels the golden run already cached); the
    golden run happens after ``run_chaos`` disarmed the injector.
    Returns the ``run_chaos`` record (with ``rec["golden"]`` added).
    """
    from spark_rapids_tpu.runtime.resilience import DOMAINS
    from spark_rapids_tpu.utils.asserts import assert_tables_equal

    rec = run_chaos(df_builder, inject, conf)
    golden = df_builder(tpu_session(dict(conf or {}))).toArrow()
    rec["golden"] = golden
    if rec["status"] == "failed":
        assert rec["domain"] in DOMAINS, (
            f"terminal failure not domain-tagged: {rec['error']!r}")
        return rec
    entry = rec["entry"] or {}
    res = entry.get("resilience") or {}
    approx = (bool(res.get("degraded_ops"))
              or bool((res.get("retries") or {}).get("alloc")))
    assert_tables_equal(golden, rec["result"], ignore_order=ignore_order,
                        approx_float=approx)
    return rec


def random_chaos_schedule(seed: int, domains=None,
                          max_at: int = 6) -> Dict[str, Tuple[int, int]]:
    """A seed-deterministic injection schedule for soak tests: 1-2
    domains, each armed at a random call count with a random transient
    budget (0 = terminal)."""
    from spark_rapids_tpu.runtime.resilience import DOMAINS

    rnd = random.Random(seed)
    pool = list(domains if domains is not None else DOMAINS)
    picks = rnd.sample(pool, k=min(rnd.randint(1, 2), len(pool)))
    return {d: (rnd.randint(1, max_at), rnd.choice([0, 1, 1, 2, 3]))
            for d in picks}


# ---------------------------------------------------------------------------
# rendezvous chaos harness: the distributed failure domains
# ---------------------------------------------------------------------------

def run_rendezvous_chaos(inject: Dict[str, Tuple[int, int]],
                         nprocs: int = 3,
                         heartbeat_s: float = 0.05,
                         lease_s: float = 0.3,
                         stage_timeout: float = 5.0) -> dict:
    """Run an N-participant two-phase rendezvous stage (allgather +
    entry barrier through ``run_stage_epochs``) with the ``rendezvous``
    / ``peer_loss`` domains armed, one client thread per participant.

    The invariant the distributed tier owes its callers:

    * a **transient** ``rendezvous`` fault → every participant retries
      at a bumped epoch and completes with results identical to a clean
      run (the stage's inputs never change across epochs);
    * a ``peer_loss`` fault → the victim simulates death (heartbeat
      silenced, lease expires) and EVERY survivor raises the same
      peer-tagged ``TerminalDeviceError`` within ~2× the lease — no
      full-deadline waits, no hangs;
    * either way the coordinator's ``_stages`` table drains to empty
      (stage GC), and a bare ``InjectedDeviceError`` never escapes.

    Returns ``{"records": [per-pid record], "live_stages": {...},
    "expected": [the clean allgather result]}``.  Each record:
    ``{pid, status: ok|failed|bare_injected, result, error, domain,
    peer, died, elapsed}``.
    """
    import threading
    import time

    from spark_rapids_tpu.parallel import rendezvous as RD
    from spark_rapids_tpu.runtime import resilience as R

    R.INJECTOR.reset()
    R.INJECTOR.configure(inject)
    policy = R.RetryPolicy(backoff_base_ms=0)
    coord = RD.RendezvousCoordinator(nprocs, lease_s=lease_s)
    payloads = {pid: {"pid": pid, "v": pid * 11} for pid in range(nprocs)}
    records: list = [None] * nprocs

    def run(pid: int) -> None:
        client = RD.RendezvousClient(coord.address, pid,
                                     default_timeout=stage_timeout)
        rec = {"pid": pid, "status": "ok", "result": None, "error": None,
               "domain": None, "peer": None, "died": False,
               "elapsed": 0.0}
        t0 = time.monotonic()
        try:
            client.start_heartbeat(heartbeat_s)

            def attempt(epoch: int):
                vals = client.allgather("chaos:gather", payloads[pid],
                                        epoch=epoch)
                client.barrier("chaos:enter", epoch=epoch)
                return vals

            rec["result"] = RD.run_stage_epochs(
                client, "chaos", attempt, policy=policy)
        except R.TerminalDeviceError as e:
            rec["status"] = "failed"
            rec["error"] = e
            rec["domain"] = e.domain
            rec["peer"] = e.peer
            rec["died"] = isinstance(e.cause, R.InjectedDeviceError)
        except R.InjectedDeviceError as e:  # pragma: no cover - invariant
            rec["status"] = "bare_injected"
            rec["error"] = e
        finally:
            rec["elapsed"] = time.monotonic() - t0
            client.stop_heartbeat()
            records[pid] = rec

    threads = [threading.Thread(target=run, args=(pid,), daemon=True)
               for pid in range(nprocs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    hung = [i for i, r in enumerate(records) if r is None]
    live_stages = {k: st.waiters for k, st in coord._stages.items()}
    coord.shutdown()
    R.INJECTOR.reset()
    assert not hung, f"rendezvous chaos participants hung: {hung}"
    for rec in records:
        assert rec["status"] != "bare_injected", (
            f"bare InjectedDeviceError escaped: {rec['error']!r}")
    return {"records": records, "live_stages": live_stages,
            "expected": [payloads[i] for i in range(nprocs)]}


# ---------------------------------------------------------------------------
# cancel chaos harness: cancellation fired mid-domain, reclamation checked
# ---------------------------------------------------------------------------

def run_cancel_chaos(df_builder: Callable[[TpuSession], "object"],
                     inject: Dict[str, Tuple[int, int]],
                     conf: Optional[Dict] = None,
                     poll_ms: float = 50.0,
                     seed: int = 0,
                     timeout_s: float = 60.0) -> dict:
    """Run one query with ``inject``'s domains armed and fire a cancel
    at a randomized point while the query is INSIDE an armed domain
    (detected by the domain's injection counter moving — the query is
    then in that domain's retry/backoff loop).

    The schedule should keep the query spinning long enough to be
    cancelled mid-flight: a large transient budget makes every attempt
    re-fire, and backoff is pinned to ~2x the poll interval so the
    worker thread lives inside cancellable waits.

    Returns a record::

        {"status": "cancelled" | "completed" | "error",
         "error":      the raised exception (cancelled/error),
         "fired":      the domain whose counter moved (None if raced),
         "cancel_sent": True if cancel_query found the query in flight,
         "latency_s":  token-recorded request→raise latency (from the
                       event-log entry's ``cancel`` record),
         "leaks":      DeviceMemoryManager.report_leaks() afterwards,
         "sem_holders": semaphore holders afterwards,
         "spill_files": leftover files under the manager's spill dir,
         "entry":      the query's event-log entry}
    """
    import os
    import threading
    import time

    from spark_rapids_tpu.runtime import cancel as CN
    from spark_rapids_tpu.runtime import memory as M
    from spark_rapids_tpu.runtime import resilience as R
    from spark_rapids_tpu.runtime.semaphore import peek_semaphore

    backoff_ms = max(int(2 * poll_ms), 1)
    full: Dict = {
        "spark.rapids.tpu.query.cancelPollMs": int(poll_ms),
        "spark.rapids.tpu.retry.backoffBaseMs": backoff_ms,
        "spark.rapids.tpu.retry.backoffMaxMs": backoff_ms,
        "spark.rapids.tpu.retry.maxAttempts": 1_000_000,
        "spark.rapids.tpu.retry.budgetPerQuery": 0,  # unlimited
    }
    full.update(conf or {})
    for d, (at, budget) in inject.items():
        full[f"spark.rapids.tpu.test.inject.{d}.at"] = at
        full[f"spark.rapids.tpu.test.inject.{d}.transientCount"] = budget
    R.INJECTOR.reset()
    CN.reset()
    s = tpu_session(full)
    df = df_builder(s)
    base = dict(R._TM_INJECTED.child_values())
    box: Dict = {}

    def run():
        try:
            box["result"] = df.toArrow()
        except BaseException as e:
            box["error"] = e

    worker = threading.Thread(target=run, daemon=True,
                              name="tpuq-cancel-chaos-query")
    worker.start()
    # wait until the query is demonstrably inside an armed domain
    fired = None
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline and worker.is_alive():
        cur = R._TM_INJECTED.child_values()
        fired = next((d for d in inject
                      if cur.get(d, 0) > base.get(d, 0)), None)
        if fired is not None:
            break
        time.sleep(0.002)
    # randomized cancel point inside the domain's retry window
    rnd = random.Random(seed)
    time.sleep(rnd.uniform(0.0, backoff_ms / 1000.0))
    active = CN.active_queries()
    cancel_sent = bool(active) and CN.cancel_query(
        active[0], reason="user",
        detail=f"cancel-chaos mid-{fired or 'unknown'}")
    worker.join(timeout=timeout_s)
    R.INJECTOR.reset()
    assert not worker.is_alive(), (
        f"query failed to observe the cancel within {timeout_s}s "
        f"(mid-{fired}; cancel_sent={cancel_sent})")
    err = box.get("error")
    if isinstance(err, CN.QueryCancelled):
        status = "cancelled"
    elif err is not None:
        status = "error"
    else:
        status = "completed"
    entry = getattr(df, "_last_query_entry", None) or {}
    mgr = M.peek_manager()
    sem = peek_semaphore()
    spill_files = []
    if mgr is not None and os.path.isdir(mgr.spill_path):
        spill_files = sorted(os.listdir(mgr.spill_path))
    return {
        "status": status,
        "error": err,
        "fired": fired,
        "cancel_sent": cancel_sent,
        "latency_s": (entry.get("cancel") or {}).get("latency_s"),
        "leaks": mgr.report_leaks() if mgr is not None else 0,
        "sem_holders": sem.holders if sem is not None else 0,
        "spill_files": spill_files,
        "entry": entry,
    }


def assert_cancel_invariant(df_builder: Callable[[TpuSession], "object"],
                            inject: Dict[str, Tuple[int, int]],
                            conf: Optional[Dict] = None,
                            poll_ms: float = 50.0,
                            seed: int = 0) -> dict:
    """Assert THE cancel invariant for one query + injection schedule:
    a cancel fired mid-domain surfaces as ``QueryCancelled`` with a
    request→raise latency under 2x ``cancelPollMs``, and the engine is
    back at a clean steady state — zero leaked spillables, zero
    semaphore holders, an empty spill directory."""
    rec = run_cancel_chaos(df_builder, inject, conf=conf,
                           poll_ms=poll_ms, seed=seed)
    assert rec["cancel_sent"], (
        f"query finished before the cancel could fire (mid-"
        f"{rec['fired']}): {rec['status']}")
    assert rec["status"] == "cancelled", (
        f"expected QueryCancelled, got {rec['status']}: "
        f"{rec['error']!r}")
    assert rec["latency_s"] is not None, "no cancel latency recorded"
    bound = 2.0 * poll_ms / 1000.0
    assert rec["latency_s"] < bound, (
        f"cancel latency {rec['latency_s']:.3f}s >= 2x cancelPollMs "
        f"({bound:.3f}s) mid-{rec['fired']}")
    assert rec["leaks"] == 0, (
        f"{rec['leaks']} spillables leaked after cancel "
        f"mid-{rec['fired']}")
    assert rec["sem_holders"] == 0, (
        f"{rec['sem_holders']} semaphore holders after cancel")
    assert not rec["spill_files"], (
        f"spill files stranded after cancel: {rec['spill_files']}")
    entry = rec["entry"]
    assert entry.get("status") == "cancelled", entry.get("status")
    assert (entry.get("cancel") or {}).get("reason") == "user"
    return rec


def run_scheduler_chaos(n_queries: int = 24,
                        tenants: Tuple[str, ...] = ("a", "b"),
                        conf: Optional[Dict] = None,
                        seed: int = 0,
                        max_concurrent: int = 2,
                        cancel_fraction: float = 0.25,
                        inject: Optional[Dict[str, Tuple[int, int]]] = None,
                        poll_ms: float = 20.0,
                        timeout_s: float = 120.0) -> dict:
    """Concurrency soak for the multi-tenant scheduler: blast
    ``n_queries`` submissions round-robin across ``tenants`` through a
    ``QueryServer`` (run-slot cap pinned low so the service saturates),
    cancel a seed-randomized fraction mid-flight, optionally with chaos
    faults armed (``inject`` uses the ``run_chaos`` schedule format —
    transient budgets make queries ride faults out under load), and
    drain everything.

    Returns a record::

        {"outcomes": {"ok": n, "cancelled": n, "error": n},
         "errors":   [the non-cancel exceptions, if any],
         "rejected": submissions QueryRejected at admission,
         "stats":    per-tenant scheduler accounting (completions,
                     shed/reject counts — what the bench records),
         "leaks":    DeviceMemoryManager.report_leaks() afterwards,
         "sem_holders": semaphore holders afterwards,
         "queued", "running": scheduler totals afterwards (must be 0)}

    Asserts the no-deadlock invariant itself: every admitted query
    reaches ``done`` within ``timeout_s``.
    """
    from spark_rapids_tpu.runtime import cancel as CN
    from spark_rapids_tpu.runtime import memory as M
    from spark_rapids_tpu.runtime import resilience as R
    from spark_rapids_tpu.runtime import scheduler as SCH
    from spark_rapids_tpu.runtime.semaphore import peek_semaphore
    from spark_rapids_tpu.sql.server import QueryRejected, QueryServer

    full: Dict = {
        "spark.rapids.tpu.scheduler.maxConcurrentQueries": max_concurrent,
        "spark.rapids.tpu.query.cancelPollMs": int(poll_ms),
        "spark.rapids.tpu.retry.backoffBaseMs": 0,
    }
    full.update(conf or {})
    for d, (at, budget) in (inject or {}).items():
        full[f"spark.rapids.tpu.test.inject.{d}.at"] = at
        full[f"spark.rapids.tpu.test.inject.{d}.transientCount"] = budget
    R.INJECTOR.reset()
    CN.reset()
    SCH.reset_scheduler()
    s = tpu_session(full)
    server = QueryServer(s)
    rnd = random.Random(seed)
    handles = []
    rejected = 0
    for i in range(n_queries):
        tenant = tenants[i % len(tenants)]
        n = 512 + rnd.randint(0, 1536)

        def build(n=n):
            return s.range(n, numPartitions=2)

        try:
            handles.append(server.submit(
                build, tenant=tenant, priority=rnd.choice((0, 0, 1))))
        except QueryRejected:
            rejected += 1
    # cancel a random slice mid-flight (queued or running)
    for h in rnd.sample(handles,
                        k=int(len(handles) * cancel_fraction)):
        server.cancel(h.query_id, reason="user")
    outcomes = {"ok": 0, "cancelled": 0, "error": 0}
    errors = []
    for h in handles:
        assert h.done.wait(timeout=timeout_s), (
            f"scheduler chaos deadlock: query {h.query_id} "
            f"({h.tenant}) still {h.state} after {timeout_s}s")
        if h.state == "OK":
            outcomes["ok"] += 1
        elif h.state == "CANCELLED":
            outcomes["cancelled"] += 1
        else:
            outcomes["error"] += 1
            errors.append(h.error)
    stats = server.stats()
    sched = SCH.peek_scheduler()
    server.shutdown()
    R.INJECTOR.reset()
    mgr = M.peek_manager()
    sem = peek_semaphore()
    return {
        "outcomes": outcomes,
        "errors": errors,
        "rejected": rejected,
        "stats": stats,
        "leaks": mgr.report_leaks() if mgr is not None else 0,
        "sem_holders": sem.holders if sem is not None else 0,
        "queued": sched.queued_total if sched is not None else 0,
        "running": sched.running_total if sched is not None else 0,
    }


def assert_fairness_invariant(stats: Dict[str, dict],
                              min_share: float = 0.25) -> None:
    """THE fairness invariant over a per-tenant scheduler ``stats``
    snapshot: among tenants of EQUAL weight, nobody gets less than
    ``min_share`` of its fair share of completions (fair share =
    the group's completions / group size).  Weighted tenants are
    compared only against peers of the same weight — a deliberately
    light tenant draining slower is policy, not unfairness."""
    groups: Dict[float, Dict[str, int]] = {}
    for name, t in stats.items():
        groups.setdefault(round(float(t["weight"]), 6), {})[name] = \
            int(t["completed"])
    for weight, members in groups.items():
        if len(members) < 2:
            continue
        total = sum(members.values())
        if total == 0:
            continue
        fair = total / len(members)
        for name, completed in members.items():
            assert completed >= min_share * fair, (
                f"tenant {name!r} (weight {weight}) completed "
                f"{completed} of a fair share of {fair:.1f} "
                f"(< {min_share:.0%}) — {members}")


def run_rendezvous_cancel_chaos(nprocs: int = 3,
                                cancel_pid: int = 0,
                                cancel_after_s: float = 0.2,
                                poll_ms: float = 50.0,
                                stage_timeout: float = 20.0) -> dict:
    """Cancel one participant of an in-flight rendezvous stage and
    verify the fast-abort contract: the stage is sized for nprocs+1
    entrants, so all ``nprocs`` clients park in the barrier (nobody can
    complete); cancelling one must (a) raise ``QueryCancelled`` on the
    cancelled participant and (b) fail every OTHER participant with a
    peer-tagged ``TerminalDeviceError`` promptly — nobody waits out the
    full stage deadline wedged on a cancelled peer.

    Returns ``{"records": [per-pid record], "cancel_elapsed": seconds
    from the cancel to the last participant unblocking}``.
    """
    import threading
    import time

    from spark_rapids_tpu.parallel import rendezvous as RD
    from spark_rapids_tpu.runtime import cancel as CN
    from spark_rapids_tpu.runtime import resilience as R

    R.INJECTOR.reset()
    policy = R.RetryPolicy(backoff_base_ms=0)
    # one seat never fills: every client parks until aborted
    coord = RD.RendezvousCoordinator(nprocs + 1)
    tokens = {pid: CN.CancelToken(query_id=1000 + pid, poll_ms=poll_ms)
              for pid in range(nprocs)}
    records: list = [None] * nprocs
    done = threading.Event()

    def run(pid: int) -> None:
        client = RD.RendezvousClient(coord.address, pid,
                                     default_timeout=stage_timeout)
        rec = {"pid": pid, "status": "ok", "error": None, "domain": None,
               "peer": None, "elapsed": 0.0}
        t0 = time.monotonic()
        try:
            RD.run_stage_epochs(
                client, "cancel-chaos",
                lambda epoch: client.allgather("cancel-chaos:gather",
                                               pid, epoch=epoch),
                policy=policy, token=tokens[pid])
        except CN.QueryCancelled as e:
            rec["status"] = "cancelled"
            rec["error"] = e
        except R.TerminalDeviceError as e:
            rec["status"] = "failed"
            rec["error"] = e
            rec["domain"] = e.domain
            rec["peer"] = e.peer
        except BaseException as e:
            rec["status"] = "error"
            rec["error"] = e
        finally:
            rec["elapsed"] = time.monotonic() - t0
            records[pid] = rec
            if all(r is not None for r in records):
                done.set()

    threads = [threading.Thread(target=run, args=(pid,), daemon=True)
               for pid in range(nprocs)]
    for t in threads:
        t.start()
    time.sleep(cancel_after_s)  # let everyone park in the barrier
    t_cancel = time.monotonic()
    tokens[cancel_pid].cancel(
        "user", f"rendezvous cancel chaos on pid {cancel_pid}")
    done.wait(timeout=stage_timeout + 10)
    cancel_elapsed = time.monotonic() - t_cancel
    coord.shutdown()
    hung = [i for i, r in enumerate(records) if r is None]
    assert not hung, f"rendezvous cancel participants hung: {hung}"
    return {"records": records, "cancel_elapsed": cancel_elapsed}


# ---------------------------------------------------------------------------
# preempt chaos harness: suspend mid-domain, resume, demand bit-identity
# ---------------------------------------------------------------------------

def run_preempt_chaos(df_builder: Callable[[TpuSession], "object"],
                      inject: Dict[str, Tuple[int, int]],
                      conf: Optional[Dict] = None,
                      poll_ms: float = 50.0,
                      seed: int = 0,
                      timeout_s: float = 60.0) -> dict:
    """Run one query with ``inject``'s domains armed, suspend it at a
    randomized point while it is provably mid-domain (cooperative
    preemption through the cancel plane's yield points), hold it parked
    for a randomized interval, resume it, and let it finish.

    Mirrors ``run_cancel_chaos``: the schedule keeps the query spinning
    (large transient budget, backoff pinned to ~2x the poll interval)
    so the worker thread lives inside yield points when the suspend
    request lands.  The clean golden run (for the bit-identity
    comparison) executes FIRST, deliberately: it warms the kernel
    cache, so the chaos run's pump threads are never wedged inside a
    multi-hundred-ms fresh compile when the suspend request lands and
    the 2x-poll permit-drain bound is honest.  (Consequence: do not arm
    the ``compile`` domain here — its injection points are pre-cached
    away.  The result cache is pinned OFF for the chaos session so the
    warm run cannot short-circuit it.)

    Returns a record::

        {"status": "completed" | "cancelled" | "error",
         "error":       the raised exception, if any,
         "fired":       the domain whose counter moved (None if raced),
         "suspend_sent": True if suspend_query found the query in flight,
         "suspended":   True if the token reached SUSPENDED,
         "latency_s":   token-recorded request->parked latency,
         "preempt_count": completed suspend/resume cycles on the token,
         "sem_holders_during": semaphore holders once parked and
                        drained (must be 0 for a lone query),
         "sem_drain_s":  suspend request -> zero holders (all the
                        query's pump threads yielded their permits),
         "result", "golden": the two Arrow tables,
         "leaks", "sem_holders", "spill_files": steady-state checks}
    """
    import os
    import threading
    import time

    from spark_rapids_tpu.runtime import cancel as CN
    from spark_rapids_tpu.runtime import memory as M
    from spark_rapids_tpu.runtime import resilience as R
    from spark_rapids_tpu.runtime.semaphore import peek_semaphore

    backoff_ms = max(int(2 * poll_ms), 1)
    full: Dict = {
        "spark.rapids.tpu.query.cancelPollMs": int(poll_ms),
        "spark.rapids.tpu.retry.backoffBaseMs": backoff_ms,
        "spark.rapids.tpu.retry.backoffMaxMs": backoff_ms,
        "spark.rapids.tpu.retry.maxAttempts": 1_000_000,
        "spark.rapids.tpu.retry.budgetPerQuery": 0,  # unlimited
    }
    full.update(conf or {})
    for d, (at, budget) in inject.items():
        full[f"spark.rapids.tpu.test.inject.{d}.at"] = at
        full[f"spark.rapids.tpu.test.inject.{d}.transientCount"] = budget
    full["spark.rapids.tpu.cache.enabled"] = False
    R.INJECTOR.reset()
    CN.reset()
    golden = df_builder(tpu_session(dict(conf or {}))).toArrow()
    s = tpu_session(full)
    df = df_builder(s)
    base = dict(R._TM_INJECTED.child_values())
    box: Dict = {}

    def run():
        try:
            box["result"] = df.toArrow()
        except BaseException as e:
            box["error"] = e

    worker = threading.Thread(target=run, daemon=True,
                              name="tpuq-preempt-chaos-query")
    worker.start()
    # wait until the query is demonstrably inside an armed domain
    fired = None
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline and worker.is_alive():
        cur = R._TM_INJECTED.child_values()
        fired = next((d for d in inject
                      if cur.get(d, 0) > base.get(d, 0)), None)
        if fired is not None:
            break
        time.sleep(0.002)
    rnd = random.Random(seed)
    time.sleep(rnd.uniform(0.0, backoff_ms / 1000.0))
    active = CN.active_queries()
    qid = active[0] if active else None
    tok = CN.get_token(qid) if qid is not None else None
    t_req = time.monotonic()
    suspend_sent = qid is not None and CN.suspend_query(
        qid, detail=f"preempt-chaos mid-{fired or 'unknown'}")
    # observe the park: ``suspended()`` flips when the FIRST pump
    # thread parks; sibling pump threads may still be between yield
    # points holding permits, so the permit-drain clock keeps running
    # until holders hits zero (this is a lone query — nobody else can
    # be holding)
    suspended = False
    sem_holders_during = None
    sem_drain_s = None
    if suspend_sent and tok is not None:
        park_deadline = time.monotonic() + timeout_s
        while time.monotonic() < park_deadline and worker.is_alive():
            if tok.suspended():
                suspended = True
                break
            time.sleep(0.001)
        if suspended:
            sem = peek_semaphore()
            while (sem is not None and sem.holders > 0
                   and time.monotonic() < park_deadline):
                time.sleep(0.001)
            sem_drain_s = time.monotonic() - t_req
            sem_holders_during = sem.holders if sem is not None else 0
            # hold it parked across a few poll intervals, then resume
            time.sleep(rnd.uniform(0.0, 2 * backoff_ms / 1000.0))
            CN.resume_query(qid)
        elif tok.preempt_pending():
            # raced query completion before any yield point: withdraw
            CN.resume_query(qid)
    worker.join(timeout=timeout_s)
    R.INJECTOR.reset()
    assert not worker.is_alive(), (
        f"query failed to resume within {timeout_s}s "
        f"(mid-{fired}; suspended={suspended})")
    err = box.get("error")
    if isinstance(err, CN.QueryCancelled):
        status = "cancelled"
    elif err is not None:
        status = "error"
    else:
        status = "completed"
    # a COMPLETED query legitimately leaves scan-cache residency alive
    # (shared, table-lifetime) — under pressure confs it sits spilled
    # on disk.  Evict it so "stranded spill files" below means actual
    # orphans, not the cache doing its job.
    from spark_rapids_tpu.exec.basic import clear_scan_cache
    clear_scan_cache()
    mgr = M.peek_manager()
    sem = peek_semaphore()
    spill_files = []
    if mgr is not None and os.path.isdir(mgr.spill_path):
        spill_files = sorted(os.listdir(mgr.spill_path))
    return {
        "status": status,
        "error": err,
        "fired": fired,
        "suspend_sent": suspend_sent,
        "suspended": suspended,
        "latency_s": tok.suspend_latency_s if tok is not None else None,
        "preempt_count": tok.preempt_count if tok is not None else 0,
        "final_preempt_state": (tok.preempt_state
                                if tok is not None else None),
        "sem_holders_during": sem_holders_during,
        "sem_drain_s": sem_drain_s,
        "result": box.get("result"),
        "golden": golden,
        "leaks": mgr.report_leaks() if mgr is not None else 0,
        "sem_holders": sem.holders if sem is not None else 0,
        "spill_files": spill_files,
    }


def assert_preempt_invariant(
        df_builder: Callable[[TpuSession], "object"],
        inject: Dict[str, Tuple[int, int]],
        conf: Optional[Dict] = None,
        poll_ms: float = 50.0,
        seed: int = 0) -> dict:
    """Assert THE preemption invariant for one query + injection
    schedule: a suspend fired mid-domain parks the query within 2x
    ``cancelPollMs`` with every semaphore permit released; after resume
    the query completes **bit-identical** to an unpreempted run of the
    same plan, and the engine is back at a clean steady state — zero
    leaked spillables, zero semaphore holders, an empty spill dir.
    The wedge guard rides along: whatever happened mid-flight, the
    token must END in RUN or RESUMED — never stuck in
    SUSPEND_REQUESTED/SUSPENDED after the query finished."""
    from spark_rapids_tpu.runtime import cancel as CN
    from spark_rapids_tpu.utils.asserts import assert_tables_equal

    rec = run_preempt_chaos(df_builder, inject, conf=conf,
                            poll_ms=poll_ms, seed=seed)
    assert rec["suspend_sent"], (
        f"query finished before the suspend could fire "
        f"(mid-{rec['fired']}): {rec['status']}")
    assert rec["suspended"], (
        f"suspend requested mid-{rec['fired']} but the query never "
        f"parked: {rec['status']} ({rec['error']!r})")
    assert rec["status"] == "completed", (
        f"expected clean completion after resume, got "
        f"{rec['status']}: {rec['error']!r}")
    assert rec["latency_s"] is not None, "no suspend latency recorded"
    bound = 2.0 * poll_ms / 1000.0
    assert rec["latency_s"] < bound, (
        f"suspend latency {rec['latency_s']:.3f}s >= 2x cancelPollMs "
        f"({bound:.3f}s) mid-{rec['fired']}")
    assert rec["sem_holders_during"] == 0, (
        f"{rec['sem_holders_during']} semaphore permits still held "
        f"while SUSPENDED — preemption must release the device")
    assert rec["sem_drain_s"] is not None and rec["sem_drain_s"] < bound, (
        f"permits drained {rec['sem_drain_s']}s after the suspend "
        f"request (>= 2x cancelPollMs {bound:.3f}s) mid-{rec['fired']}")
    assert_tables_equal(rec["golden"], rec["result"])
    assert rec["leaks"] == 0, (
        f"{rec['leaks']} spillables leaked after preempt cycle "
        f"mid-{rec['fired']}")
    assert rec["sem_holders"] == 0, (
        f"{rec['sem_holders']} semaphore holders after preempt cycle")
    assert not rec["spill_files"], (
        f"spill files stranded after preempt cycle: "
        f"{rec['spill_files']}")
    assert rec["final_preempt_state"] in (CN.PREEMPT_RUN,
                                          CN.PREEMPT_RESUMED), (
        f"token wedged in {rec['final_preempt_state']} after the query "
        f"finished — a suspend requester must never leave a query "
        f"parked (mid-{rec['fired']})")
    return rec


# ---------------------------------------------------------------------------
# tenancy soak: sustained mixed hot/cold multi-tenant load
# ---------------------------------------------------------------------------

def _pctile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (0 when
    empty)."""
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1,
            max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def run_tenancy_soak(duration_s: float = 3.0,
                     in_flight: int = 8,
                     tenants: Optional[Dict[str, dict]] = None,
                     conf: Optional[Dict] = None,
                     seed: int = 0,
                     timeout_s: float = 120.0,
                     make_query: Optional[Callable] = None) -> dict:
    """Sustained-load soak for the preemptive-tenancy planes: keep
    ``in_flight`` submissions outstanding across mixed hot/cold tenants
    through a ``QueryServer`` for ``duration_s``, resubmitting as
    completions land, then drain.

    ``tenants`` maps name -> spec: ``{"priority": int, "hot": bool,
    "rows": int}``.  Hot tenants resubmit the SAME plan (result-cache
    hits once warm); cold tenants vary the plan every submission.
    Preemption, HBM-share enforcement, and the result cache run with
    whatever the caller's ``conf`` enables.  ``make_query(session,
    name, spec, rnd, i) -> DataFrame | zero-arg callable`` overrides
    the default ``session.range`` workload (the bench drives TPC-H
    plans through it).

    Returns a record::

        {"duration_s", "in_flight",
         "tenants": {name: {"submitted", "completed", "errors",
                            "rejected", "p50_ms", "p99_ms"}},
         "outcomes": {"ok": n, "cancelled": n, "error": n},
         "errors":  [the non-cancel exceptions, if any],
         "preempt": {"requests", "suspended", "resumed"}  (TM deltas),
         "hbm_breaches": tenant HBM budget breaches (manager metric),
         "sched_stats": per-tenant scheduler accounting,
         "zero_deadlock": every submission drained inside timeout_s,
         "zero_leak": no spillables/permits/spill files left behind,
         "ledgers_closed": every recorded attribution ledger closed}
    """
    import time

    from spark_rapids_tpu.runtime import cancel as CN
    from spark_rapids_tpu.runtime import memory as M
    from spark_rapids_tpu.runtime import scheduler as SCH
    from spark_rapids_tpu.runtime.semaphore import peek_semaphore
    from spark_rapids_tpu.sql.server import QueryRejected, QueryServer

    tenants = tenants or {
        "hot-a": {"priority": 0, "hot": True, "rows": 2048},
        "hot-b": {"priority": 0, "hot": True, "rows": 3072},
        "cold-a": {"priority": 0, "hot": False, "rows": 4096},
        "urgent": {"priority": 10, "hot": False, "rows": 1024},
    }
    full: Dict = {
        "spark.rapids.tpu.scheduler.maxConcurrentQueries": 2,
        "spark.rapids.tpu.scheduler.preempt.enabled": True,
        "spark.rapids.tpu.scheduler.preempt.graceMs": 50,
        "spark.rapids.tpu.scheduler.preempt.minRunMs": 10,
        "spark.rapids.tpu.query.cancelPollMs": 20,
        "spark.rapids.tpu.retry.backoffBaseMs": 0,
        "spark.rapids.tpu.cache.enabled": True,
    }
    full.update(conf or {})
    CN.reset()
    SCH.reset_scheduler()
    s = tpu_session(full)
    server = QueryServer(s)
    rnd = random.Random(seed)
    names = sorted(tenants)
    per = {n: {"submitted": 0, "completed": 0, "errors": 0,
               "rejected": 0, "lat": []} for n in names}
    outcomes = {"ok": 0, "cancelled": 0, "error": 0}
    errors: list = []
    pending: list = []
    pre_req = CN._TM_PREEMPT_REQ.value
    pre_sus = CN._TM_PREEMPT_SUSPENDED.value
    pre_res = CN._TM_PREEMPT_RESUMED.value
    mgr0 = M.peek_manager()
    breaches0 = mgr0.metrics["tenantBreaches"] if mgr0 is not None else 0
    counter = [0]

    def submit_one() -> None:
        name = names[counter[0] % len(names)]
        i = counter[0]
        counter[0] += 1
        spec = tenants[name]
        if make_query is not None:
            build = make_query(s, name, spec, rnd, i)
        else:
            rows = int(spec.get("rows", 2048))
            if not spec.get("hot"):
                rows += 64 * rnd.randint(0, 63)  # vary: cache-cold

            def build(rows=rows):
                return s.range(rows, numPartitions=2)

        try:
            h = server.submit(build, tenant=name,
                              priority=int(spec.get("priority", 0)))
            per[name]["submitted"] += 1
            pending.append((h, name))
        except QueryRejected:
            per[name]["rejected"] += 1

    def reap(h, name) -> None:
        if h.state == "OK":
            outcomes["ok"] += 1
        elif h.state == "CANCELLED":
            outcomes["cancelled"] += 1
        else:
            outcomes["error"] += 1
            per[name]["errors"] += 1
            errors.append(h.error)
        per[name]["completed"] += 1
        if h.wall_s is not None:
            per[name]["lat"].append(h.wall_s)

    for _ in range(in_flight):
        submit_one()
    deadline = time.monotonic() + duration_s
    while time.monotonic() < deadline:
        done_now = [(h, n) for h, n in pending if h.done.is_set()]
        for h, n in done_now:
            pending.remove((h, n))
            reap(h, n)
            if time.monotonic() < deadline:
                submit_one()
        if not done_now:
            time.sleep(0.002)
    # drain
    zero_deadlock = True
    drain_deadline = time.monotonic() + timeout_s
    for h, n in pending:
        if not h.done.wait(timeout=max(
                0.0, drain_deadline - time.monotonic())):
            zero_deadlock = False
            continue
        reap(h, n)
    sched_stats = server.stats()
    sched = SCH.peek_scheduler()
    server.shutdown()
    if sched is not None and (sched.queued_total or sched.running_total):
        zero_deadlock = False
    mgr = M.peek_manager()
    sem = peek_semaphore()
    import os
    spill_files = []
    if mgr is not None and os.path.isdir(mgr.spill_path):
        spill_files = sorted(os.listdir(mgr.spill_path))
    zero_leak = ((mgr.report_leaks() if mgr is not None else 0) == 0
                 and (sem.holders if sem is not None else 0) == 0
                 and not spill_files)
    entries = s.query_history()
    closed = [bool((e.get("attribution") or {}).get("closed", True))
              for e in entries]
    for n in names:
        lat = sorted(per[n].pop("lat"))
        per[n]["p50_ms"] = round(_pctile(lat, 0.50) * 1000.0, 3)
        per[n]["p99_ms"] = round(_pctile(lat, 0.99) * 1000.0, 3)
    return {
        "duration_s": duration_s,
        "in_flight": in_flight,
        "tenants": per,
        "outcomes": outcomes,
        "errors": errors,
        "preempt": {
            "requests": CN._TM_PREEMPT_REQ.value - pre_req,
            "suspended": CN._TM_PREEMPT_SUSPENDED.value - pre_sus,
            "resumed": CN._TM_PREEMPT_RESUMED.value - pre_res,
        },
        "hbm_breaches": ((mgr.metrics["tenantBreaches"]
                          if mgr is not None else 0) - breaches0),
        "sched_stats": sched_stats,
        "zero_deadlock": zero_deadlock,
        "zero_leak": zero_leak,
        "ledgers_closed": all(closed) if closed else True,
    }


# ---------------------------------------------------------------------------
# cluster tenancy soak: multi-executor enforcement over the rendezvous
# ---------------------------------------------------------------------------

def run_cluster_tenancy_soak(duration_s: float = 3.0,
                             executors: int = 2,
                             in_flight: int = 8,
                             tenants: Optional[Dict[str, dict]] = None,
                             conf: Optional[Dict] = None,
                             seed: int = 0,
                             timeout_s: float = 120.0,
                             heartbeat_s: float = 0.05,
                             arbiter_grace_s: float = 0.05,
                             inject_executor_loss: bool = True,
                             inject_coordinator_restart: bool = True,
                             inject: Optional[Dict[str, Tuple[int, int]]]
                             = None,
                             make_query: Optional[Callable] = None
                             ) -> dict:
    """Fault-injected soak for CLUSTER-WIDE tenancy enforcement: host
    ``executors`` thread-backed executors in this process — each with
    its OWN non-singleton ``QueryScheduler``, a ``QueryServer`` pinned
    to it, and a ``TenancyAgent`` heartbeating per-tenant reports to a
    real TCP ``RendezvousCoordinator`` — then drive mixed hot/cold
    tenant load through all of them for ``duration_s`` while the
    coordinator's ``TenancyArbiter`` fans suspend/resume/shed
    directives back out on the heartbeat responses.

    Three failure domains fire mid-soak (each individually gateable):

    * ``inject_executor_loss`` — the last executor ``simulate_death``s
      ~35% in: its lease expires, the arbiter forgets its report and
      hosted suspends, and any suspend lease it held force-resumes
      locally (``tpuq_preempt_force_resumed_total``) — never a wedged
      token.
    * ``inject_coordinator_restart`` — ~60% in the coordinator is shut
      down, agents miss heartbeats into degraded local-only mode
      (``tpuq_tenancy_degraded_total``), and a NEW coordinator binds
      the SAME port; agents re-sync on the first round trip.
    * ``inject`` — ``{"tenancy": (at, transient_count)}`` arms the
      ``tenancy`` chaos domain: an injected fault in the directive
      path drops one beat's directives; lease renewal self-heals.

    Returns a record with the all-green verdicts the bench asserts:
    per-tenant ``slo`` (p99 met or the breach was recorded+shed —
    never silent), ``wedged_tokens`` (must be 0), ``zero_deadlock``,
    ``zero_leak``, ``ledgers_closed``, and a ``cluster`` block
    (directives applied per kind, stale drops, re-syncs, degraded
    entries, force-resumes, max observed directive fan-out latency)."""
    import os
    import threading  # noqa: F401  (QueryServer workers)
    import time

    from spark_rapids_tpu.parallel import rendezvous as PR
    from spark_rapids_tpu.runtime import cancel as CN
    from spark_rapids_tpu.runtime import memory as M
    from spark_rapids_tpu.runtime import resilience as R
    from spark_rapids_tpu.runtime import scheduler as SCH
    from spark_rapids_tpu.runtime import tenancy as TN
    from spark_rapids_tpu.runtime.semaphore import peek_semaphore
    from spark_rapids_tpu.sql.server import QueryRejected, QueryServer

    tenants = tenants or {
        # the hog floods every executor with longer queries — the hold
        # must comfortably exceed the arbiter grace so the hog reliably
        # occupies every slot past the starvation threshold (a 20 ms
        # hold on a warm runtime drains queues too fast to ever starve
        # anyone, and the soak then proves nothing about directives)
        "hog": {"priority": 0, "mix": 4, "rows": 8192, "hold_s": 0.08},
        # ...and the latency tenant's short queries starve behind them
        # until the cluster arbiter preempts the hog's largest victim
        "latency": {"priority": 0, "mix": 1, "rows": 2048,
                    "hold_s": 0.0},
    }
    inject = {"tenancy": (6, 2)} if inject is None else inject
    full: Dict = {
        "spark.rapids.tpu.scheduler.maxConcurrentQueries": 1,
        "spark.rapids.tpu.scheduler.maxQueuedQueries": 64,
        # local arbitration OFF: every suspension in this soak is
        # attributably a CLUSTER directive
        "spark.rapids.tpu.scheduler.preempt.enabled": False,
        "spark.rapids.tpu.scheduler.preempt.graceMs": 250,
        "spark.rapids.tpu.scheduler.preempt.minRunMs": 10,
        "spark.rapids.tpu.scheduler.tenantSloP99Ms": 60_000,
        "spark.rapids.tpu.scheduler.sloWindow": 16,
        "spark.rapids.tpu.tenancy.enabled": True,
        "spark.rapids.tpu.query.cancelPollMs": 10,
        "spark.rapids.tpu.retry.backoffBaseMs": 0,
        "spark.rapids.tpu.cache.enabled": False,
    }
    full.update(conf or {})
    for d, (at, budget) in (inject or {}).items():
        full[f"spark.rapids.tpu.test.inject.{d}.at"] = at
        full[f"spark.rapids.tpu.test.inject.{d}.transientCount"] = budget
    R.INJECTOR.reset()
    CN.reset()
    SCH.reset_scheduler()
    TN.reset_agent()
    s = tpu_session(full)
    conf_obj = s.rapids_conf()
    rnd = random.Random(seed)
    lease_s = max(0.4, 8.0 * heartbeat_s)

    def _mk_coord():
        c = PR.RendezvousCoordinator(executors, lease_s=lease_s)
        c.tenancy.grace_s = arbiter_grace_s
        c.tenancy.suspend_ttl_s = max(4.0 * heartbeat_s, 0.2)
        return c

    coord = _mk_coord()
    port = int(coord.address.rsplit(":", 1)[1])
    scheds, servers, agents, clients = [], [], [], []
    for pid in range(executors):
        sched = SCH.QueryScheduler(conf_obj)
        scheds.append(sched)
        servers.append(QueryServer(s, scheduler=sched))
        agent = TN.TenancyAgent(sched, conf=conf_obj)
        agents.append(agent)
        client = PR.RendezvousClient(coord.address, pid)
        client.start_heartbeat(heartbeat_s, payload_fn=agent.payload,
                               on_response=agent.on_heartbeat,
                               on_miss=agent.on_miss)
        clients.append(client)
    TN.set_agent(agents[0])   # the HBM arbiter's breach-relay target

    names = sorted(tenants)
    mix = [n for n in names for _ in range(
        max(1, int(tenants[n].get("mix", 1))))]
    per = {n: {"submitted": 0, "completed": 0, "errors": 0,
               "rejected": 0, "lat": []} for n in names}
    outcomes = {"ok": 0, "cancelled": 0, "error": 0}
    errors: list = []
    pending: list = []
    live = list(range(executors))
    counter = [0]
    fr0 = CN._TM_PREEMPT_FORCE_RESUMED.value
    inj_base = dict(R._TM_INJECTED.child_values())

    def submit_one() -> None:
        i = counter[0]
        counter[0] += 1
        name = mix[i % len(mix)]
        spec = tenants[name]
        epid = live[i % len(live)]
        if make_query is not None:
            build = make_query(s, name, spec, rnd, i)
        else:
            rows = int(spec.get("rows", 2048)) + 64 * rnd.randint(0, 15)
            hold = float(spec.get("hold_s", 0.0))

            def build(rows=rows, hold=hold):
                # the hold keeps the ticket RUNNING long enough to be
                # an eligible remote victim (past preempt.minRunMs);
                # the suspend itself parks at toArrow's preempt points
                if hold:
                    time.sleep(hold)
                return s.range(rows, numPartitions=2)

        try:
            h = servers[epid].submit(
                build, tenant=name,
                priority=int(spec.get("priority", 0)))
            per[name]["submitted"] += 1
            pending.append((h, name, epid))
        except QueryRejected:
            per[name]["rejected"] += 1

    def reap(h, name) -> None:
        if h.state == "OK":
            outcomes["ok"] += 1
        elif h.state == "CANCELLED":
            outcomes["cancelled"] += 1
        else:
            outcomes["error"] += 1
            per[name]["errors"] += 1
            errors.append(h.error)
        per[name]["completed"] += 1
        if h.wall_s is not None:
            per[name]["lat"].append(h.wall_s)

    faults = {"executor_lost": None, "coordinator_restarted": False,
              "degraded_window_s": 0.0}
    arbiter_pre: Optional[dict] = None
    t_start = time.monotonic()
    loss_at = t_start + 0.35 * duration_s
    restart_at = t_start + 0.60 * duration_s
    deadline = t_start + duration_s
    for _ in range(in_flight):
        submit_one()
    while time.monotonic() < deadline:
        now = time.monotonic()
        if (inject_executor_loss and faults["executor_lost"] is None
                and now >= loss_at and len(live) > 1):
            lost = live.pop()   # the last executor goes dark
            clients[lost].simulate_death()
            faults["executor_lost"] = lost
        if (inject_coordinator_restart
                and not faults["coordinator_restarted"]
                and now >= restart_at):
            arbiter_pre = coord.tenancy.stats()
            coord.shutdown()
            # let agents miss into degraded local-only mode
            gap = max(0.25,
                      (agents[0].degraded_after + 1) * heartbeat_s)
            time.sleep(gap)
            faults["degraded_window_s"] = gap
            for attempt in range(20):
                try:
                    coord = PR.RendezvousCoordinator(
                        executors, port=port, lease_s=lease_s)
                    break
                except OSError:
                    time.sleep(0.05)
            coord.tenancy.grace_s = arbiter_grace_s
            coord.tenancy.suspend_ttl_s = max(4.0 * heartbeat_s, 0.2)
            faults["coordinator_restarted"] = True
        done_now = [(h, n, p) for h, n, p in pending
                    if h.done.is_set()]
        for h, n, p in done_now:
            pending.remove((h, n, p))
            reap(h, n)
            if time.monotonic() < deadline:
                submit_one()
        if not done_now:
            time.sleep(0.002)
    # drain
    zero_deadlock = True
    drain_deadline = time.monotonic() + timeout_s
    wedged = 0
    for h, n, p in pending:
        if not h.done.wait(timeout=max(
                0.0, drain_deadline - time.monotonic())):
            zero_deadlock = False
            tok = CN.get_token(h.query_id)
            if tok is not None and tok.preempt_pending():
                wedged += 1
            continue
        reap(h, n)
    for client in clients:
        client.stop_heartbeat()
    sched_stats = {i: sch.stats() for i, sch in enumerate(scheds)}
    agent_stats = [a.stats() for a in agents]
    arbiter_stats = coord.tenancy.stats()
    for server in servers:
        server.shutdown(timeout_s=10.0)
    coord.shutdown()
    for sch in scheds:
        with sch._cv:
            wedged += len(sch._suspended)
        if sch.queued_total or sch.running_total:
            zero_deadlock = False
    for qid in CN.active_queries():
        tok = CN.get_token(qid)
        if tok is not None and tok.preempt_pending():
            wedged += 1
    R.INJECTOR.reset()
    TN.reset_agent()
    mgr = M.peek_manager()
    sem = peek_semaphore()
    spill_files = []
    if mgr is not None and os.path.isdir(mgr.spill_path):
        spill_files = sorted(os.listdir(mgr.spill_path))
    zero_leak = ((mgr.report_leaks() if mgr is not None else 0) == 0
                 and (sem.holders if sem is not None else 0) == 0
                 and not spill_files)
    entries = s.query_history()
    closed = [bool((e.get("attribution") or {}).get("closed", True))
              for e in entries]
    # per-tenant SLO verdict ACROSS executors: p99 within target on
    # every executor, or the breach was RECORDED and shed — a breach
    # the guardrail never saw is the only failing shape
    slo = {}
    for name in names:
        target, breaches, obs = 0, 0, []
        for st in sched_stats.values():
            t = st.get(name)
            if not t:
                continue
            target = max(target, int(t["slo_p99_ms"]))
            breaches += int(t["slo_breaches"])
            if t["observed_p99_ms"] is not None:
                obs.append(float(t["observed_p99_ms"]))
        met = target <= 0 or all(o <= target for o in obs)
        slo[name] = {"target_ms": target,
                     "observed_p99_ms": max(obs) if obs else None,
                     "breaches": breaches,
                     "met_or_shed": bool(met or breaches > 0)}
    inj_now = R._TM_INJECTED.child_values()
    cluster = {
        "applied": {k: sum(a["applied"].get(k, 0) for a in agent_stats)
                    for k in ("suspend", "resume", "shed", "unshed")},
        "stale": sum(a["stale"] for a in agent_stats),
        "resyncs": sum(a["resyncs"] for a in agent_stats),
        "degraded_entries": sum(a["degraded_entries"]
                                for a in agent_stats),
        "force_resumed": CN._TM_PREEMPT_FORCE_RESUMED.value - fr0,
        "max_fanout_s": max([a["max_fanout_s"] for a in agent_stats]
                            or [0.0]),
        "injected_faults": (inj_now.get("tenancy", 0)
                            - inj_base.get("tenancy", 0)),
        "arbiter": arbiter_stats,
        "arbiter_pre_restart": arbiter_pre,
    }
    for n in names:
        lat = sorted(per[n].pop("lat"))
        per[n]["p50_ms"] = round(_pctile(lat, 0.50) * 1000.0, 3)
        per[n]["p99_ms"] = round(_pctile(lat, 0.99) * 1000.0, 3)
    return {
        "duration_s": duration_s,
        "executors": executors,
        "heartbeat_s": heartbeat_s,
        "in_flight": in_flight,
        "tenants": per,
        "outcomes": outcomes,
        "errors": errors,
        "faults": faults,
        "slo": slo,
        "cluster": cluster,
        "sched_stats": sched_stats,
        "agent_stats": agent_stats,
        "wedged_tokens": wedged,
        "zero_deadlock": zero_deadlock,
        "zero_leak": zero_leak,
        "ledgers_closed": all(closed) if closed else True,
    }
