"""End-to-end CPU-vs-TPU oracle harness for DataFrame queries.

[REF: integration_tests/src/main/python/asserts.py ::
 assert_gpu_and_cpu_are_equal_collect, spark_session.py ::
 with_cpu_session/with_gpu_session] — the workhorse test pattern: build
the same query twice, once with ``spark.rapids.sql.enabled=false`` (the
numpy oracle path) and once ``=true`` with test mode on (any unexpected
fallback raises), and compare collected results.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from spark_rapids_tpu.sql.session import TpuSession


def tpu_session(conf: Optional[Dict] = None) -> TpuSession:
    base = {"spark.rapids.sql.enabled": True,
            "spark.rapids.sql.test.enabled": True}
    base.update(conf or {})
    return TpuSession(base)


def cpu_session(conf: Optional[Dict] = None) -> TpuSession:
    base = {"spark.rapids.sql.enabled": False}
    base.update(conf or {})
    return TpuSession(base)


def assert_tpu_and_cpu_are_equal_collect(
    df_builder: Callable[[TpuSession], "object"],
    conf: Optional[Dict] = None,
    ignore_order: bool = False,
    approx_float: bool = False,
    allow_non_tpu: Optional[list] = None,
):
    """df_builder: session -> DataFrame.  Runs both ways and compares."""
    from spark_rapids_tpu.utils.asserts import assert_tables_equal

    tconf = dict(conf or {})
    if allow_non_tpu:
        tconf["spark.rapids.sql.test.allowedNonGpu"] = ",".join(allow_non_tpu)
    t = df_builder(tpu_session(tconf)).toArrow()
    c = df_builder(cpu_session(conf)).toArrow()
    assert_tables_equal(c, t, ignore_order=ignore_order,
                        approx_float=approx_float)
    return c, t


def assert_tpu_fallback_collect(
    df_builder: Callable[[TpuSession], "object"],
    fallback_exec: str,
    conf: Optional[Dict] = None,
    ignore_order: bool = False,
):
    """Assert the query still works WITH the plugin on but the named exec
    falls back to CPU [REF: asserts.py :: assert_gpu_fallback_collect]."""
    from spark_rapids_tpu.plan.overrides import apply_overrides
    from spark_rapids_tpu.plan.planner import plan_physical
    from spark_rapids_tpu.utils.asserts import assert_tables_equal

    tconf = dict(conf or {})
    tconf["spark.rapids.sql.test.enabled"] = False
    s = tpu_session(tconf)
    df = df_builder(s)
    rc = s.rapids_conf()
    result = apply_overrides(plan_physical(df._plan, rc), rc)
    lines = [ln.strip() for ln in result.plan.tree_string().splitlines()]
    # CPU nodes print bare ("Project [...]"); TPU ones as "*TpuProject".
    assert any(ln.startswith(fallback_exec) for ln in lines), (
        f"expected {fallback_exec} to fall back to CPU; plan:\n"
        + "\n".join(lines))
    t = df.toArrow()
    c = df_builder(cpu_session(conf)).toArrow()
    assert_tables_equal(c, t, ignore_order=ignore_order)
