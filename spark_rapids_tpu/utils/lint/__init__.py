"""Engine invariant analyzer — AST lint framework.

[REF: the reference enforces these invariants with Scala's type system
 plus a scalastyle/IWYU lint wall run in premerge CI; this engine is
 Python, so the equivalent is an AST pass over the package run as a
 tier-1 gate.]

Run:  ``python -m spark_rapids_tpu.utils.lint``  — nonzero exit on any
finding.  The same entry is asserted clean by tier-1
(``tests/test_lint.py``) and reported by ``docs_gen.main``.

Rules (catalog in docs/static_analysis.md):

``lock-order``        static lock-acquisition graph from nested
                      ``with <lock>`` / ``.acquire()`` scopes; flags
                      cycles, non-reentrant self-acquisition, and
                      edges inverting the canonical order
``conf-drift``        string-literal ``conf.get("spark.rapids...")``
                      keys must exist in the conf.py registry, and
                      every registered key must have a read site
``failure-domain``    ``raise`` sites of device/retryable error types
                      in runtime/ | shuffle/ | parallel/ must carry a
                      failure domain (no bare RuntimeError bypasses
                      the RetryPolicy's domain routing)
``host-sync-in-jit``  ``np.asarray`` / ``float()`` / ``.item()`` /
                      ``.block_until_ready()`` on traced values inside
                      jit-wrapped kernel builders (TPU hot-path purity)
``blocking-wait``     bare ``.wait()`` / ``time.sleep`` in runtime/ |
                      parallel/ that the cancellation layer cannot
                      interrupt (the former regex gate, now AST-exact)
``op-stats``          every concrete exec's ``execute`` must be the
                      auto-wrapped one: no inheriting it from a
                      non-exec mixin, no module-level monkey-patching
                      past the stats/trace/cancel pump wrapper
``scheduler-bypass``  ``get_semaphore`` calls / ``DeviceSemaphore``
                      construction outside the scheduler's admission
                      path (runtime/scheduler.py, runtime/semaphore.py)
                      — device admission must flow through
                      ``runtime.scheduler.device_hold`` so multi-tenant
                      fairness and load shedding see all traffic
``raw-jit``           ``jax.jit`` calls/decorators outside
                      runtime/kernel_cache.py — raw jits bypass the
                      fingerprint cache, compile-storm telemetry, the
                      compile failure domain, and the persistent
                      on-disk cache (kernel.cacheDir)
``exchange-purity``   host materialization (``device_get`` /
                      ``np.asarray`` / ``.addressable_shards`` /
                      ``num_rows_host``) inside the compiled
                      exchange's ``build_*_program`` builders in
                      parallel/shuffle.py | exec/distributed.py |
                      exec/exchange.py — a stage seam must stay one
                      device collective, host pulls reintroduce the
                      round-trip the exchange plane was rebuilt to
                      kill
``kernel-purity``     the same host-materialization flags inside ANY
                      function of the kernel plane (kernels/ minus the
                      dispatcher in __init__.py, whose one ``bool(ok)``
                      sync is the exactness protocol) — kernel bodies
                      are traced device code; a host pull there
                      serializes the async pump on every batch
``adaptive-purity``   the same host-materialization flags inside ANY
                      function of the adaptive plane (adaptive/) —
                      replanner decisions must come from recorded
                      stats, history, or conf, never a fresh device
                      sync in the planning path; measurement lives in
                      the exec layer, which hands the numbers in
``cache-safety``      mutation of a session ``_catalog`` entry or a
                      relation ``fingerprint`` outside the
                      fingerprint-bump chokepoint
                      (cache/fingerprints.py, sql/session.py) —
                      changing a registered input without re-minting
                      its digest is exactly the bug that serves stale
                      cached results
``bucket-accounting`` every string-literal stage at a
                      ``.timer("<stage>")`` or
                      ``.begin/.span(op, "<stage>")`` site must map to
                      a declared attribution bucket
                      (runtime/attribution.py STAGE_BUCKETS) — an
                      unmapped stage silently grows the per-query
                      ``unaccounted`` gap until the time books stop
                      closing

A deliberate violation carries a same-line or preceding-line
annotation::

    # lint: exempt(<rule>): <why>

The reason is mandatory — an empty reason is itself a finding.  The
legacy ``# cancel-exempt: <why>`` annotation is honored as an alias
for ``exempt(blocking-wait)``, ``# jit-exempt: <why>`` as an alias
for ``exempt(raw-jit)``, and ``# attribution-exempt: <why>`` as an
alias for ``exempt(bucket-accounting)``.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence

EXEMPT_RE = re.compile(
    r"#\s*lint:\s*exempt\(\s*([\w*-]+(?:\s*,\s*[\w*-]+)*)\s*\)"
    r"\s*(?::\s*(.*))?")
# legacy PR-5 annotation, kept working so the two gates can't disagree
CANCEL_EXEMPT_RE = re.compile(r"#\s*cancel-exempt\s*(?::\s*(.*))?")
# raw-jit's domain-specific spelling (mirrors cancel-exempt)
JIT_EXEMPT_RE = re.compile(r"#\s*jit-exempt\s*(?::\s*(.*))?")
# bucket-accounting's domain-specific spelling
ATTRIBUTION_EXEMPT_RE = re.compile(
    r"#\s*attribution-exempt\s*(?::\s*(.*))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation at one source site."""

    rule: str
    path: str        # relative to the package root's parent
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceModule:
    """One parsed source file: AST + raw lines + exemption table."""

    def __init__(self, path: str, rel: str, text: Optional[str] = None):
        self.path = path
        self.rel = rel
        if text is None:
            with open(path) as f:
                text = f.read()
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel)
        # line -> (set of exempted rule names, reason); "*" = any rule
        self.exemptions: Dict[int, tuple] = {}
        self._bad_exemptions: List[Finding] = []
        for i, ln in self._comments():
            m = EXEMPT_RE.search(ln)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                reason = (m.group(2) or "").strip()
                if not reason:
                    self._bad_exemptions.append(Finding(
                        "exemption", rel, i,
                        "exemption without a reason — write "
                        "'# lint: exempt(<rule>): <why>'"))
                self.exemptions[i] = (rules, reason)
                continue
            m = CANCEL_EXEMPT_RE.search(ln)
            if m:
                reason = (m.group(1) or "").strip()
                if not reason:
                    self._bad_exemptions.append(Finding(
                        "exemption", rel, i,
                        "cancel-exempt without a reason — write "
                        "'# cancel-exempt: <why>'"))
                self.exemptions[i] = ({"blocking-wait"}, reason)
                continue
            m = JIT_EXEMPT_RE.search(ln)
            if m:
                reason = (m.group(1) or "").strip()
                if not reason:
                    self._bad_exemptions.append(Finding(
                        "exemption", rel, i,
                        "jit-exempt without a reason — write "
                        "'# jit-exempt: <why>'"))
                self.exemptions[i] = ({"raw-jit"}, reason)
                continue
            m = ATTRIBUTION_EXEMPT_RE.search(ln)
            if m:
                reason = (m.group(1) or "").strip()
                if not reason:
                    self._bad_exemptions.append(Finding(
                        "exemption", rel, i,
                        "attribution-exempt without a reason — write "
                        "'# attribution-exempt: <why>'"))
                self.exemptions[i] = ({"bucket-accounting"}, reason)

    def _comments(self):
        """(line, comment_text) for real COMMENT tokens only — an
        annotation quoted inside a docstring must not count."""
        try:
            toks = tokenize.generate_tokens(
                io.StringIO(self.text).readline)
            return [(t.start[0], t.string) for t in toks
                    if t.type == tokenize.COMMENT]
        except tokenize.TokenizeError:
            return []

    def exempt_at(self, line: int, rule: str) -> bool:
        """Same-line or preceding-line exemption for ``rule``."""
        for ln in (line, line - 1):
            ex = self.exemptions.get(ln)
            if ex is not None and (rule in ex[0] or "*" in ex[0]):
                return True
        return False

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


class Rule:
    """One invariant.  ``check`` runs per module; ``finalize`` runs once
    after every module, for cross-module analyses (lock graph, conf
    registry)."""

    name = "rule"

    def check(self, mod: SourceModule) -> Iterable[Finding]:
        return ()

    def finalize(self) -> Iterable[Finding]:
        return ()


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def iter_modules(pkg_dir: Optional[str] = None) -> List[SourceModule]:
    """Every .py file of the package, parsed once and shared by all
    rules."""
    if pkg_dir is None:
        pkg_dir = _package_root()
    base = os.path.dirname(pkg_dir)
    mods = []
    for root, dirs, files in os.walk(pkg_dir):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            mods.append(SourceModule(path, os.path.relpath(path, base)))
    return mods


def all_rules() -> List[Rule]:
    from spark_rapids_tpu.utils.lint.adaptive_purity import (
        AdaptivePurityRule)
    from spark_rapids_tpu.utils.lint.blocking_wait import BlockingWaitRule
    from spark_rapids_tpu.utils.lint.bucket_accounting import (
        BucketAccountingRule)
    from spark_rapids_tpu.utils.lint.cache_safety import CacheSafetyRule
    from spark_rapids_tpu.utils.lint.conf_drift import ConfDriftRule
    from spark_rapids_tpu.utils.lint.exchange_purity import (
        ExchangePurityRule)
    from spark_rapids_tpu.utils.lint.failure_domains import (
        FailureDomainRule)
    from spark_rapids_tpu.utils.lint.fusion_purity import FusionPurityRule
    from spark_rapids_tpu.utils.lint.host_sync import HostSyncInJitRule
    from spark_rapids_tpu.utils.lint.kernel_purity import KernelPurityRule
    from spark_rapids_tpu.utils.lint.lock_order import LockOrderRule
    from spark_rapids_tpu.utils.lint.op_stats import OpStatsRule
    from spark_rapids_tpu.utils.lint.raw_jit import RawJitRule
    from spark_rapids_tpu.utils.lint.scheduler_bypass import (
        SchedulerBypassRule)
    return [LockOrderRule(), ConfDriftRule(), FailureDomainRule(),
            HostSyncInJitRule(), BlockingWaitRule(), OpStatsRule(),
            SchedulerBypassRule(), RawJitRule(), ExchangePurityRule(),
            KernelPurityRule(), AdaptivePurityRule(), CacheSafetyRule(),
            FusionPurityRule(), BucketAccountingRule()]


def run_lint(pkg_dir: Optional[str] = None,
             rules: Optional[Sequence[Rule]] = None,
             modules: Optional[Sequence[SourceModule]] = None
             ) -> List[Finding]:
    """Run every rule over every package module; returns the surviving
    (un-exempted) findings, sorted by site."""
    if modules is None:
        modules = iter_modules(pkg_dir)
    if rules is None:
        rules = all_rules()
    by_rel = {m.rel: m for m in modules}
    findings: List[Finding] = []
    for m in modules:
        findings.extend(m._bad_exemptions)
    for rule in rules:
        for m in modules:
            findings.extend(rule.check(m))
        findings.extend(rule.finalize())
    out = []
    for f in findings:
        m = by_rel.get(f.path)
        if m is not None and m.exempt_at(f.line, f.rule):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: print findings, exit nonzero on any."""
    pkg_dir = None
    if argv:
        pkg_dir = argv[0]
    findings = run_lint(pkg_dir)
    for f in findings:
        print(f)
    if findings:
        print(f"lint: {len(findings)} finding(s)")
        return 1
    print("lint: clean")
    return 0
