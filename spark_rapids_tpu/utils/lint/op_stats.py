"""``op-stats`` — every concrete exec's pump is stats-covered.

The stats plane (runtime/stats.py) and the tracer observe operators at
the ``execute`` wrapper that ``ExecNode.__init_subclass__`` installs —
and that hook wraps only an ``execute`` defined in the subclass's OWN
body (``cls.__dict__``).  Two shapes silently escape it:

* **mixin execute** — an exec class inheriting ``execute`` from a
  non-exec mixin base: the mixin is outside the ``ExecNode`` hierarchy,
  so ``__init_subclass__`` never saw its ``execute`` and every pump of
  that class is invisible to stats, tracing, and cancellation;
* **monkey-patch** — a module-level ``SomeExec.execute = fn``
  assignment replaces the wrapped method with a bare one after class
  creation.

Inheriting ``execute`` from another exec-family class is fine (the
definer was wrapped); an abstract intermediate that never defines
``execute`` is fine too (it pumps nothing itself).  A deliberate
escape carries ``# lint: exempt(op-stats): <why>`` on the class (or
assignment) line.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from spark_rapids_tpu.utils.lint import Finding, Rule, SourceModule

# the hierarchy whose __init_subclass__ owns the wrapping
ROOT_CLASSES = {"ExecNode", "CpuExec", "TpuExec"}


def _base_name(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class OpStatsRule(Rule):
    name = "op-stats"

    def __init__(self):
        # class name -> (rel, line, base names, defines execute)
        self._classes: Dict[str, Tuple[str, int, List[str], bool]] = {}
        # names defined in >1 module: base resolution would guess
        self._ambiguous: Set[str] = set()
        # (rel, line, class name) of module-level X.execute = ...
        self._patches: List[Tuple[str, int, str]] = []

    def check(self, mod: SourceModule) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                bases = [b for b in map(_base_name, node.bases)
                         if b is not None]
                has_exec = any(
                    isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and s.name == "execute" for s in node.body)
                if node.name in self._classes:
                    self._ambiguous.add(node.name)
                else:
                    self._classes[node.name] = (
                        mod.rel, node.lineno, bases, has_exec)
        # ONLY module top-level assignments: the wrapper's own
        # ``cls.execute = _wrap_execute(fn)`` lives inside a method body
        for stmt in mod.tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            for tgt in stmt.targets:
                if (isinstance(tgt, ast.Attribute)
                        and tgt.attr == "execute"
                        and isinstance(tgt.value, ast.Name)):
                    self._patches.append(
                        (mod.rel, stmt.lineno, tgt.value.id))
        return ()

    # -- cross-module resolution -----------------------------------------

    def _is_exec_family(self, name: str, seen: Set[str]) -> bool:
        if name in ROOT_CLASSES:
            return True
        if name in seen or name in self._ambiguous:
            return False
        seen.add(name)
        info = self._classes.get(name)
        if info is None:
            return False
        return any(self._is_exec_family(b, seen) for b in info[2])

    def _execute_definer(self, name: str, seen: Set[str]
                         ) -> Optional[str]:
        """Nearest class (depth-first over declared bases — the static
        stand-in for the MRO) defining ``execute``; None when
        unresolvable (external / ambiguous base)."""
        if name in seen:
            return None
        seen.add(name)
        info = self._classes.get(name)
        if info is None:
            # ExecNode itself resolves here when base.py was scanned;
            # an external base we can't see resolves to None
            return name if name in ROOT_CLASSES else None
        if info[3]:
            return name
        for b in info[2]:
            d = self._execute_definer(b, seen)
            if d is not None:
                return d
        return None

    def finalize(self) -> Iterable[Finding]:
        out: List[Finding] = []
        exec_family = {n for n in self._classes
                       if self._is_exec_family(n, set())}
        for name in sorted(exec_family):
            rel, line, _bases, has_exec = self._classes[name]
            if has_exec:
                continue  # own-body execute: __init_subclass__ wrapped it
            definer = self._execute_definer(name, set())
            if definer is None or definer in ROOT_CLASSES:
                continue  # abstract (inherits the NotImplementedError)
            if definer in exec_family:
                continue  # definer's own body was wrapped at ITS creation
            out.append(Finding(
                self.name, rel, line,
                f"exec class {name!r} inherits execute from non-exec "
                f"mixin {definer!r} — __init_subclass__ never wrapped "
                "it, so its pump is invisible to stats/trace/cancel; "
                "define execute in the exec class (delegating is fine) "
                "or exempt with a reason"))
        for rel, line, cls in self._patches:
            if cls in exec_family:
                out.append(Finding(
                    self.name, rel, line,
                    f"module-level assignment replaces {cls}.execute "
                    "AFTER class creation — the stats/trace/cancel "
                    "wrapper is discarded; override in a subclass "
                    "instead or exempt with a reason"))
        return out
