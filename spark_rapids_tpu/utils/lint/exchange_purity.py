"""``exchange-purity`` — compiled-exchange program builders stay device-pure.

The compiled exchange's whole point is that a stage seam is ONE device
collective: ``build_prepare_program`` / ``build_range_prepare_program``
/ ``build_boundary_program`` (and the legacy count/shuffle builders)
must construct SPMD programs without ever materializing data on the
host.  A ``device_get`` / ``np.asarray`` / ``.addressable_shards`` pull
inside a builder would either fail at trace time or silently reintroduce
the host round-trip the exchange plane was rebuilt to kill — and it
would do so on EVERY stage seam, which is exactly the 0.05 GB/s
regression mode this PR's microbench guards against.

Scope: function defs matching ``build_*_program`` (plus everything
nested in them) inside the exchange plane's modules —
``parallel/shuffle.py``, ``exec/distributed.py``, ``exec/exchange.py``.
The generic ``host-sync-in-jit`` rule covers only jit-traced bodies;
this rule also covers the builders' un-traced construction code, where
a host pull is legal Python but still a seam-latency bug.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set

from spark_rapids_tpu.utils.lint import Finding, Rule, SourceModule

SCOPE_FILES = (
    "spark_rapids_tpu/parallel/shuffle.py",
    "spark_rapids_tpu/exec/distributed.py",
    "spark_rapids_tpu/exec/exchange.py",
)
BUILDER_RE = re.compile(r"^build_\w*_program$")

SYNC_ATTRS = {"item", "block_until_ready", "addressable_shards",
              "addressable_data"}
NP_SYNC_FUNCS = {"asarray", "array", "ascontiguousarray"}
HOST_FUNCS = {"device_get", "device_to_host", "num_rows_host"}


class ExchangePurityRule(Rule):
    name = "exchange-purity"

    def check(self, mod: SourceModule) -> Iterable[Finding]:
        if mod.rel not in SCOPE_FILES:
            return ()
        out: List[Finding] = []
        seen: Set[int] = set()
        for node in ast.walk(mod.tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and BUILDER_RE.match(node.name)):
                for sub in ast.walk(node):
                    msg = self._flag(sub)
                    if msg and sub.lineno not in seen:
                        seen.add(sub.lineno)
                        out.append(Finding(
                            self.name, mod.rel, sub.lineno,
                            f"{msg} inside exchange program builder "
                            f"`{node.name}` "
                            f"(`{mod.snippet(sub.lineno)}`)"))
        return out

    def _flag(self, node) -> Optional[str]:
        if isinstance(node, ast.Attribute):
            if node.attr in ("addressable_shards", "addressable_data"):
                return f".{node.attr} host shard access"
            return None
        if not isinstance(node, ast.Call):
            return None
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in ("item", "block_until_ready"):
                return f".{f.attr}() host sync"
            if (f.attr in NP_SYNC_FUNCS
                    and isinstance(f.value, ast.Name)
                    and f.value.id in ("np", "numpy", "onp")):
                return f"np.{f.attr} host materialization"
            if f.attr in HOST_FUNCS:
                return f".{f.attr}() host materialization"
        elif isinstance(f, ast.Name) and f.id in HOST_FUNCS:
            return f"{f.id}() host materialization"
        return None
