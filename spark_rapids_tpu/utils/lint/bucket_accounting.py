"""bucket-accounting rule: every stage label lands in a declared
attribution bucket.

The attribution ledger (runtime/attribution.py) can only close the
per-query time books if every span the engine emits maps to one of the
declared buckets — a new ``MetricTimer`` stage name or
``tracer.begin``/``trace.span`` stage label that ``STAGE_BUCKETS``
doesn't know about silently grows the ``unaccounted`` gap until the
closure check fails in production.  This rule moves that failure to
lint time: it collects every string-literal stage at

- ``.timer("<stage>")`` call sites (the ``MetricTimer`` pairing — the
  no-arg form defaults to ``opTime``, which maps), and
- the second argument of ``.begin(op, "<stage>")`` /
  ``.span(op, "<stage>")`` call sites,

across the engine (``utils/`` excluded — the toolchain talks *about*
stages) and fails any stage missing from
``attribution.STAGE_BUCKETS``.  A deliberately unbucketed stage
carries::

    # attribution-exempt: <why>

(or the generic ``# lint: exempt(bucket-accounting): <why>``).
"""

from __future__ import annotations

import ast
from typing import Iterable

from spark_rapids_tpu.utils.lint import Finding, Rule, SourceModule

# paths never scanned: the lint/docs toolchain mentions stage names in
# catalogs and fixtures, not as live span sites
SKIP_PREFIXES = (
    "spark_rapids_tpu/utils/",
)


def _stage_literal(node: ast.Call) -> tuple:
    """(stage, is_stage_site) for one call node."""
    func = node.func
    if not isinstance(func, ast.Attribute):
        return None, False
    if func.attr == "timer":
        if not node.args:
            return "opTime", True  # the .timer() default
        a = node.args[0]
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value, True
        return None, False  # dynamic stage — not statically checkable
    if func.attr in ("begin", "span") and len(node.args) >= 2:
        op, stage = node.args[0], node.args[1]
        # only op+stage string-literal pairs are span sites — keeps
        # str.span()/re matches and forwarding wrappers out
        if (isinstance(op, ast.Constant) and isinstance(op.value, str)
                and isinstance(stage, ast.Constant)
                and isinstance(stage.value, str)):
            return stage.value, True
    return None, False


class BucketAccountingRule(Rule):
    name = "bucket-accounting"

    def check(self, mod: SourceModule) -> Iterable[Finding]:
        rel = mod.rel.replace("\\", "/")
        if any(rel.startswith(p) for p in SKIP_PREFIXES):
            return
        from spark_rapids_tpu.runtime.attribution import STAGE_BUCKETS
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            stage, is_site = _stage_literal(node)
            if not is_site or stage is None:
                continue
            if stage in STAGE_BUCKETS:
                continue
            yield Finding(
                self.name, mod.rel, node.lineno,
                f"stage '{stage}' is not mapped to an attribution "
                "bucket — add it to "
                "runtime/attribution.py:STAGE_BUCKETS (and the bucket "
                "to BUCKETS/docs if new) so the per-query time books "
                "still close, or annotate the site with "
                "'# attribution-exempt: <why>'")
