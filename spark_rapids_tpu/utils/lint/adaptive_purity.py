"""``adaptive-purity`` — replanner decisions come from recorded stats.

The adaptive plane (``spark_rapids_tpu/adaptive/``) is the PLANNING
path: its cost model and replanner run at stage boundaries, often
under exec-node locks, and decide from stats the pumps already
recorded, profile-store history, and conf.  A ``.block_until_ready()``
/ ``.item()`` / ``np.asarray`` host pull there is a fresh device sync
smuggled into planning — it serializes the async pipeline at exactly
the point the plane exists to keep cheap, and it makes decisions
depend on device state instead of the recorded stats they claim to
explain.  Measurement that must touch the device (gathering a build
side, counting partition rows) belongs in the exec layer, which hands
the numbers in.  Same shape as ``kernel-purity``; the flag tables are
shared with ``exchange-purity`` so the three rules can't drift.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from spark_rapids_tpu.utils.lint import Finding, Rule, SourceModule
from spark_rapids_tpu.utils.lint.exchange_purity import (
    ExchangePurityRule)

SCOPE_PREFIX = "spark_rapids_tpu/adaptive/"


class AdaptivePurityRule(Rule):
    name = "adaptive-purity"

    def check(self, mod: SourceModule) -> Iterable[Finding]:
        if not mod.rel.startswith(SCOPE_PREFIX):
            return ()
        flag = ExchangePurityRule()._flag
        out: List[Finding] = []
        seen: Set[int] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for sub in ast.walk(node):
                msg = flag(sub)
                if msg and sub.lineno not in seen:
                    seen.add(sub.lineno)
                    out.append(Finding(
                        self.name, mod.rel, sub.lineno,
                        f"{msg} inside adaptive-plane function "
                        f"`{node.name}` — replanner decisions must "
                        f"come from recorded stats or conf "
                        f"(`{mod.snippet(sub.lineno)}`)"))
        return out
