"""``kernel-purity`` — the kernel plane's device code stays device-pure.

Everything under ``spark_rapids_tpu/kernels/`` except the dispatcher
(``__init__.py``, whose one ``bool(ok)`` host sync is the exactness
protocol by design) is DEVICE code traced inside ``cached_kernel``
builders: hash mixing, hash-grouped layout, tiled segmented sort, the
fused join probe.  A ``np.asarray`` / ``.item()`` / ``device_get`` pull
there either fails at trace time or — worse — silently serializes the
async pipeline the kernel plane exists to keep full, on EVERY batch of
every join/sort/agg.  Same shape as ``exchange-purity``, scoped to the
kernel modules; the flag tables are shared so the two rules can't
drift.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from spark_rapids_tpu.utils.lint import Finding, Rule, SourceModule
from spark_rapids_tpu.utils.lint.exchange_purity import (
    ExchangePurityRule)

SCOPE_PREFIX = "spark_rapids_tpu/kernels/"
EXEMPT_FILES = ("spark_rapids_tpu/kernels/__init__.py",)


class KernelPurityRule(Rule):
    name = "kernel-purity"

    def check(self, mod: SourceModule) -> Iterable[Finding]:
        if (not mod.rel.startswith(SCOPE_PREFIX)
                or mod.rel in EXEMPT_FILES):
            return ()
        flag = ExchangePurityRule()._flag
        out: List[Finding] = []
        seen: Set[int] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for sub in ast.walk(node):
                msg = flag(sub)
                if msg and sub.lineno not in seen:
                    seen.add(sub.lineno)
                    out.append(Finding(
                        self.name, mod.rel, sub.lineno,
                        f"{msg} inside kernel-plane function "
                        f"`{node.name}` "
                        f"(`{mod.snippet(sub.lineno)}`)"))
        return out
