"""``lock-order`` — static lock-acquisition graph + canonical order.

PRs 1–5 grew ~30 ``threading.Lock``/``RLock``/``Condition`` instances
across runtime/, exec/, parallel/, and shuffle/.  Before the
multi-tenant serving layer multiplies concurrent queries over this
substrate, the acquisition ORDER becomes a correctness surface: two
subsystems nesting each other's locks in opposite orders deadlock only
under exactly the interleaving 64 in-flight queries will find.

The rule builds a static lock-acquisition graph:

* **lock identities** — every ``threading.Lock()``/``RLock()``/
  ``Condition()`` creation site, named ``<module>.<Class>.<attr>`` (or
  ``<module>.<name>`` for module-level locks).  One identity covers
  every instance created at that site — order is a property of the
  code path, not the object.
* **direct edges** — inside every function, a nested ``with <lock>``
  scope or an ``.acquire()`` under a held ``with`` adds
  ``held → acquired``.
* **call edges** — a call made under a held lock contributes the
  callee's transitively-computed acquisitions.  Callees resolve
  through bare names, ``self.method``, module-global instances
  (``_SCOPE.lock``, ``INJECTOR.on``), and package import aliases
  (``R.run_guarded`` → ``runtime.resilience::run_guarded``), with one
  global fixpoint over the whole package.  Dynamic dispatch through
  locals stays out of static reach — ``runtime/lockdep.py`` covers it
  at runtime against the same canonical order.

Findings: (1) a non-reentrant lock acquired while already held
(self-deadlock), (2) any cycle in the accumulated graph, and (3) an
edge that inverts ``CANONICAL_ORDER`` below (outermost tier first —
the order docs/static_analysis.md publishes).  Leaf tiers (telemetry,
trace) must never call out into engine tiers while holding their own
locks.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from spark_rapids_tpu.utils.lint import Finding, Rule, SourceModule

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

PKG = "spark_rapids_tpu"

# Canonical acquisition order, outermost tier first.  An edge from a
# later tier into an earlier one is an inversion.  Unmatched locks are
# order-unranked (still cycle-checked).
CANONICAL_ORDER: List[Tuple[str, str]] = [
    (r"^(sql|exec|plan|io)\.", "query/exec layer (materialization, "
                               "AQE, join state)"),
    (r"^shuffle\.", "shuffle manager + exchange"),
    (r"^parallel\.", "multi-executor tier (executor pool, rendezvous)"),
    (r"^runtime\.semaphore\.", "device admission (semaphore CV)"),
    (r"^runtime\.memory\.", "HBM arbiter + spill store"),
    (r"^runtime\.kernel_cache\.", "kernel cache"),
    (r"^runtime\.resilience\.", "retry/breaker state"),
    (r"^runtime\.cancel\.", "cancel tokens + query scope"),
    (r"^runtime\.(device|lockdep)\.|^native\.|^ops\.",
     "device init + op-local state"),
    (r"^runtime\.telemetry\.", "telemetry registry (leaf)"),
    (r"^runtime\.trace\.", "tracer + event log (leaf)"),
]


def lock_rank(lock_id: str) -> Optional[int]:
    for i, (pat, _) in enumerate(CANONICAL_ORDER):
        if re.search(pat, lock_id):
            return i
    return None


def _ctor_kind(node) -> Optional[str]:
    """'Lock' | 'RLock' | 'Condition' for a lock-factory call."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if (isinstance(f, ast.Attribute) and f.attr in LOCK_FACTORIES
            and isinstance(f.value, ast.Name)
            and f.value.id == "threading"):
        return f.attr
    if isinstance(f, ast.Name) and f.id in LOCK_FACTORIES:
        return f.id
    return None


def _short_mod(rel: str) -> str:
    s = rel.replace("\\", "/")
    if s.startswith(PKG + "/"):
        s = s[len(PKG) + 1:]
    if s.endswith(".py"):
        s = s[:-3]
    return s.replace("/", ".")


class _FnFacts:
    """Per-function lock facts from one traversal."""

    def __init__(self):
        # (held_id, acquired_id, line)
        self.edges: List[Tuple[str, str, int]] = []
        self.acquires: Set[str] = set()
        # (held_ids_tuple, callee_key "mod::qual", line)
        self.calls: List[Tuple[Tuple[str, ...], str, int]] = []


class LockOrderRule(Rule):
    name = "lock-order"

    def __init__(self):
        # (a, b) -> list of (mod_rel, line, note)
        self.graph: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}
        self.kinds: Dict[str, str] = {}      # lock id -> ctor kind
        # "mod::qual" -> merged facts entries
        self.all_facts: List[Tuple[str, str, _FnFacts]] = []

    # -- per-module ------------------------------------------------------

    def check(self, mod: SourceModule) -> Iterable[Finding]:
        short = _short_mod(mod.rel)
        module_locks: Dict[str, str] = {}           # name -> id
        class_locks: Dict[Tuple[str, str], str] = {}  # (cls, attr) -> id
        class_names: Set[str] = set()
        module_instances: Dict[str, str] = {}       # name -> class name
        import_alias: Dict[str, str] = {}           # name -> short mod
        import_func: Dict[str, Tuple[str, str]] = {}  # name -> (mod, fn)

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                m = node.module
                if m == PKG or m.startswith(PKG + "."):
                    base = m[len(PKG) + 1:] if m != PKG else ""
                    for alias in node.names:
                        name = alias.asname or alias.name
                        sub = (f"{base}.{alias.name}" if base
                               else alias.name)
                        # imported module vs imported symbol: decide at
                        # resolution time — record both candidates
                        import_alias[name] = sub
                        if base:
                            import_func[name] = (base, alias.name)

        for node in mod.tree.body:
            for tgt, val in _assignments(node):
                if not isinstance(tgt, ast.Name):
                    continue
                kind = _ctor_kind(val)
                if kind:
                    lid = f"{short}.{tgt.id}"
                    module_locks[tgt.id] = lid
                    self.kinds[lid] = kind
                elif (isinstance(val, ast.Call)
                        and isinstance(val.func, ast.Name)):
                    module_instances[tgt.id] = val.func.id

        for cnode in ast.walk(mod.tree):
            if not isinstance(cnode, ast.ClassDef):
                continue
            class_names.add(cnode.name)
            for stmt in cnode.body:
                for tgt, val in _assignments(stmt):
                    kind = _ctor_kind(val)
                    if kind and isinstance(tgt, ast.Name):
                        lid = f"{short}.{cnode.name}.{tgt.id}"
                        class_locks[(cnode.name, tgt.id)] = lid
                        self.kinds[lid] = kind
            for fnode in ast.walk(cnode):
                if not isinstance(fnode, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                    continue
                for sub in ast.walk(fnode):
                    for tgt, val in _assignments(sub):
                        kind = _ctor_kind(val)
                        if (kind and isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            lid = f"{short}.{cnode.name}.{tgt.attr}"
                            class_locks[(cnode.name, tgt.attr)] = lid
                            self.kinds[lid] = kind

        ctx = dict(short=short, module_locks=module_locks,
                   class_locks=class_locks, class_names=class_names,
                   module_instances=module_instances,
                   import_alias=import_alias, import_func=import_func)
        for fn, cls in _functions(mod.tree):
            qual = f"{cls}.{fn.name}" if cls else fn.name
            facts = self._analyze_fn(fn, cls, ctx)
            self.all_facts.append((mod.rel, f"{short}::{qual}", facts))
        return ()

    def _analyze_fn(self, fn, cls, ctx) -> _FnFacts:
        facts = _FnFacts()
        short = ctx["short"]
        module_locks = ctx["module_locks"]
        class_locks = ctx["class_locks"]
        class_names = ctx["class_names"]
        module_instances = ctx["module_instances"]
        import_alias = ctx["import_alias"]
        import_func = ctx["import_func"]

        def resolve(expr) -> Optional[str]:
            if isinstance(expr, ast.Name):
                return module_locks.get(expr.id)
            if isinstance(expr, ast.Attribute) and isinstance(
                    expr.value, ast.Name):
                base = expr.value.id
                if base in ("self", "cls") and cls:
                    return class_locks.get((cls, expr.attr))
                if base in class_names:
                    return class_locks.get((base, expr.attr))
                inst_cls = module_instances.get(base)
                if inst_cls:
                    return class_locks.get((inst_cls, expr.attr))
            return None

        def callee_key(func) -> Optional[str]:
            if isinstance(func, ast.Name):
                name = func.id
                if name in import_func:
                    m, f = import_func[name]
                    return f"{m}::{f}"
                return f"{short}::{name}"
            if (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)):
                base = func.value.id
                if base == "self" and cls:
                    return f"{short}::{cls}.{func.attr}"
                if base in class_names:
                    return f"{short}::{base}.{func.attr}"
                inst_cls = module_instances.get(base)
                if inst_cls:
                    return f"{short}::{inst_cls}.{func.attr}"
                if base in import_alias:
                    return f"{import_alias[base]}::{func.attr}"
            return None

        held: List[str] = []

        def walk(node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return  # not executed at definition point
            if isinstance(node, (ast.With, ast.AsyncWith)):
                pushed = 0
                for item in node.items:
                    lid = resolve(item.context_expr)
                    if lid is not None:
                        for h in held:
                            facts.edges.append((h, lid, node.lineno))
                        held.append(lid)
                        facts.acquires.add(lid)
                        pushed += 1
                    else:
                        walk(item.context_expr)
                for b in node.body:
                    walk(b)
                for _ in range(pushed):
                    held.pop()
                return
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "acquire":
                    lid = resolve(f.value)
                    if lid is not None:
                        for h in held:
                            facts.edges.append((h, lid, node.lineno))
                        facts.acquires.add(lid)
                else:
                    ck = callee_key(f)
                    if ck is not None:
                        facts.calls.append((tuple(held), ck,
                                            node.lineno))
            for child in ast.iter_child_nodes(node):
                walk(child)

        for stmt in fn.body:
            walk(stmt)
        return facts

    def _add_edge(self, a: str, b: str, rel: str, line: int, note: str):
        self.graph.setdefault((a, b), []).append((rel, line, note))

    # -- cross-module ----------------------------------------------------

    def finalize(self) -> Iterable[Finding]:
        # global fixpoint: what can each function (transitively) acquire
        total: Dict[str, Set[str]] = {}
        for _, key, f in self.all_facts:
            total.setdefault(key, set()).update(f.acquires)
        changed = True
        while changed:
            changed = False
            for _, key, f in self.all_facts:
                mine = total[key]
                for _, callee, _ in f.calls:
                    sub = total.get(callee)
                    if sub and not sub <= mine:
                        mine |= sub
                        changed = True

        for rel, key, f in self.all_facts:
            for a, b, line in f.edges:
                self._add_edge(a, b, rel, line, "")
            for held, callee, line in f.calls:
                if not held:
                    continue
                for b in total.get(callee, ()):
                    for a in held:
                        self._add_edge(
                            a, b, rel, line,
                            f"via {callee.split('::')[-1]}()")

        out: List[Finding] = []
        # 1) non-reentrant self-acquisition
        for (a, b), sites in sorted(self.graph.items()):
            if a == b and self.kinds.get(a) == "Lock":
                rel, line, note = sites[0]
                out.append(Finding(
                    self.name, rel, line,
                    f"non-reentrant lock {a} acquired while already "
                    f"held — self-deadlock"
                    f"{' (' + note + ')' if note else ''}"))
        # 2) canonical-order inversions
        for (a, b), sites in sorted(self.graph.items()):
            if a == b:
                continue
            ra, rb = lock_rank(a), lock_rank(b)
            if ra is not None and rb is not None and ra > rb:
                rel, line, note = sites[0]
                out.append(Finding(
                    self.name, rel, line,
                    f"acquires {b} (tier {rb}) while holding {a} "
                    f"(tier {ra}) — inverts the canonical lock order"
                    f"{' (' + note + ')' if note else ''}"))
        # 3) cycles in the accumulated graph
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.graph:
            if a != b:
                adj.setdefault(a, set()).add(b)
                adj.setdefault(b, set())
        for scc in _sccs(adj):
            if len(scc) < 2:
                continue
            cyc = sorted(scc)
            edges_in = sorted((a, b) for (a, b) in self.graph
                              if a in scc and b in scc and a != b)
            rel, line, note = self.graph[edges_in[0]][0]
            out.append(Finding(
                self.name, rel, line,
                "lock-order cycle: " + " -> ".join(cyc + [cyc[0]])))
        return out


def _functions(tree):
    """(function_node, enclosing_class_name | None) for every def —
    module-level, methods, and nested defs (which keep the enclosing
    class so ``self.X`` still resolves)."""
    out = []

    def scan(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                scan(child, child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                out.append((child, cls))
                scan(child, cls)
            else:
                scan(child, cls)

    scan(tree, None)
    return out


def _assignments(node):
    """(target, value) pairs for Assign/AnnAssign statements."""
    if isinstance(node, ast.Assign):
        for t in node.targets:
            yield t, node.value
    elif isinstance(node, ast.AnnAssign) and node.value is not None:
        yield node.target, node.value


def _sccs(adj: Dict[str, Set[str]]) -> List[Set[str]]:
    """Tarjan's strongly connected components, iterative."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[Set[str]] = []
    counter = [0]

    for root in adj:
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.add(w)
                    if w == v:
                        break
                sccs.append(comp)
    return sccs
