"""``failure-domain`` — every device/retryable raise carries a domain.

PR 3 routes faults through one conf-driven ``RetryPolicy`` keyed by
failure domain; PR 4 extended the domain set to the distributed tier.
That routing only works if error objects ARE domain-tagged.  This rule
keeps runtime/, shuffle/, and parallel/ honest:

* ``raise RuntimeError(...)`` / ``raise Exception(...)`` is flagged —
  a generic error crossing a retry boundary routes through no domain
  and reaches the user as an anonymous failure.  Use a domain-tagged
  engine type (``TerminalDeviceError``, ``InjectedDeviceError``, the
  ``RetryOOM`` / ``Rendezvous*`` families whose domain is implicit in
  the type) or a plain programming-error type (ValueError, TypeError,
  ...), which the retry layer never swallows.
* ``raise TerminalDeviceError(...)`` / ``InjectedDeviceError(...)``
  without the domain argument is flagged statically (the constructor
  would fail at runtime, but the lint wall catches it pre-merge).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from spark_rapids_tpu.utils.lint import Finding, Rule, SourceModule

SCOPES = ("runtime", "shuffle", "parallel")

# generic types whose raise in failure-domain code bypasses routing
GENERIC = {"RuntimeError", "Exception", "BaseException"}

# engine error types that REQUIRE an explicit domain constructor arg:
# name -> (positional index, keyword name)
NEEDS_DOMAIN_ARG = {
    "TerminalDeviceError": (0, "domain"),
    "InjectedDeviceError": (0, "where"),
}


def _in_scope(rel: str) -> bool:
    parts = rel.replace("\\", "/").split("/")
    return any(p in SCOPES for p in parts[:-1])


def _callee_name(func) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


class FailureDomainRule(Rule):
    name = "failure-domain"

    def check(self, mod: SourceModule) -> Iterable[Finding]:
        if not _in_scope(mod.rel):
            return ()
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            # `raise RuntimeError` without a call — same class hazard
            if isinstance(exc, ast.Name) and exc.id in GENERIC:
                out.append(Finding(
                    self.name, mod.rel, node.lineno,
                    f"bare {exc.id} in failure-domain code — raise a "
                    "domain-tagged engine error type"))
                continue
            if not isinstance(exc, ast.Call):
                continue  # `raise e` re-raises keep their tag
            cname = _callee_name(exc.func)
            if cname in GENERIC:
                out.append(Finding(
                    self.name, mod.rel, node.lineno,
                    f"generic {cname} in failure-domain code — raise a "
                    "domain-tagged engine error type "
                    f"(`{mod.snippet(node.lineno)}`)"))
            elif cname in NEEDS_DOMAIN_ARG:
                pos, kw = NEEDS_DOMAIN_ARG[cname]
                has = (len(exc.args) > pos
                       or any(k.arg == kw for k in exc.keywords))
                if not has:
                    out.append(Finding(
                        self.name, mod.rel, node.lineno,
                        f"{cname} raised without its '{kw}' domain "
                        "argument"))
        return out
