"""``host-sync-in-jit`` — traced kernel bodies must stay on device.

Every operator kernel in this engine compiles through ONE of two
funnels: ``jax.jit`` directly (decorator or call) or
``kernel_cache.cached_kernel(key, builder)``, whose ``_build_wrapper``
jits the function the builder returns.  Inside those traced bodies a
host synchronization — ``np.asarray``/``np.array`` on a traced value,
``float()``/``int()``/``bool()`` coercion, ``.item()``,
``.block_until_ready()`` — either fails at trace time
(``TracerArrayConversionError``) or, worse, silently constant-folds a
traced value and bakes one batch's data into the compiled executable.
On TPU it also stalls the pipeline: each sync is a device→host round
trip in the middle of the hot path (feeds ROADMAP item 4's
zero-compile-storm / flat-p99 goal).

Detection is the funnel inversion: a function body is "traced" when it
is (a) decorated with ``jax.jit`` / ``functools.partial(jax.jit,..)``,
(b) the argument of a ``jax.jit(...)`` call, or (c) returned by a
builder passed to ``cached_kernel`` (including through the
``lambda: build(...)`` trampoline idiom every call site uses).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from spark_rapids_tpu.utils.lint import Finding, Rule, SourceModule

SYNC_ATTRS = {"item", "block_until_ready"}
NP_SYNC_FUNCS = {"asarray", "array"}
COERCIONS = {"float", "int", "bool"}


def _is_jit_expr(node) -> bool:
    """``jax.jit`` / ``jit`` / ``partial(jax.jit, ...)``."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return True
    if isinstance(node, ast.Name) and node.id == "jit":
        return True
    if (isinstance(node, ast.Call)
            and isinstance(node.func, (ast.Name, ast.Attribute))):
        fname = (node.func.id if isinstance(node.func, ast.Name)
                 else node.func.attr)
        if fname == "partial" and node.args:
            return _is_jit_expr(node.args[0])
    return False


class _ModuleIndex(ast.NodeVisitor):
    """Collects function defs, jit marks, and cached_kernel builders."""

    def __init__(self):
        self.defs = {}          # name -> [FunctionDef] (any nesting)
        self.traced: Set[ast.AST] = set()
        self.builder_names: Set[str] = set()
        self.jit_target_names: Set[str] = set()

    def visit_FunctionDef(self, node):
        self.defs.setdefault(node.name, []).append(node)
        if any(_is_jit_expr(d) for d in node.decorator_list):
            self.traced.add(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def _mark_builder_expr(self, b) -> None:
        """The 2nd arg of ``cached_kernel``: a Name, a lambda
        trampoline around a call, or a lambda returning a lambda."""
        if isinstance(b, ast.Name):
            self.builder_names.add(b.id)
        elif isinstance(b, ast.Lambda):
            body = b.body
            if isinstance(body, ast.Call) and isinstance(
                    body.func, ast.Name):
                self.builder_names.add(body.func.id)
            elif isinstance(body, ast.Lambda):
                # builder returns the kernel directly
                self.traced.add(body)

    def visit_Call(self, node):
        fname = ""
        if isinstance(node.func, ast.Name):
            fname = node.func.id
        elif isinstance(node.func, ast.Attribute):
            fname = node.func.attr
        if fname == "cached_kernel" and len(node.args) >= 2:
            self._mark_builder_expr(node.args[1])
        elif _is_jit_expr(node.func) and node.args:
            a0 = node.args[0]
            if isinstance(a0, ast.Lambda):
                self.traced.add(a0)
            elif isinstance(a0, ast.Name):
                # resolved after the full pass — the def may follow
                self.jit_target_names.add(a0.id)
        self.generic_visit(node)


def _returned_kernels(fn: ast.AST):
    """Functions/lambdas a builder returns — those bodies get traced."""
    out = []
    local_defs = {}
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not fn:
                local_defs[node.name] = node
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            v = node.value
            if isinstance(v, ast.Lambda):
                out.append(v)
            elif isinstance(v, ast.Name) and v.id in local_defs:
                out.append(local_defs[v.id])
    return out


class HostSyncInJitRule(Rule):
    name = "host-sync-in-jit"

    def check(self, mod: SourceModule) -> Iterable[Finding]:
        idx = _ModuleIndex()
        idx.visit(mod.tree)
        traced = set(idx.traced)
        for tname in idx.jit_target_names:
            traced.update(idx.defs.get(tname, ()))
        for bname in idx.builder_names - idx.jit_target_names:
            for fn in idx.defs.get(bname, ()):
                traced.update(_returned_kernels(fn))
        out: List[Finding] = []
        seen: Set[int] = set()
        for fn in traced:
            for node in ast.walk(fn):
                msg = self._flag(node)
                if msg and node.lineno not in seen:
                    seen.add(node.lineno)
                    out.append(Finding(
                        self.name, mod.rel, node.lineno,
                        f"{msg} inside a jit-traced kernel body "
                        f"(`{mod.snippet(node.lineno)}`)"))
        return out

    def _flag(self, node) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in SYNC_ATTRS:
                return f".{f.attr}() host sync"
            if (f.attr in NP_SYNC_FUNCS and isinstance(f.value, ast.Name)
                    and f.value.id in ("np", "numpy", "onp")):
                return f"np.{f.attr} host materialization"
        elif isinstance(f, ast.Name) and f.id in COERCIONS:
            # float(1e-6) etc. on literals is shape-static and fine
            if node.args and not isinstance(node.args[0], ast.Constant):
                return f"{f.id}() scalar coercion"
        return None
