"""``blocking-wait`` — uncancellable waits in runtime/ and parallel/.

AST migration of the PR-5 regex gate
(``docs_gen.check_blocking_waits_cancellable``): a bare ``<cv>.wait()``
(no timeout — a cancel can never wake it unless the CV is registered
with the token, and even then an unbounded wait defeats the
poll-interval guarantee) or a plain ``time.sleep(...)`` (should be
``cancel.sleep`` / a token-bounded wait).  AST-exact: a ``.wait()``
inside a string or comment no longer counts, and ``wait(timeout=None)``
— which the regex missed — now does.

The preemption plane adds a second requirement in runtime/: a BOUNDED
``.wait(timeout=...)`` is only half the contract.  Waking up on time is
useless if the waking function never consults the query token — the
thread rides straight back into the wait and a suspend request (or a
cancel) parks unobserved until some other yield point.  So any function
in runtime/ containing a bounded ``.wait`` must also poll the token:
call one of ``check`` / ``preempt_point`` / ``preempt_pending`` /
``wait_interval`` somewhere in the same function (``wait_interval``
counts because deriving the timeout from the token is exactly the
poll-interval contract).  Daemon/shim waits with no query scope stay
``# cancel-exempt`` with a reason, as before.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from spark_rapids_tpu.utils.lint import Finding, Rule, SourceModule

SCOPES = ("runtime", "parallel")

#: runtime/-only: bounded waits must live in a token-polling function
PREEMPT_SCOPES = ("runtime",)

#: any of these called anywhere in the function counts as polling the
#: query token around the wait
POLL_CALLS = frozenset(
    {"check", "preempt_point", "preempt_pending", "wait_interval"})

#: cluster-tenancy directive handlers get the token-polling requirement
#: in EVERY lint scope (not just runtime/): a suspend/resume/shed
#: applier that parks in a bounded wait without consulting the token
#: can wedge the cross-process protocol — the lease expiry that is
#: supposed to unwedge it is itself observed via the token
DIRECTIVE_MARKER = "directive"


def _in_scope(rel: str) -> bool:
    parts = rel.replace("\\", "/").split("/")
    return any(p in SCOPES for p in parts[:-1])


def _in_preempt_scope(rel: str) -> bool:
    parts = rel.replace("\\", "/").split("/")
    return any(p in PREEMPT_SCOPES for p in parts[:-1])


def _is_unbounded_wait(call: ast.Call) -> bool:
    """``x.wait()`` or ``x.wait(None)`` / ``x.wait(timeout=None)``."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "wait"):
        return False
    args = call.args + [kw.value for kw in call.keywords
                        if kw.arg in (None, "timeout")]
    if not args:
        return True
    return all(isinstance(a, ast.Constant) and a.value is None
               for a in args)


def _is_plain_sleep(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "sleep"
            and isinstance(f.value, ast.Name) and f.value.id == "time")


def _is_bounded_wait(call: ast.Call) -> bool:
    """``x.wait(<non-None timeout>)`` — bounded, so cancel-legal, but
    the enclosing function must still poll the token (see module
    docstring)."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "wait"):
        return False
    return not _is_unbounded_wait(call)


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _own_calls(fn: ast.AST) -> List[ast.Call]:
    """Call nodes lexically inside ``fn`` but outside any nested
    function — a nested function's waits are judged against the nested
    function's own polling."""
    out: List[ast.Call] = []

    def walk(node, root=False):
        if not root and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(node, ast.Call):
            out.append(node)
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(fn, root=True)
    return out


class BlockingWaitRule(Rule):
    name = "blocking-wait"

    def check(self, mod: SourceModule) -> Iterable[Finding]:
        if not _in_scope(mod.rel):
            return ()
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_unbounded_wait(node):
                out.append(Finding(
                    self.name, mod.rel, node.lineno,
                    "unbounded .wait() — pass a token-bounded timeout "
                    f"(`{mod.snippet(node.lineno)}`)"))
            elif _is_plain_sleep(node):
                out.append(Finding(
                    self.name, mod.rel, node.lineno,
                    "plain time.sleep — use cancel.sleep / a "
                    f"token-bounded wait (`{mod.snippet(node.lineno)}`)"))
        if _in_preempt_scope(mod.rel):
            out.extend(self._check_preempt_aware(mod))
        else:
            # outside runtime/ only DIRECTIVE handlers carry the
            # token-polling contract (parallel/ waits are otherwise
            # bounded-is-fine — see PREEMPT_SCOPES)
            out.extend(self._check_preempt_aware(
                mod, only_directive=True))
        return out

    def _check_preempt_aware(self, mod: SourceModule,
                             only_directive: bool = False
                             ) -> Iterable[Finding]:
        """runtime/ bounded waits must sit in a token-polling function
        (module-level waits have no query scope and are skipped — the
        unbounded/plain-sleep checks above still cover them).  With
        ``only_directive`` the check narrows to functions whose name
        contains ``directive`` — the cluster-tenancy fan-out path,
        which must stay cancel/preempt-aware in every scope."""
        out: List[Finding] = []
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if (only_directive
                    and DIRECTIVE_MARKER not in fn.name.lower()):
                continue
            calls = _own_calls(fn)
            if any(_call_name(c) in POLL_CALLS for c in calls):
                continue
            for call in calls:
                if _is_bounded_wait(call):
                    what = ("directive handler with preempt-unaware "
                            "bounded wait" if only_directive else
                            "preempt-unaware bounded wait")
                    out.append(Finding(
                        self.name, mod.rel, call.lineno,
                        f"{what} — poll the query "
                        "token (check/preempt_point/wait_interval) "
                        "around the wait so a suspend request lands "
                        f"(`{mod.snippet(call.lineno)}`)"))
        return out
