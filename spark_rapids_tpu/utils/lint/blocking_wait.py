"""``blocking-wait`` — uncancellable waits in runtime/ and parallel/.

AST migration of the PR-5 regex gate
(``docs_gen.check_blocking_waits_cancellable``): a bare ``<cv>.wait()``
(no timeout — a cancel can never wake it unless the CV is registered
with the token, and even then an unbounded wait defeats the
poll-interval guarantee) or a plain ``time.sleep(...)`` (should be
``cancel.sleep`` / a token-bounded wait).  AST-exact: a ``.wait()``
inside a string or comment no longer counts, and ``wait(timeout=None)``
— which the regex missed — now does.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from spark_rapids_tpu.utils.lint import Finding, Rule, SourceModule

SCOPES = ("runtime", "parallel")


def _in_scope(rel: str) -> bool:
    parts = rel.replace("\\", "/").split("/")
    return any(p in SCOPES for p in parts[:-1])


def _is_unbounded_wait(call: ast.Call) -> bool:
    """``x.wait()`` or ``x.wait(None)`` / ``x.wait(timeout=None)``."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "wait"):
        return False
    args = call.args + [kw.value for kw in call.keywords
                        if kw.arg in (None, "timeout")]
    if not args:
        return True
    return all(isinstance(a, ast.Constant) and a.value is None
               for a in args)


def _is_plain_sleep(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "sleep"
            and isinstance(f.value, ast.Name) and f.value.id == "time")


class BlockingWaitRule(Rule):
    name = "blocking-wait"

    def check(self, mod: SourceModule) -> Iterable[Finding]:
        if not _in_scope(mod.rel):
            return ()
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_unbounded_wait(node):
                out.append(Finding(
                    self.name, mod.rel, node.lineno,
                    "unbounded .wait() — pass a token-bounded timeout "
                    f"(`{mod.snippet(node.lineno)}`)"))
            elif _is_plain_sleep(node):
                out.append(Finding(
                    self.name, mod.rel, node.lineno,
                    "plain time.sleep — use cancel.sleep / a "
                    f"token-bounded wait (`{mod.snippet(node.lineno)}`)"))
        return out
