"""cache-safety rule: registered inputs change only at the bump
chokepoint.

The result cache (spark_rapids_tpu/cache/) is sound exactly as long as
every mutation of a registered input flows through the fingerprint-bump
chokepoint: ``TpuSession.registerTable`` re-mints the content digest
and invalidates dependent entries.  Code that rebinds a ``_catalog``
entry or re-assigns a relation's ``fingerprint`` anywhere else changes
what a query reads WITHOUT changing its result key — the exact bug
class that serves stale results.  This rule fails any module outside
the sanctioned set that

- assigns, augments, or deletes a subscript of a ``_catalog`` mapping
  (``x._catalog[name] = ...`` / ``del ...``),
- calls a mutating mapping method on a ``_catalog`` attribute
  (``pop``/``update``/``clear``/``setdefault``/``popitem``/
  ``__setitem__``), or
- assigns a ``.fingerprint`` attribute (relation fingerprints are
  minted only by cache/fingerprints.py).

Reading the catalog (``self._catalog[name]``, ``in`` checks) stays
legal everywhere.  A deliberate mutation carries::

    # lint: exempt(cache-safety): <why>
"""

from __future__ import annotations

import ast
from typing import Iterable

from spark_rapids_tpu.utils.lint import Finding, Rule, SourceModule

# the fingerprint chokepoint + the catalog's owning session
ALLOWED = (
    "spark_rapids_tpu/cache/fingerprints.py",
    "spark_rapids_tpu/sql/session.py",
)

_MUTATORS = {"pop", "update", "clear", "setdefault", "popitem",
             "__setitem__"}


def _is_catalog(node: ast.AST) -> bool:
    """True for a ``_catalog`` name or ``<x>._catalog`` attribute."""
    return (isinstance(node, ast.Name) and node.id == "_catalog") or (
        isinstance(node, ast.Attribute) and node.attr == "_catalog")


class CacheSafetyRule(Rule):
    name = "cache-safety"

    def check(self, mod: SourceModule) -> Iterable[Finding]:
        rel = mod.rel.replace("\\", "/")
        if rel in ALLOWED:
            return
        for node in ast.walk(mod.tree):
            targets = ()
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = (node.target,)
            elif isinstance(node, ast.Delete):
                targets = node.targets
            for t in targets:
                if isinstance(t, ast.Subscript) and _is_catalog(t.value):
                    yield Finding(
                        self.name, mod.rel, node.lineno,
                        "catalog entry mutated outside the "
                        "fingerprint-bump chokepoint — rebind tables "
                        "via session.registerTable so the content "
                        "digest is re-minted and stale cached results "
                        "are invalidated")
                elif (isinstance(t, ast.Attribute)
                        and t.attr == "fingerprint"):
                    yield Finding(
                        self.name, mod.rel, node.lineno,
                        "relation fingerprint assigned outside "
                        "cache/fingerprints.py — fingerprints are "
                        "minted only at the chokepoint; assigning one "
                        "elsewhere can alias a stale cached result")
            if isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in _MUTATORS
                        and _is_catalog(f.value)):
                    yield Finding(
                        self.name, mod.rel, node.lineno,
                        f"_catalog.{f.attr}() outside the "
                        "fingerprint-bump chokepoint — catalog "
                        "mutation must flow through "
                        "session.registerTable")
