"""``fusion-purity`` — fused-region builders stay host-pull-free.

A fused region's whole point is that N operators run as ONE jitted
program with their intermediates as device-resident SSA values
(docs/fusion.md).  The functions that build those programs — the
fusion plane (``spark_rapids_tpu/fusion/``), ``exec/fused.py``, and
every operator's ``fusion()`` region-builder hook in ``exec/`` — must
therefore never materialize on the host: a ``np.asarray`` / ``.item()``
/ ``device_get`` there either fails at trace time inside the region
program or silently reinstates a per-batch host round trip *multiplied
by every region the operator joins*.  The region-selection contract
("fusable" == provably host-pull-free) is exactly this rule: an
operator whose hook can't pass it must keep ``fusion() -> None`` and
stay a region boundary.  Same flag tables as ``exchange-purity`` /
``kernel-purity`` so the three rules can't drift.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from spark_rapids_tpu.utils.lint import Finding, Rule, SourceModule
from spark_rapids_tpu.utils.lint.exchange_purity import (
    ExchangePurityRule)

SCOPE_PREFIX = "spark_rapids_tpu/fusion/"
SCOPE_FILES = ("spark_rapids_tpu/exec/fused.py",)
# outside the plane itself, only the region-builder hooks are in scope
HOOK_PREFIX = "spark_rapids_tpu/exec/"
HOOK_NAME = "fusion"


class FusionPurityRule(Rule):
    name = "fusion-purity"

    def check(self, mod: SourceModule) -> Iterable[Finding]:
        whole_module = (mod.rel.startswith(SCOPE_PREFIX)
                        or mod.rel in SCOPE_FILES)
        if not whole_module and not mod.rel.startswith(HOOK_PREFIX):
            return ()
        flag = ExchangePurityRule()._flag
        out: List[Finding] = []
        seen: Set[int] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not whole_module and node.name != HOOK_NAME:
                continue
            for sub in ast.walk(node):
                msg = flag(sub)
                if msg and sub.lineno not in seen:
                    seen.add(sub.lineno)
                    out.append(Finding(
                        self.name, mod.rel, sub.lineno,
                        f"{msg} inside fused-region builder "
                        f"`{node.name}` "
                        f"(`{mod.snippet(sub.lineno)}`)"))
        return out
