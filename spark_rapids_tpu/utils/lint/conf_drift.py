"""``conf-drift`` — the conf registry and its read sites can't diverge.

Two directions:

* **phantom key** — a string-literal ``conf.get("spark.rapids...")`` /
  ``get_raw("spark.rapids...")`` whose key is not in the conf.py
  registry reads a default forever and silently ignores the user's
  setting.  (Per-op kill-switch prefixes
  ``spark.rapids.sql.{exec,expression}.`` are registered dynamically
  and excluded.)
* **dead conf** — a registered key with NO read site anywhere in the
  package documents a knob that does nothing.  A read site is a Load
  reference to the key's conf.py constant (``C.RETRY_MAX``, a
  ``RapidsConf`` property using it, the family dict for loop-registered
  keys) or a string-literal ``get``/``get_raw`` of the key itself.

The registry is imported live (same registry-is-the-truth coupling the
docs generators use), so a key added to conf.py without a consumer
fails tier-1 the moment it lands.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from spark_rapids_tpu.utils.lint import Finding, Rule, SourceModule

DYNAMIC_PREFIXES = ("spark.rapids.sql.exec.",
                    "spark.rapids.sql.expression.",
                    "spark.rapids.tpu.scheduler.tenant.")
READ_CALLS = {"get", "get_raw"}


class ConfDriftRule(Rule):
    name = "conf-drift"

    def __init__(self):
        # (mod.rel, line, key) of every string-literal conf read
        self.literal_reads: List[Tuple[str, int, str]] = []
        # identifier -> Load-reference seen outside conf.py
        self.loads_elsewhere: Set[str] = set()
        # Load references inside conf.py (property bodies count as
        # reads; the declaration itself is a Store and never counts)
        self.loads_in_conf: Set[str] = set()
        self.conf_rel = None
        self.conf_mod = None

    def check(self, mod: SourceModule) -> Iterable[Finding]:
        is_conf = mod.rel.replace("\\", "/").endswith(
            "spark_rapids_tpu/conf.py")
        if is_conf:
            self.conf_rel = mod.rel
            self.conf_mod = mod
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in READ_CALLS and node.args):
                a0 = node.args[0]
                if (isinstance(a0, ast.Constant)
                        and isinstance(a0.value, str)
                        and a0.value.startswith("spark.rapids.")):
                    self.literal_reads.append(
                        (mod.rel, node.lineno, a0.value))
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load):
                (self.loads_in_conf if is_conf
                 else self.loads_elsewhere).add(node.id)
            elif isinstance(node, ast.Attribute):
                (self.loads_in_conf if is_conf
                 else self.loads_elsewhere).add(node.attr)
        return ()

    # -- registry introspection -----------------------------------------

    def _registry_maps(self):
        """key -> constant name(s), from the LIVE registry + conf module
        namespace; and key -> conf.py declaration line."""
        from spark_rapids_tpu import conf as C
        key_to_names: Dict[str, Set[str]] = {
            k: set() for k in C.REGISTRY.entries}
        family_names: Dict[str, Set[str]] = {}
        for attr, val in vars(C).items():
            if isinstance(val, C.ConfEntry):
                key_to_names.setdefault(val.key, set()).add(attr)
            elif isinstance(val, dict) and val and all(
                    isinstance(v, C.ConfEntry) for v in val.values()):
                for v in val.values():
                    family_names.setdefault(v.key, set()).add(attr)
        decl_lines: Dict[str, int] = {}
        for node in ast.walk(self.conf_mod.tree):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Name) and node.func.id == "conf":
                if (node.args and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    decl_lines[node.args[0].value] = node.lineno
        return key_to_names, family_names, decl_lines

    def finalize(self) -> Iterable[Finding]:
        from spark_rapids_tpu import conf as C
        out: List[Finding] = []
        registered = set(C.REGISTRY.entries)
        for rel, line, key in self.literal_reads:
            if key in registered:
                continue
            if any(key.startswith(p) for p in DYNAMIC_PREFIXES):
                continue
            out.append(Finding(
                self.name, rel, line,
                f"conf key {key!r} is not in the conf.py registry — "
                "a read of it returns the fallback default forever"))
        if self.conf_mod is None:
            # partial run (rule fixture tests): without conf.py scanned
            # the dead-conf direction has no declaration sites to anchor
            return out
        key_to_names, family_names, decl_lines = self._registry_maps()
        literal_keys = {k for _, _, k in self.literal_reads}
        loads_any = self.loads_elsewhere | self.loads_in_conf
        for key in sorted(registered):
            names = key_to_names.get(key) or set()
            fams = family_names.get(key) or set()
            if key in literal_keys:
                continue
            if any(n in loads_any for n in names):
                continue
            # family dicts: conf.py's own subscript-store also Loads the
            # dict name, so only references OUTSIDE conf.py count
            if any(f in self.loads_elsewhere for f in fams):
                continue
            line = decl_lines.get(key, 1)
            out.append(Finding(
                self.name, self.conf_rel or "spark_rapids_tpu/conf.py",
                line,
                f"registered conf key {key!r} has no read site in the "
                "package (dead conf)"))
        return out
