"""scheduler-bypass rule: all device admission goes through the
scheduler.

The multi-tenant admission controller (runtime/scheduler.py) is only a
real gate if nothing routes around it: an exec or IO path that grabs
``get_semaphore`` and ``hold``s permits directly would consume device
admission the fairness dispatcher and load-shed watermarks never saw.
This rule fails any module outside the sanctioned set that

- calls ``get_semaphore`` (the gateway to the process semaphore), or
- instantiates ``DeviceSemaphore`` directly (a private semaphore
  escapes the cap entirely).

``peek_semaphore`` stays legal everywhere — observation (telemetry
gauges, health probes, the admission controller's own saturation
signal) must not require an exemption.  A deliberate bypass carries::

    # lint: exempt(scheduler-bypass): <why>
"""

from __future__ import annotations

import ast
from typing import Iterable

from spark_rapids_tpu.utils.lint import Finding, Rule, SourceModule

# the admission path itself + the module that owns the semaphore
ALLOWED = (
    "spark_rapids_tpu/runtime/scheduler.py",
    "spark_rapids_tpu/runtime/semaphore.py",
)


def _callee_name(func: ast.AST) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


class SchedulerBypassRule(Rule):
    name = "scheduler-bypass"

    def check(self, mod: SourceModule) -> Iterable[Finding]:
        rel = mod.rel.replace("\\", "/")
        if rel in ALLOWED:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _callee_name(node.func)
            if callee == "get_semaphore":
                yield Finding(
                    self.name, mod.rel, node.lineno,
                    "get_semaphore() outside the scheduler's admission "
                    "path — acquire device admission via "
                    "runtime.scheduler.device_hold so per-tenant "
                    "fairness and load shedding see this traffic "
                    "(peek_semaphore is fine for observation)")
            elif callee == "DeviceSemaphore":
                yield Finding(
                    self.name, mod.rel, node.lineno,
                    "direct DeviceSemaphore construction outside "
                    "runtime/semaphore.py — a private semaphore "
                    "escapes the process concurrency cap and the "
                    "scheduler's admission control")
