"""``python -m spark_rapids_tpu.utils.lint`` — tier-1 invariant gate."""

import sys

from spark_rapids_tpu.utils.lint import main

sys.exit(main(sys.argv[1:]))
