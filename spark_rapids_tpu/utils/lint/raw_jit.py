"""raw-jit rule: every jit compilation goes through the kernel cache.

``runtime/kernel_cache.py`` is the engine's single compile chokepoint:
it fingerprints the kernel, counts the compile in
``tpuq_kernel_compile_total`` (the compile-storm health signal), tags
the trace span, routes the build through the ``compile`` failure
domain, and — with ``spark.rapids.tpu.kernel.cacheDir`` — persists the
executable.  A ``jax.jit`` call anywhere else bypasses ALL of that: its
compiles are invisible to storm detection, un-retried on injected
faults, and never land in the persistent cache, so a "warmed" server
still pays them on the hot path.

This rule flags ``jax.jit(...)`` calls and ``@jax.jit`` decorators in
any module other than runtime/kernel_cache.py.  A deliberate raw jit
(e.g. a sharding-constrained collective wrapper ``cached_kernel``
cannot express) carries::

    # jit-exempt: <why>

(an alias for ``# lint: exempt(raw-jit): <why>``).
"""

from __future__ import annotations

import ast
from typing import Iterable

from spark_rapids_tpu.utils.lint import Finding, Rule, SourceModule

# the compile chokepoint itself
ALLOWED = ("spark_rapids_tpu/runtime/kernel_cache.py",)


def _is_jax_jit(node: ast.AST) -> bool:
    """``jax.jit`` as an attribute access (call or decorator base)."""
    return (isinstance(node, ast.Attribute) and node.attr == "jit"
            and isinstance(node.value, ast.Name)
            and node.value.id == "jax")


class RawJitRule(Rule):
    name = "raw-jit"

    def check(self, mod: SourceModule) -> Iterable[Finding]:
        rel = mod.rel.replace("\\", "/")
        if rel in ALLOWED:
            return
        for node in ast.walk(mod.tree):
            sites = []
            if isinstance(node, ast.Call) and _is_jax_jit(node.func):
                sites.append(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    # @jax.jit and @jax.jit(static_argnums=...) — the
                    # call form is also an ast.Call caught above, so
                    # only the bare-attribute decorator needs this arm
                    if _is_jax_jit(dec):
                        sites.append(dec)
            for site in sites:
                yield Finding(
                    self.name, mod.rel, site.lineno,
                    "raw jax.jit outside runtime/kernel_cache.py — "
                    "route compilation through cached_kernel so it is "
                    "fingerprint-cached, counted by compile-storm "
                    "telemetry, retried via the compile failure "
                    "domain, and persisted by kernel.cacheDir "
                    "(deliberate: '# jit-exempt: <why>')")
