"""Equality assertions for the CPU-vs-TPU oracle harness.

[REF: integration_tests/src/main/python/asserts.py ::
 assert_gpu_and_cpu_are_equal_collect] — NaN compares equal to NaN and
-0.0 equal to 0.0 is NOT applied (Spark collects distinguish them via
java semantics; we follow: NaN == NaN for test equality, -0.0 != 0.0 only
when bit-compare is requested).
"""

from __future__ import annotations

import math

import numpy as np
import pyarrow as pa


def _values_equal(a, b, approx_float: bool, rel: float) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) and isinstance(b, float):
        if math.isnan(a) and math.isnan(b):
            return True
        if approx_float:
            if math.isinf(a) or math.isinf(b):
                return a == b
            return math.isclose(a, b, rel_tol=rel, abs_tol=rel)
        return a == b
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(
            _values_equal(x, y, approx_float, rel) for x, y in zip(a, b))
    if isinstance(a, dict) and isinstance(b, dict):
        return a.keys() == b.keys() and all(
            _values_equal(a[k], b[k], approx_float, rel) for k in a)
    return a == b


def assert_columns_equal(expected: pa.ChunkedArray, actual: pa.ChunkedArray,
                         name: str = "", approx_float: bool = False,
                         rel: float = 1e-6):
    ev = expected.to_pylist()
    av = actual.to_pylist()
    assert len(ev) == len(av), (
        f"column {name}: row count {len(ev)} != {len(av)}")
    for i, (e, a) in enumerate(zip(ev, av)):
        assert _values_equal(e, a, approx_float, rel), (
            f"column {name} row {i}: expected {e!r} got {a!r}")


def assert_tables_equal(expected: pa.Table, actual: pa.Table,
                        approx_float: bool = False, ignore_order: bool = False,
                        rel: float = 1e-6):
    assert expected.column_names == actual.column_names, (
        f"schema mismatch: {expected.column_names} vs {actual.column_names}")
    if ignore_order and expected.num_rows > 0:
        expected = _sorted_for_compare(expected)
        actual = _sorted_for_compare(actual)
    for name in expected.column_names:
        assert_columns_equal(expected.column(name), actual.column(name),
                             name, approx_float, rel)


def _sort_key(v):
    if v is None:
        return (0,)
    if isinstance(v, float) and math.isnan(v):
        return (2,)
    if isinstance(v, (list, tuple)):
        return (1, tuple(_sort_key(x) for x in v))
    if isinstance(v, dict):
        return (1, tuple(sorted((k, _sort_key(x)) for k, x in v.items())))
    return (1, v)


def _sorted_for_compare(tbl: pa.Table) -> pa.Table:
    rows = list(zip(*[tbl.column(i).to_pylist() for i in range(tbl.num_columns)]))
    try:
        rows.sort(key=lambda r: tuple(_sort_key(v) for v in r))
    except TypeError:
        rows.sort(key=lambda r: tuple(str(v) for v in r))
    if not rows:
        return tbl
    cols = list(zip(*rows))
    return pa.table(
        [pa.array(list(c), type=tbl.column(i).type) for i, c in enumerate(cols)],
        names=tbl.column_names)
