"""Exchange-bandwidth microbench, shared by bench.py and the multichip
dry-run.

Measures the SAME programs the engine's compiled exchange runs
(parallel/shuffle.py ``build_prepare_program`` / ``build_boundary_program``)
plus an in-memory floor for the host transport, per partition count —
so ``BENCH_*`` and ``MULTICHIP_*`` report one consistent trajectory for
the 0.05 GB/s → compiled-collective gap.

Three numbers per partition count:

* ``compiled`` — the boundary program alone (clip-gather + tiled
  all_to_all + receive mask): the marginal cost of a stage seam, what
  ``ici_all_to_all_virtual8`` tracks.  Reps are dispatched pipelined and
  synced once, the way a pump overlaps seams with compute.
* ``e2e`` — prepare + counts host round-trip + boundary: a full cold
  exchange of a never-before-partitioned batch.
* ``host`` — D2H, numpy stable partition sort, H2D.  Deliberately
  FLATTERING to the host path (no files, no serializer framing, no
  framing copies) so a compiled win over it is a lower bound.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, Optional, Sequence

import numpy as np


def _sync(outs) -> None:
    """Wait for dispatched device work.

    ``block_until_ready`` covers in-process backends; tunnel-backed
    platforms (axon) need a real pull, so one element of every local
    shard of the last output is fetched as well."""
    import jax
    jax.block_until_ready(outs)
    last = outs[-1] if isinstance(outs, (list, tuple)) else outs
    leaf = jax.tree.leaves(last)[0]
    for s in leaf.addressable_shards:
        np.asarray(s.data[:1])


def _rtt_seconds(reps: int = 5) -> float:
    """Trivial-kernel dispatch+pull round trip, subtracted from timings
    so tunnel latency is not billed to the exchange."""
    import jax
    import jax.numpy as jnp
    # jit-exempt: bench-only trivial kernel, not an engine hot path
    tiny = jax.jit(lambda x: x + 1)
    x = jnp.int32(0)
    int(tiny(x))
    t0 = time.perf_counter()
    for _ in range(reps):
        int(tiny(x))
    return (time.perf_counter() - t0) / reps


def _make_batch(n_rows: int, seed: int = 11):
    import pyarrow as pa

    from spark_rapids_tpu.columnar.column import host_to_device
    rng = np.random.default_rng(seed)
    table = pa.table({"k": rng.integers(0, 1 << 40, n_rows),
                      "v": rng.uniform(0, 1, n_rows)})
    return host_to_device(table)


def exchange_bench(n_rows: int = 1 << 22,
                   parts: Optional[Sequence[int]] = None,
                   reps: int = 10, e2e_reps: int = 3, host_reps: int = 3,
                   modes: Iterable[str] = ("compiled", "e2e", "host"),
                   ) -> Dict[str, Dict[str, float]]:
    """GB/s per partition count for the requested modes.

    ``n_rows`` must be a power of two (shard divisibility).  Returns
    ``{str(nparts): {"compiled": gbps, "e2e": gbps, "host": gbps,
    "bytes": payload}}`` — missing modes were not requested; a mode
    that cannot run on this platform records ``None``."""
    import jax

    from spark_rapids_tpu.columnar import dtypes as T
    from spark_rapids_tpu.ops.expressions import BoundReference
    from spark_rapids_tpu.parallel import shuffle as SH
    from spark_rapids_tpu.parallel.mesh import make_mesh, named_sharding
    from spark_rapids_tpu.runtime.device import ensure_initialized
    ensure_initialized()
    ndev = jax.device_count()
    if parts is None:
        parts = [ndev]
    batch = _make_batch(n_rows)
    nbytes = n_rows * 16  # k int64 + v float64 payload
    keys = [BoundReference(0, T.LongT)]
    out: Dict[str, Dict[str, float]] = {}
    for p in parts:
        if p > ndev:
            continue
        mesh = make_mesh(p)
        sharded = SH.shard_batch(mesh, batch)
        local_b = batch.capacity // p
        prep = SH.build_prepare_program(mesh, keys, p)
        idx, counts = prep(sharded)
        counts_np = np.asarray(counts).reshape(p, p)
        cap = SH.exchange_cap(counts_np.max(), local_b)
        shd = named_sharding(mesh)
        crecv = jax.device_put(
            np.ascontiguousarray(counts_np.T.astype(np.int32)), shd)
        # donate=False: the bench re-dispatches the same input buffers
        fn = SH.build_boundary_program(mesh, p, cap, donate=False)
        res: Dict[str, float] = {"bytes": nbytes}
        if "compiled" in modes:
            _sync(fn(sharded, idx, crecv))  # compile + warm
            rtt = _rtt_seconds()
            t0 = time.perf_counter()
            last = None
            for _ in range(reps):
                # pipelined: no sync between dispatches, but drop the
                # previous output so rep buffers recycle instead of
                # stacking up (keeping all alive costs ~60% bandwidth)
                last = fn(sharded, idx, crecv)
            _sync(last)
            per = max((time.perf_counter() - t0 - rtt) / reps, 1e-9)
            res["compiled"] = nbytes / per / 1e9
            del last
        if "e2e" in modes:
            def one():
                idx2, c2 = prep(sharded)
                cnp = np.asarray(c2).reshape(p, p)
                cr = jax.device_put(
                    np.ascontiguousarray(cnp.T.astype(np.int32)), shd)
                _sync(fn(sharded, idx2, cr))
            one()  # warm
            t0 = time.perf_counter()
            for _ in range(e2e_reps):
                one()
            res["e2e"] = nbytes / ((time.perf_counter() - t0)
                                   / e2e_reps) / 1e9
        if "host" in modes:
            res["host"] = _host_floor_gbps(batch, keys, p, nbytes,
                                           host_reps)
        out[str(p)] = res
        del sharded, idx, counts, crecv
    return out


def _host_floor_gbps(batch, keys, nparts: int, nbytes: int,
                     reps: int) -> float:
    """The host transport's in-memory floor: D2H every leaf, numpy
    stable partition sort, H2D.  Pids are precomputed (free for the
    host path) — every cost left is one the real transport must pay."""
    import jax

    from spark_rapids_tpu.parallel import shuffle as SH
    # jit-exempt: bench-only pid precompute, not an engine hot path
    pid = np.asarray(jax.jit(SH.make_pid_fn(keys, nparts))(batch))
    k_dev = batch.columns[0].data
    v_dev = batch.columns[1].data

    def one():
        k = np.asarray(k_dev)
        v = np.asarray(v_dev)
        order = np.argsort(pid, kind="stable")
        jax.block_until_ready(jax.device_put((k[order], v[order])))

    one()  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        one()
    return nbytes / ((time.perf_counter() - t0) / reps) / 1e9
