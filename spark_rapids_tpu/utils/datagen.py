"""Seeded, typed random data generation for tests and fuzzing.

Python twin of the reference's test datagen
[REF: integration_tests/src/main/python/data_gen.py :: IntegerGen, StringGen,
 DecimalGen, ...] and the Scala datagen module [REF: datagen/].  Generators
are deterministic from a seed, control null ratio, and inject the special
values that break naive kernels (NaN, ±0.0, int min/max, epoch edges).
"""

from __future__ import annotations

import datetime
import decimal
import string as _string
from typing import List, Optional, Sequence

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.columnar import dtypes as T


class DataGen:
    def __init__(self, dtype: T.DataType, nullable: bool = True,
                 null_ratio: float = 0.08):
        self.dtype = dtype
        self.nullable = nullable
        self.null_ratio = null_ratio if nullable else 0.0

    def _null_mask(self, rng: np.random.Generator, n: int) -> np.ndarray:
        if self.null_ratio <= 0:
            return np.zeros(n, dtype=bool)
        return rng.random(n) < self.null_ratio

    def generate_values(self, rng: np.random.Generator, n: int):
        raise NotImplementedError

    def special_values(self) -> list:
        return []

    def generate(self, rng: np.random.Generator, n: int) -> pa.Array:
        vals = list(self.generate_values(rng, n))
        nulls = self._null_mask(rng, n)
        # inject special values into distinct non-null slots so every edge
        # value is guaranteed present (nulls are decided first so they can't
        # erase an injected special)
        specials = self.special_values()
        if specials and n > 0:
            non_null = np.flatnonzero(~nulls)
            if len(non_null) == 0:
                non_null = np.arange(n)
                nulls[:] = False
            slots = rng.permutation(non_null)[: len(specials)]
            for sv, pos in zip(specials, slots):
                vals[int(pos)] = sv
        out = [None if nulls[i] else vals[i] for i in range(n)]
        return pa.array(out, type=T.to_arrow(self.dtype))


class BooleanGen(DataGen):
    def __init__(self, **kw):
        super().__init__(T.BooleanT, **kw)

    def generate_values(self, rng, n):
        return rng.integers(0, 2, n).astype(bool).tolist()


class _IntGen(DataGen):
    BITS = 32

    def __init__(self, dtype, min_val=None, max_val=None, **kw):
        super().__init__(dtype, **kw)
        lo = -(2 ** (self.BITS - 1))
        hi = 2 ** (self.BITS - 1) - 1
        self.min_val = lo if min_val is None else min_val
        self.max_val = hi if max_val is None else max_val

    def generate_values(self, rng, n):
        return rng.integers(self.min_val, self.max_val, n,
                            dtype=np.int64, endpoint=True).tolist()

    def special_values(self):
        return [self.min_val, self.max_val, 0]


class ByteGen(_IntGen):
    BITS = 8

    def __init__(self, **kw):
        super().__init__(T.ByteT, **kw)


class ShortGen(_IntGen):
    BITS = 16

    def __init__(self, **kw):
        super().__init__(T.ShortT, **kw)


class IntegerGen(_IntGen):
    BITS = 32

    def __init__(self, **kw):
        super().__init__(T.IntegerT, **kw)


class LongGen(_IntGen):
    BITS = 64

    def __init__(self, **kw):
        super().__init__(T.LongT, **kw)


class FloatGen(DataGen):
    def __init__(self, no_nans: bool = False, **kw):
        super().__init__(T.FloatT, **kw)
        self.no_nans = no_nans

    def generate_values(self, rng, n):
        v = (rng.standard_normal(n) * 1e6).astype(np.float32)
        return v.tolist()

    def special_values(self):
        sv = [0.0, -0.0, float(np.finfo(np.float32).max),
              float(np.finfo(np.float32).min), float("inf"), float("-inf")]
        if not self.no_nans:
            sv.append(float("nan"))
        return sv


class DoubleGen(DataGen):
    def __init__(self, no_nans: bool = False, **kw):
        super().__init__(T.DoubleT, **kw)
        self.no_nans = no_nans

    def generate_values(self, rng, n):
        return (rng.standard_normal(n) * 1e12).tolist()

    def special_values(self):
        sv = [0.0, -0.0, 1.7976931348623157e308, -1.7976931348623157e308,
              float("inf"), float("-inf")]
        if not self.no_nans:
            sv.append(float("nan"))
        return sv


class StringGen(DataGen):
    def __init__(self, charset: str = _string.ascii_letters + _string.digits + " ",
                 min_len: int = 0, max_len: int = 20, **kw):
        super().__init__(T.StringT, **kw)
        self.charset = charset
        self.min_len = min_len
        self.max_len = max_len

    def generate_values(self, rng, n):
        lens = rng.integers(self.min_len, self.max_len, n, endpoint=True)
        chars = np.array(list(self.charset))
        out = []
        for ln in lens:
            out.append("".join(chars[rng.integers(0, len(chars), ln)]))
        return out

    def special_values(self):
        return ["", " ", "a" * self.max_len]


class DecimalGen(DataGen):
    def __init__(self, precision: int = 10, scale: int = 2, **kw):
        super().__init__(T.DecimalType(precision, scale), **kw)

    def generate_values(self, rng, n):
        p = self.dtype.precision
        hi = 10 ** p - 1
        unscaled = rng.integers(-hi, hi, n, dtype=np.int64, endpoint=True)
        s = self.dtype.scale
        return [decimal.Decimal(int(u)).scaleb(-s) for u in unscaled]

    def special_values(self):
        p, s = self.dtype.precision, self.dtype.scale
        hi = decimal.Decimal(10 ** p - 1).scaleb(-s)
        return [hi, -hi, decimal.Decimal(0)]


class DateGen(DataGen):
    EPOCH = datetime.date(1970, 1, 1)

    def __init__(self, start_days=-36500, end_days=36500, **kw):
        super().__init__(T.DateT, **kw)
        self.start_days, self.end_days = start_days, end_days

    def generate_values(self, rng, n):
        d = rng.integers(self.start_days, self.end_days, n)
        return [self.EPOCH + datetime.timedelta(days=int(x)) for x in d]

    def special_values(self):
        return [self.EPOCH, datetime.date(1582, 10, 15), datetime.date(9999, 12, 31)]


class TimestampGen(DataGen):
    def __init__(self, **kw):
        super().__init__(T.TimestampT, **kw)

    def generate_values(self, rng, n):
        us = rng.integers(-2_000_000_000_000_000, 4_000_000_000_000_000, n)
        ep = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
        return [ep + datetime.timedelta(microseconds=int(x)) for x in us]

    def special_values(self):
        ep = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
        return [ep]


class SkewedLongGen(_IntGen):
    """Long keys with a deliberately hot head: ``hot_mass`` of the rows
    land on one of ``hot_keys`` values, the rest spread over
    ``distinct`` — the shape that makes one hash partition dominate an
    exchange (the skew the stats plane and AQE splitting must see)."""
    BITS = 64

    def __init__(self, hot_keys: int = 1, hot_mass: float = 0.9,
                 distinct: int = 10_000, **kw):
        super().__init__(T.LongT, **kw)
        self.hot_keys = max(int(hot_keys), 1)
        self.hot_mass = float(hot_mass)
        self.distinct = max(int(distinct), self.hot_keys + 1)

    def generate_values(self, rng, n):
        hot = rng.random(n) < self.hot_mass
        vals = np.where(
            hot,
            rng.integers(0, self.hot_keys, n, dtype=np.int64),
            rng.integers(0, self.distinct, n, dtype=np.int64))
        return vals.tolist()

    def special_values(self):
        # min/max sentinels would dilute the engineered hot head
        return []


def skewed_null_table(n: int, seed: int = 0, hot_mass: float = 0.9,
                      null_ratio: float = 0.4) -> "pa.Table":
    """The canonical nasty table for skew + null-ratio tests: a
    non-null hot-headed long key ``k`` (hash-partitions into one fat
    partition), a null-heavy double ``v``, and a null-heavy string
    ``s``."""
    return gen_table(
        [SkewedLongGen(hot_mass=hot_mass, nullable=False),
         DoubleGen(no_nans=True, null_ratio=null_ratio),
         StringGen(min_len=1, max_len=8, null_ratio=null_ratio)],
        n, seed=seed, names=["k", "v", "s"])


# canonical suites used across tests (mirrors data_gen.py's *_gens lists)
numeric_gens: List[DataGen] = [
    ByteGen(), ShortGen(), IntegerGen(), LongGen(), FloatGen(), DoubleGen(),
]
integral_gens: List[DataGen] = [ByteGen(), ShortGen(), IntegerGen(), LongGen()]
basic_gens: List[DataGen] = numeric_gens + [
    BooleanGen(), StringGen(), DateGen(), TimestampGen(), DecimalGen(10, 2),
]


def gen_table(gens: Sequence[DataGen], n: int, seed: int = 0,
              names: Optional[Sequence[str]] = None) -> pa.Table:
    """Generate a pyarrow table, one column per generator."""
    rng = np.random.default_rng(seed)
    names = list(names) if names else [f"c{i}" for i in range(len(gens))]
    arrays = [g.generate(rng, n) for g in gens]
    return pa.table(arrays, names=names)
