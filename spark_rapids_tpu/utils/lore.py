"""LORE — local replay of a single operator from dumped input batches.

[REF: sql-plugin/../lore/ :: GpuLore, GpuLoreDumpExec, GpuLoreReplayExec]
— the reference's best debugging tool (SURVEY §5.4 "build this early"):
tag a plan node, dump its input batches + meta while the query runs, then
re-run JUST that operator offline from the dump.

Usage:
    conf: spark.rapids.sql.lore.tag=TpuSortMergeJoinExec
          spark.rapids.sql.lore.dumpPath=/tmp/dump
    ... run the failing query ...
    from spark_rapids_tpu.utils import lore
    table = lore.replay("/tmp/dump/TpuSortMergeJoinExec-0")

Dump layout: one directory per tagged node instance —
    meta.json                   node string, child schemas/partitions
    node.pkl                    the exec skeleton (children stripped)
    child<i>-part<p>-<j>.parquet  input batches in arrival order
"""

from __future__ import annotations

import glob
import json
import os
import pickle
import re
from typing import Iterator, List, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.exec.base import CpuExec, ExecNode, TpuExec


class LoreTapExec(TpuExec):
    """Pass-through child wrapper that dumps every batch it forwards."""

    def __init__(self, child: TpuExec, dump_dir: str, child_idx: int):
        super().__init__(child.schema, child)
        self.dump_dir = dump_dir
        self.child_idx = child_idx

    def node_string(self):
        return f"LoreTap [child={self.child_idx} → {self.dump_dir}]"

    def execute(self, partition: int) -> Iterator:
        from spark_rapids_tpu.columnar.column import device_to_host
        j = 0
        for b in self.children[0].execute(partition):
            tbl = device_to_host(b)
            path = os.path.join(
                self.dump_dir,
                f"child{self.child_idx}-part{partition}-{j}.parquet")
            pq.write_table(tbl, path)
            j += 1
            yield b


def _strip_for_pickle(node: ExecNode):
    """Copy the node without children/metrics/caches; None if the node
    holds unpicklable state (replay then returns just the inputs)."""
    import copy
    clone = copy.copy(node)
    clone._children = ()
    clone.metrics = {}
    for attr in ("_mat_lock", "_lock", "_materialized", "_result",
                 "_cached", "_shuffle_id", "_map_parts"):
        if hasattr(clone, attr):
            try:
                setattr(clone, attr, None)
            except AttributeError:
                pass
    try:
        pickle.dumps(clone)
        return clone
    except Exception:
        return None


def install_lore_taps(plan: ExecNode, tag: str, base_path: str
                      ) -> ExecNode:
    """Wrap every exec named ``tag``'s children with dump taps."""
    counter = [0]

    def walk(node: ExecNode) -> ExecNode:
        node._children = tuple(walk(c) for c in node.children)
        if type(node).__name__ == tag:
            d = os.path.join(base_path, f"{tag}-{counter[0]}")
            counter[0] += 1
            os.makedirs(d, exist_ok=True)
            meta = {
                "node": node.node_string(),
                "cls": type(node).__name__,
                "children": [
                    {"schema": _schema_json(c.schema),
                     "num_partitions": c.num_partitions()}
                    for c in node.children],
            }
            with open(os.path.join(d, "meta.json"), "w") as f:
                json.dump(meta, f, indent=2)
            skel = _strip_for_pickle(node)
            if skel is not None:
                with open(os.path.join(d, "node.pkl"), "wb") as f:
                    pickle.dump(skel, f)
            node._children = tuple(
                LoreTapExec(c, d, i) if isinstance(c, TpuExec) else c
                for i, c in enumerate(node.children))
        return node

    return walk(plan)


def _schema_json(schema: T.StructType) -> list:
    return [{"name": f.name, "type": f.dtype.simple_name} for f in
            schema.fields]


class LoreReplayScan(TpuExec):
    """Feeds dumped parquet batches back as device batches."""

    def __init__(self, dump_dir: str, child_idx: int,
                 schema: T.StructType, num_partitions: int):
        super().__init__(schema)
        self.dump_dir = dump_dir
        self.child_idx = child_idx
        self._nparts = num_partitions

    def num_partitions(self) -> int:
        return self._nparts

    def execute(self, partition: int) -> Iterator:
        from spark_rapids_tpu.columnar.column import host_to_device
        pat = os.path.join(
            self.dump_dir,
            f"child{self.child_idx}-part{partition}-*.parquet")

        def order(p):
            return int(re.search(r"-(\d+)\.parquet$", p).group(1))

        for path in sorted(glob.glob(pat), key=order):
            tbl = pq.read_table(path)
            b = host_to_device(tbl)
            yield type(b)(self.schema, b.columns, b.sel, b.compacted)


def replay(dump_dir: str) -> pa.Table:
    """Re-run the dumped operator over its dumped inputs → result table.

    Falls back to returning the concatenated child-0 inputs when the
    exec skeleton was not picklable (meta.json says what it was)."""
    from spark_rapids_tpu.columnar import host as H
    with open(os.path.join(dump_dir, "meta.json")) as f:
        meta = json.load(f)
    scans = []
    for i, child in enumerate(meta["children"]):
        fields = tuple(
            T.StructField(c["name"], _type_from_simple(c["type"]))
            for c in child["schema"])
        scans.append(LoreReplayScan(dump_dir, i, T.StructType(fields),
                                    child["num_partitions"]))
    pkl = os.path.join(dump_dir, "node.pkl")
    if not os.path.exists(pkl):
        tables = [H.to_arrow_table(b) for p in
                  range(scans[0].num_partitions())
                  for b in scans[0].execute(p)]
        return pa.concat_tables(tables)
    with open(pkl, "rb") as f:
        node = pickle.load(f)
    node._children = tuple(scans)
    node.metrics = {}
    from spark_rapids_tpu.columnar.column import device_to_host
    tables = []
    for p in range(node.num_partitions()):
        for b in node.execute(p):
            tables.append(device_to_host(b))
    return pa.concat_tables(tables)


_SIMPLE_TYPES = None


def _type_from_simple(name: str) -> T.DataType:
    global _SIMPLE_TYPES
    if _SIMPLE_TYPES is None:
        _SIMPLE_TYPES = {
            t.simple_name: t for t in (
                T.BooleanT, T.ByteT, T.ShortT, T.IntegerT, T.LongT,
                T.FloatT, T.DoubleT, T.StringT, T.BinaryT, T.DateT,
                T.TimestampT, T.NullT)}
    if name in _SIMPLE_TYPES:
        return _SIMPLE_TYPES[name]
    m = re.match(r"decimal\((\d+),(\d+)\)", name)
    if m:
        return T.DecimalType(int(m.group(1)), int(m.group(2)))
    raise ValueError(f"cannot parse type {name!r} from LORE meta")
