"""Documentation generators — docs never drift from the registries.

[REF: RapidsConf.scala :: doc-gen main (configs.md);
 TypeChecks.scala :: supported_ops.md generation]

Run:  python -m spark_rapids_tpu.utils.docs_gen [out_dir]
"""

from __future__ import annotations

import inspect
import os
import re
import sys


def collect_metric_names(pkg_dir: str = None) -> set:
    """Every metric name created anywhere in the package source.

    Creation sites are all string-literal ``.metric(...)`` /
    ``.timer(...)`` / ``Metric(...)`` calls plus the defaults table in
    exec/base.py, so a source scan is exact — the same
    registry-is-the-doc coupling the config/ops tables get from their
    live registries."""
    if pkg_dir is None:
        pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
    pat = re.compile(
        r'(?:\.metric\(\s*|\.timer\(\s*(?:name\s*=\s*)?|\bMetric\(\s*)'
        r'"([A-Za-z]\w*)"')
    names = {"opTime"}  # .timer() default
    for root, _dirs, files in os.walk(pkg_dir):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            with open(os.path.join(root, fname)) as f:
                names.update(pat.findall(f.read()))
    from spark_rapids_tpu.exec.base import _DEFAULT_METRIC_LEVEL
    names.update(_DEFAULT_METRIC_LEVEL)
    return names


def _documented_names(doc_path: str = None) -> set:
    """Backticked names in the doc's TABLE ROWS only.  Scanning the
    whole file over-matched: any backticked word in prose ("see
    `collect`") silently satisfied the drift check for a metric of the
    same name that no table ever documented."""
    if doc_path is None:
        doc_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "docs", "observability.md")
    names = set()
    with open(doc_path) as f:
        for line in f:
            if line.lstrip().startswith("|"):
                names.update(re.findall(r"`(\w+)`", line))
    return names


def check_metrics_documented(doc_path: str = None) -> list:
    """Metric names created in the package but missing from the
    docs/observability.md table — run in tier-1 tests so metric drift
    fails fast.  Returns the sorted list of undocumented names."""
    return sorted(collect_metric_names() - _documented_names(doc_path))


def collect_telemetry_names() -> set:
    """Every process-telemetry registry metric.  Registration is
    import-time, so the live registry after importing all producers IS
    the exact name set (no source scan needed)."""
    from spark_rapids_tpu.runtime import telemetry
    telemetry.ensure_producers()
    return set(telemetry.REGISTRY.names())


def check_telemetry_documented(doc_path: str = None) -> list:
    """Registry metric names missing from docs/observability.md — the
    tier-1 drift check's process-telemetry arm."""
    return sorted(collect_telemetry_names() - _documented_names(doc_path))


def collect_stats_fields() -> set:
    """Every stats-plane profile field.  The catalog dict in
    runtime/stats.py IS the registry — the record builder and this
    check both read it, so a field cannot ship undeclared."""
    from spark_rapids_tpu.runtime.stats import STATS_FIELDS
    return set(STATS_FIELDS)


def check_stats_documented(doc_path: str = None) -> list:
    """Stats-plane profile fields missing from docs/observability.md —
    the tier-1 drift check's stats-plane arm."""
    return sorted(collect_stats_fields() - _documented_names(doc_path))


def collect_attribution_buckets() -> set:
    """Every attribution-ledger bucket.  The ``BUCKETS`` catalog dict
    in runtime/attribution.py IS the registry — the ledger fold, the
    bucket-accounting lint rule, and this check all read it."""
    from spark_rapids_tpu.runtime.attribution import BUCKETS
    return set(BUCKETS)


def check_attribution_documented(doc_path: str = None) -> list:
    """Attribution buckets missing from docs/observability.md — the
    tier-1 drift check's attribution-plane arm."""
    return sorted(collect_attribution_buckets()
                  - _documented_names(doc_path))


def check_blocking_waits_cancellable(pkg_dir: str = None) -> list:
    """Blocking waits in runtime/ and parallel/ that the cancellation
    layer cannot interrupt — enforced in tier-1 so no new unbounded
    wait can sneak in.

    Flags two shapes: a bare ``<cv>.wait()`` (no timeout — a cancel can
    never wake it unless the CV is registered with the token, and even
    then an unbounded wait defeats the poll-interval guarantee) and a
    plain ``time.sleep(...)`` (should be ``cancel.sleep`` / a
    token-bounded wait).  A deliberate exemption carries a
    ``# cancel-exempt: <why>`` (or ``# lint: exempt(blocking-wait):
    <why>``) annotation on the same or the preceding line.  Returns
    ``["path:lineno: snippet", ...]``.

    Thin wrapper over the AST ``blocking-wait`` lint rule
    (utils/lint/blocking_wait.py) — the former regex body counted
    matches inside strings/comments and missed ``wait(timeout=None)``;
    the AST rule is exact and this gate can no longer disagree with
    ``python -m spark_rapids_tpu.utils.lint``."""
    from spark_rapids_tpu.utils.lint import iter_modules, run_lint
    from spark_rapids_tpu.utils.lint.blocking_wait import BlockingWaitRule
    if pkg_dir is None:
        pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
    mods = iter_modules(pkg_dir)
    by_rel = {m.rel: m for m in mods}
    bad = []
    for f in run_lint(pkg_dir, rules=[BlockingWaitRule()], modules=mods):
        if f.rule != "blocking-wait":
            continue
        m = by_rel[f.path]
        rel = os.path.relpath(m.path, pkg_dir)
        bad.append(f"{rel}:{f.line}: {m.snippet(f.line)}")
    return bad


def generate_supported_ops_md() -> str:
    """Exec + expression + aggregate support tables from the live
    registries (same coupling the reference keeps: the rule table IS the
    doc source)."""
    from spark_rapids_tpu.ops import aggregates as A
    from spark_rapids_tpu.ops import datetime_ops as D
    from spark_rapids_tpu.ops import expressions as E
    from spark_rapids_tpu.ops import hashing as HH
    from spark_rapids_tpu.ops import strings as S
    from spark_rapids_tpu.plan import overrides as O

    O._register_lazy_rules()
    lines = [
        "# Supported operators",
        "",
        "Generated from the rule/expression registries "
        "(`python -m spark_rapids_tpu.utils.docs_gen`) — do not edit.",
        "",
        "Every exec and expression below also has a per-op kill switch: "
        "`spark.rapids.sql.exec.<Name>=false` / "
        "`spark.rapids.sql.expression.<Name>=false`.",
        "",
        "## Execs",
        "",
        "| Exec | Description |",
        "|---|---|",
    ]
    seen = set()
    for rule in O.EXEC_RULES.values():
        if rule.name in seen:
            continue
        seen.add(rule.name)
        lines.append(f"| {rule.name} | {rule.desc} |")
    # short column headers for the per-type support matrix
    sig_cols = [("boolean", "BOOL"), ("byte", "B"), ("short", "SH"),
                ("int", "I"), ("long", "L"), ("float", "F"),
                ("double", "D"), ("decimal", "DEC"), ("string", "STR"),
                ("binary", "BIN"), ("date", "DATE"), ("timestamp", "TS"),
                ("null", "NULL"), ("array", "ARR"), ("map", "MAP"),
                ("struct", "STCT")]
    lines += [
        "",
        "## Expressions",
        "",
        "Per-type INPUT support (the declared TypeSig, checked during "
        "plan tagging): S = the device lowering accepts that input "
        "type, blank = CPU fallback.  `→` lists the result types when "
        "narrower than the inputs.",
        "",
        "| Expression | " + " | ".join(h for _, h in sig_cols)
        + " | Notes |",
        "|---|" + "---|" * len(sig_cols) + "---|",
    ]
    mods = (E, S, D, HH)
    rows = []
    for mod in mods:
        for name, cls in sorted(vars(mod).items()):
            if (not inspect.isclass(cls)
                    or not issubclass(cls, E.Expression)
                    or cls is E.Expression or name.startswith("_")):
                continue
            if cls.__module__ != mod.__name__:
                continue
            if (not hasattr(cls, "eval_tpu")
                    or cls.eval_tpu is E.Expression.eval_tpu):
                continue
            notes = []
            if getattr(cls, "incompat", None):
                notes.append(
                    f"INCOMPAT ({cls.incompat}); needs "
                    "`spark.rapids.sql.incompatibleOps.enabled=true`")
            if getattr(cls, "ansi_sensitive", False):
                notes.append("falls back under `spark.sql.ansi.enabled`")
            in_sig = (cls.input_sig if cls.input_sig is not None
                      else cls.type_sig)
            if cls.type_sig != in_sig:
                notes.insert(0, "→ " + ", ".join(sorted(
                    cls.type_sig)))
            cells = " | ".join("S" if tag in in_sig else ""
                               for tag, _ in sig_cols)
            rows.append((name, cells, "; ".join(notes)))
    for name, cells, notes in sorted(set(rows)):
        lines.append(f"| {name} | {cells} | {notes} |")
    lines += [
        "",
        "### Host-evaluated expressions",
        "",
        "Implemented with Spark semantics but not yet device-lowered — "
        "their subtree reports NOT_ON_TPU and runs the CPU path:",
        "",
    ]
    from spark_rapids_tpu.ops import json_ops as J
    for name, cls in sorted(vars(J).items()):
        if (inspect.isclass(cls) and issubclass(cls, E.Expression)
                and cls is not E.Expression
                and cls.__module__ == J.__name__
                and not name.startswith("_")):
            lines.append(f"- `{name}` (device JSON scanner planned)")
    lines += [
        "",
        "## Aggregate functions",
        "",
        "| Function | Notes |",
        "|---|---|",
    ]
    agg_notes = {
        "count_distinct": "planner-rewritten to a two-level aggregate",
        "collect_list": "grouped only; numeric elements; whole-partition "
                        "kernel (no partial/merge)",
        "var_samp": "sum-of-squares buffers (float tolerance vs Welford)",
        "var_pop": "sum-of-squares buffers",
        "stddev_samp": "sum-of-squares buffers",
        "stddev_pop": "sum-of-squares buffers",
        "first": "input order within this engine's batches",
        "sum": "falls back under ANSI mode (wrap-on-overflow kernels)",
    }
    for name, cls in sorted(vars(A).items()):
        if (not inspect.isclass(cls)
                or not issubclass(cls, A.AggregateFunction)
                or cls is A.AggregateFunction or name.startswith("_")):
            continue
        fn_name = cls.name
        lines.append(f"| {fn_name} | {agg_notes.get(fn_name, '')} |")
    lines.append("")
    return "\n".join(lines)


def main(out_dir: str = "docs"):
    from spark_rapids_tpu.conf import generate_configs_md
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "configs.md"), "w") as f:
        f.write(generate_configs_md())
    with open(os.path.join(out_dir, "supported_ops.md"), "w") as f:
        f.write(generate_supported_ops_md())
    print(f"wrote {out_dir}/configs.md and {out_dir}/supported_ops.md")
    obs = os.path.join(out_dir, "observability.md")
    if os.path.exists(obs):
        missing = check_metrics_documented(obs)
        if missing:
            print(f"UNDOCUMENTED metrics (add to {obs}): {missing}")
        missing_tm = check_telemetry_documented(obs)
        if missing_tm:
            print(f"UNDOCUMENTED telemetry metrics (add to {obs}): "
                  f"{missing_tm}")
        missing_st = check_stats_documented(obs)
        if missing_st:
            print(f"UNDOCUMENTED stats fields (add to {obs}): "
                  f"{missing_st}")
        missing_att = check_attribution_documented(obs)
        if missing_att:
            print(f"UNDOCUMENTED attribution buckets (add to {obs}): "
                  f"{missing_att}")
    from spark_rapids_tpu.utils.lint import run_lint
    findings = run_lint()
    for f in findings:
        print(f)
    print(f"lint: {len(findings)} finding(s)" if findings
          else "lint: clean")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "docs")
