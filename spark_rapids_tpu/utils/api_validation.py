"""API validation — guards drift between the engine's layers.

[REF: api_validation/ :: ApiValidation; SURVEY §2.1 #37] — the reference
cross-checks Gpu exec constructor signatures against Spark's across
shims.  This engine has no shims, so the drift surfaces that actually
exist here are validated instead:

* every logical plan node has a physical-planner case;
* every registered exec rule's CPU class is constructed by the planner
  (no orphaned rules) and converts under a smoke plan;
* every pyspark-surface method the docs promise exists on
  DataFrame/GroupedData/DataFrameReader/DataFrameWriter/Column/functions;
* every registered conf key is consumed somewhere in the package
  (the generated docs must not lie — r2 verdict weak #6).

Run via ``python -m spark_rapids_tpu.utils.api_validation`` or the test
suite (tests/test_api_validation.py).
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import List


# The pyspark API surface this engine documents as supported — one name
# per row of docs/supported_ops.md's API section.  Additions to the
# engine should extend this list; removals break the check loudly.
DATAFRAME_API = [
    "select", "filter", "where", "withColumn", "withColumnRenamed",
    "drop", "limit", "union", "unionAll", "distinct", "sample",
    "repartition", "groupBy", "groupby", "rollup", "cube", "agg",
    "orderBy", "sort", "join", "crossJoin", "mapInPandas", "collect",
    "count", "toArrow", "toPandas", "show", "explain", "schema",
    "columns", "write",
]
GROUPED_API = ["agg", "count", "sum", "min", "max", "avg", "mean",
               "applyInPandas"]
READER_API = ["format", "load", "parquet", "orc", "csv", "json", "text",
              "avro", "delta", "iceberg", "schema", "option", "options"]
WRITER_API = ["mode", "option", "partitionBy", "parquet", "orc", "csv",
              "json"]
COLUMN_API = ["alias", "cast", "asc", "desc", "isNull", "isNotNull",
              "substr", "startswith", "endswith", "contains", "like",
              "rlike", "over"]
FUNCTIONS_API = [
    "col", "lit", "sum", "min", "max", "avg", "count", "countDistinct",
    "approx_count_distinct",
    "first", "sqrt", "exp", "log", "abs", "floor", "ceil", "round",
    "pow", "coalesce", "when", "concat", "substring", "upper", "lower",
    "length", "trim", "ltrim", "rtrim", "replace", "instr", "locate",
    "split", "reverse", "lpad", "rpad", "rlike", "get_json_object",
    "regexp_extract",
    "regexp_replace", "hash", "xxhash64", "year", "month", "dayofmonth",
    "date_add", "date_sub", "datediff", "from_utc_timestamp",
    "to_utc_timestamp", "var_samp", "var_pop", "stddev_samp",
    "stddev_pop", "collect_list", "row_number", "rank", "dense_rank",
    "lag", "lead", "explode", "explode_outer", "posexplode",
    "posexplode_outer", "input_file_name", "udf", "pandas_udf",
    "device_udf",
]


def validate() -> List[str]:
    """Returns a list of human-readable violations (empty = clean)."""
    problems: List[str] = []
    problems += _check_planner_covers_logical()
    problems += _check_api_surface()
    problems += _check_conf_consumers()
    return problems


def _check_planner_covers_logical() -> List[str]:
    import inspect as _i

    from spark_rapids_tpu.plan import logical as L
    from spark_rapids_tpu.plan import planner
    src = _i.getsource(planner)
    out = []
    for name, cls in vars(L).items():
        # LogicalPlan subclassing is the discriminator — helper
        # dataclasses (SortOrder, WindowFunctionSpec) don't subclass it
        if (_i.isclass(cls) and issubclass(cls, L.LogicalPlan)
                and cls is not L.LogicalPlan
                and dataclasses.is_dataclass(cls)):
            if f"L.{name}" not in src:
                out.append(f"planner has no case for logical node "
                           f"{name}")
    return out


def _check_api_surface() -> List[str]:
    from spark_rapids_tpu.io.readers import (
        DataFrameReader, DataFrameWriter)
    from spark_rapids_tpu.sql import functions as F
    from spark_rapids_tpu.sql.column import Column
    from spark_rapids_tpu.sql.dataframe import DataFrame, GroupedData
    out = []
    for obj, names, label in (
            (DataFrame, DATAFRAME_API, "DataFrame"),
            (GroupedData, GROUPED_API, "GroupedData"),
            (DataFrameReader, READER_API, "DataFrameReader"),
            (DataFrameWriter, WRITER_API, "DataFrameWriter"),
            (Column, COLUMN_API, "Column"),
            (F, FUNCTIONS_API, "functions")):
        for n in names:
            if not hasattr(obj, n):
                out.append(f"{label}.{n} is missing")
    return out


def _check_conf_consumers() -> List[str]:
    """Every key in the typed registry must have ≥1 consumer outside
    conf.py — generated docs must describe real behavior."""
    import os

    from spark_rapids_tpu import conf as C
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(
        C.__file__)))
    sources = []
    for root, dirs, files in os.walk(os.path.join(pkg_dir,
                                                  "spark_rapids_tpu")):
        for fn in files:
            if fn.endswith(".py") and fn != "conf.py":
                with open(os.path.join(root, fn)) as f:
                    sources.append(f.read())
    blob = "\n".join(sources)
    # keys may be consumed through RapidsConf property accessors —
    # associate `def prop(self): return self.get(CONST)` pairs
    import re
    with open(C.__file__) as f:
        conf_src = f.read()
    prop_of = dict(re.findall(
        r"def (\w+)\(self\)[^\n]*:\n(?:[^\n]*\n)?\s*return self\.get\("
        r"(\w+)\)", conf_src))
    prop_by_const = {v: k for k, v in prop_of.items()}
    out = []
    for name, entry in vars(C).items():
        if not name.isupper() or not hasattr(entry, "key"):
            continue
        prop = prop_by_const.get(name)
        consumed = (name in blob or entry.key in blob
                    or (prop is not None and f".{prop}" in blob))
        if not consumed:
            out.append(f"conf key {entry.key} ({name}) has no consumer")
    return out


def main():
    problems = validate()
    for p in problems:
        print("VIOLATION:", p)
    print(f"{len(problems)} violations")
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
