"""Name resolution and implicit type coercion (the analyzer).

Converts unresolved ``sql.column.UExpr`` trees into bound, typed
``ops.expressions`` nodes against a schema, applying Spark's implicit-cast
rules: widest numeric type for binary ops, both sides to double for ``/``,
null literals adopt the other side's type, comparison operands unified.
"""

from __future__ import annotations

import datetime
import decimal
from typing import List, Optional, Tuple

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.ops import datetime_ops as D
from spark_rapids_tpu.ops import expressions as E
from spark_rapids_tpu.ops import strings as S
from spark_rapids_tpu.ops import aggregates as A
from spark_rapids_tpu.sql.column import UExpr


class AnalysisException(Exception):
    pass


def infer_literal_type(v) -> T.DataType:
    if v is None:
        return T.NullT
    if isinstance(v, bool):
        return T.BooleanT
    if isinstance(v, int):
        return T.IntegerT if -(1 << 31) <= v < (1 << 31) else T.LongT
    if isinstance(v, float):
        return T.DoubleT
    if isinstance(v, str):
        return T.StringT
    if isinstance(v, decimal.Decimal):
        sign, digits, exp = v.as_tuple()
        scale = -exp if exp < 0 else 0
        return T.DecimalType(max(len(digits), scale), scale)
    if isinstance(v, datetime.datetime):
        return T.TimestampT
    if isinstance(v, datetime.date):
        return T.DateT
    if isinstance(v, bytes):
        return T.BinaryT
    raise AnalysisException(f"cannot infer literal type for {v!r}")


def literal(v) -> E.Literal:
    dt = infer_literal_type(v)
    if isinstance(dt, T.TimestampType):
        epoch = datetime.datetime(1970, 1, 1,
                                  tzinfo=datetime.timezone.utc)
        vv = v if v.tzinfo else v.replace(tzinfo=datetime.timezone.utc)
        v = int((vv - epoch).total_seconds() * 1_000_000)
    elif isinstance(dt, T.DateType):
        v = (v - datetime.date(1970, 1, 1)).days
    return E.Literal(v, dt)


_INT_ORDER = [T.ByteType, T.ShortType, T.IntegerType, T.LongType]


def common_type(a: T.DataType, b: T.DataType) -> T.DataType:
    if a == b:
        return a
    if isinstance(a, T.NullType):
        return b
    if isinstance(b, T.NullType):
        return a
    if isinstance(a, T.DoubleType) or isinstance(b, T.DoubleType):
        if T.is_numeric(a) and T.is_numeric(b):
            return T.DoubleT
    if isinstance(a, T.FloatType) or isinstance(b, T.FloatType):
        if T.is_numeric(a) and T.is_numeric(b):
            return T.FloatT
    if T.is_integral(a) and T.is_integral(b):
        ia, ib = _INT_ORDER.index(type(a)), _INT_ORDER.index(type(b))
        return a if ia >= ib else b
    if isinstance(a, T.DecimalType) and T.is_integral(b):
        return a
    if T.is_integral(a) and isinstance(b, T.DecimalType):
        return b
    if isinstance(a, T.DecimalType) and isinstance(b, T.DecimalType):
        # widest: keep every integer digit and every fraction digit
        scale = max(a.scale, b.scale)
        intd = max(a.precision - a.scale, b.precision - b.scale)
        return T.DecimalType(min(intd + scale, 38), scale)
    if isinstance(a, T.DateType) and isinstance(b, T.TimestampType):
        return b
    if isinstance(a, T.TimestampType) and isinstance(b, T.DateType):
        return a
    raise AnalysisException(f"incompatible types: {a} vs {b}")


def cast_to(e: E.Expression, dt: T.DataType) -> E.Expression:
    if e.dtype == dt:
        return e
    if isinstance(e, E.Literal):
        # constant-fold literal widenings: keeps predicates in the
        # (ref cmp literal) shape scan pushdown recognizes and shrinks
        # kernel-cache keys
        if e.value is None:
            return E.Literal(None, dt)
        v = e.value
        if not isinstance(v, bool):
            if T.is_integral(dt) and isinstance(v, int):
                return E.Literal(v, dt)
            if isinstance(dt, (T.DoubleType, T.FloatType)) and isinstance(
                    v, (int, float)):
                return E.Literal(float(v), dt)
    return E.Cast(e, dt)


def _coerce_pair(l: E.Expression, r: E.Expression):
    ct = common_type(l.dtype, r.dtype)
    return cast_to(l, ct), cast_to(r, ct)


_BIN_ARITH = {"add": E.Add, "sub": E.Subtract, "mul": E.Multiply,
              "mod": E.Remainder}
_BIN_CMP = {"eq": E.EqualTo, "lt": E.LessThan, "le": E.LessThanOrEqual,
            "gt": E.GreaterThan, "ge": E.GreaterThanOrEqual,
            "eqns": E.EqualNullSafe}
_UNARY_MATH = {"sqrt": E.Sqrt, "exp": E.Exp, "log": E.Log}
_DATE_FIELD = {"year": D.Year, "month": D.Month, "dayofmonth": D.DayOfMonth}


def resolve(u: UExpr, schema: T.StructType) -> E.Expression:
    op = u.op
    if op == "attr":
        name = u.payload
        try:
            idx = schema.field_index(name)
        except KeyError:
            raise AnalysisException(
                f"cannot resolve column '{name}' among "
                f"{schema.field_names()}")
        f = schema.fields[idx]
        return E.BoundReference(idx, f.dtype, f.nullable)
    if op == "lit":
        return literal(u.payload)
    if op == "alias":
        return E.Alias(resolve(u.children[0], schema), u.payload)
    if op in _BIN_ARITH:
        l = resolve(u.children[0], schema)
        r = resolve(u.children[1], schema)
        if isinstance(l.dtype, T.StringType) or isinstance(r.dtype, T.StringType):
            raise AnalysisException(f"'{op}' needs numeric operands")
        if (isinstance(l.dtype, T.DecimalType)
                and isinstance(r.dtype, T.DecimalType)):
            # Spark decimal arithmetic result types (non-ANSI; beyond
            # precision 38 is rejected rather than scale-adjusted)
            p1, s1 = l.dtype.precision, l.dtype.scale
            p2, s2 = r.dtype.precision, r.dtype.scale
            if op == "mul":
                rt = T.DecimalType(min(p1 + p2 + 1, 38), s1 + s2)
                if rt.scale > rt.precision:
                    raise AnalysisException(
                        f"decimal multiply result scale {rt.scale} "
                        "exceeds precision 38")
                return _BIN_ARITH[op](l, r, rt)
            if op in ("add", "sub"):
                ct = common_type(l.dtype, r.dtype)
                rt = T.DecimalType(min(ct.precision + 1, 38), ct.scale)
                return _BIN_ARITH[op](cast_to(l, ct), cast_to(r, ct),
                                      rt)
            raise AnalysisException(
                f"decimal '{op}' not supported")
        l, r = _coerce_pair(l, r)
        return _BIN_ARITH[op](l, r)
    if op == "div":
        l = resolve(u.children[0], schema)
        r = resolve(u.children[1], schema)
        return E.Divide(cast_to(l, T.DoubleT), cast_to(r, T.DoubleT))
    if op in _BIN_CMP:
        l = resolve(u.children[0], schema)
        r = resolve(u.children[1], schema)
        l, r = _coerce_pair(l, r)
        if isinstance(l.dtype, T.StringType):
            return S.string_comparison(op, l, r)
        return _BIN_CMP[op](l, r)
    if op in ("and", "or"):
        l = resolve(u.children[0], schema)
        r = resolve(u.children[1], schema)
        for side in (l, r):
            if not isinstance(side.dtype, (T.BooleanType, T.NullType)):
                raise AnalysisException(f"'{op}' needs boolean operands, "
                                        f"got {side.dtype}")
        cls = E.And if op == "and" else E.Or
        return cls(cast_to(l, T.BooleanT), cast_to(r, T.BooleanT))
    if op == "not":
        return E.Not(resolve(u.children[0], schema))
    if op == "neg":
        return E.UnaryMinus(resolve(u.children[0], schema))
    if op == "abs":
        return E.Abs(resolve(u.children[0], schema))
    if op == "isnull":
        return E.IsNull(resolve(u.children[0], schema))
    if op == "isnotnull":
        return E.IsNotNull(resolve(u.children[0], schema))
    if op == "isnan":
        return E.IsNaN(resolve(u.children[0], schema))
    if op == "coalesce":
        exprs = [resolve(c, schema) for c in u.children]
        ct = exprs[0].dtype
        for e in exprs[1:]:
            ct = common_type(ct, e.dtype)
        return E.Coalesce([cast_to(e, ct) for e in exprs])
    if op == "casewhen":
        kids = [resolve(c, schema) for c in u.children]
        has_else = len(kids) % 2 == 1
        pairs = [(kids[i], kids[i + 1]) for i in range(0, len(kids) - 1, 2)]
        else_v = kids[-1] if has_else else None
        ct = pairs[0][1].dtype
        for _, v in pairs[1:]:
            ct = common_type(ct, v.dtype)
        if else_v is not None:
            ct = common_type(ct, else_v.dtype)
            else_v = cast_to(else_v, ct)
        pairs = [(p, cast_to(v, ct)) for p, v in pairs]
        return E.CaseWhen(pairs, else_v)
    if op in _UNARY_MATH:
        c = cast_to(resolve(u.children[0], schema), T.DoubleT)
        return _UNARY_MATH[op](c)
    if op in ("floor", "ceil"):
        c = cast_to(resolve(u.children[0], schema), T.DoubleT)
        return (E.Floor if op == "floor" else E.Ceil)(c)
    if op == "round":
        return E.Round(resolve(u.children[0], schema), u.payload)
    if op == "pow":
        l = cast_to(resolve(u.children[0], schema), T.DoubleT)
        r = cast_to(resolve(u.children[1], schema), T.DoubleT)
        return E.Pow(l, r)
    if op == "cast":
        dt = u.payload if isinstance(u.payload, T.DataType) else _parse_type(u.payload)
        return E.Cast(resolve(u.children[0], schema), dt)
    if op in _DATE_FIELD:
        return _DATE_FIELD[op](resolve(u.children[0], schema))
    if op == "date_add":
        return D.DateAdd(resolve(u.children[0], schema),
                         resolve(u.children[1], schema))
    if op == "date_sub":
        return D.DateSub(resolve(u.children[0], schema),
                         resolve(u.children[1], schema))
    if op == "datediff":
        return D.DateDiff(resolve(u.children[0], schema),
                          resolve(u.children[1], schema))
    if op in ("from_utc_timestamp", "to_utc_timestamp"):
        from spark_rapids_tpu.ops.timezone import (
            TZ_CACHE, FromUTCTimestamp, ToUTCTimestamp)
        child = resolve(u.children[0], schema)
        if not isinstance(child.dtype, T.TimestampType):
            child = cast_to(child, T.TimestampT)
        tz = str(u.payload)
        # validate the zone AND build the device LUT eagerly — inside a
        # jit trace the constants would leak as tracers into the cache
        TZ_CACHE.device(tz)
        cls = (FromUTCTimestamp if op == "from_utc_timestamp"
               else ToUTCTimestamp)
        return cls(child, tz)
    if op in ("upper", "lower", "length"):
        return S.string_unary(op, resolve(u.children[0], schema))
    if op in ("trim", "ltrim", "rtrim"):
        side = {"trim": "both", "ltrim": "leading",
                "rtrim": "trailing"}[op]
        child = resolve(u.children[0], schema)
        if not isinstance(child.dtype, T.StringType):
            raise AnalysisException(f"{op} needs a string operand")
        return S.Trim(child, side)
    if op == "replace":
        search, repl = u.payload
        child = resolve(u.children[0], schema)
        if not isinstance(child.dtype, T.StringType):
            raise AnalysisException("replace needs a string operand")
        return S.StringReplace(child, search, repl)
    if op == "locate":
        substr = resolve(u.children[0], schema)
        child = resolve(u.children[1], schema)
        return S.StringLocate(substr, child, u.payload)
    if op == "like":
        child = resolve(u.children[0], schema)
        if not isinstance(child.dtype, T.StringType):
            raise AnalysisException("like needs a string operand")
        return S.Like(child, u.payload)
    if op == "substring":
        pos, ln = u.payload
        return S.Substring(resolve(u.children[0], schema), pos, ln)
    if op == "rlike":
        child = resolve(u.children[0], schema)
        if not isinstance(child.dtype, T.StringType):
            raise AnalysisException("rlike needs a string operand")
        pattern = u.payload
        S.check_regex_supported(pattern)
        simple = S.regex_as_simple(pattern)
        if simple:
            # simple patterns transpile to device predicates — the
            # RegexParser fast path [REF: CudfRegexTranspiler]
            kind, lit = simple

            def one(lit2):
                if kind == "eq":
                    return S.string_comparison(
                        "eq", child, E.Literal(lit2, T.StringT))
                return S.string_predicate(kind, child,
                                          E.Literal(lit2, T.StringT))

            if kind not in ("eq", "endswith"):
                return one(lit)
            # Java's '$' (Pattern.find, no UNIX_LINES) also matches
            # just before a FINAL line terminator: "abc\n" rlike
            # "abc$" is true — OR in each terminator variant (XLA
            # CSEs the repeated child subtree)
            out = one(lit)
            for term in ("\n", "\r\n", "\r", "\u0085",
                         "\u2028", "\u2029"):
                out = E.Or(out, one(lit + term))
            return out
        return S.RLike(child, pattern)
    if op == "get_json_object":
        from spark_rapids_tpu.ops.json_ops import GetJsonObject
        child = resolve(u.children[0], schema)
        if not isinstance(child.dtype, (T.StringType, T.BinaryType)):
            raise AnalysisException(
                "get_json_object needs a string operand, got "
                f"{child.dtype.simple_name}")
        return GetJsonObject(child, str(u.payload))
    if op == "regexp_extract":
        pattern, idx = u.payload
        S.check_regex_supported(pattern)
        return S.RegexpExtract(resolve(u.children[0], schema), pattern,
                               idx)
    if op == "regexp_replace":
        pattern, repl = u.payload
        S.check_regex_supported(pattern)
        return S.RegexpReplace(resolve(u.children[0], schema), pattern,
                               repl)
    if op == "split":
        pattern, limit = u.payload
        S.check_regex_supported(pattern)
        return S.Split(resolve(u.children[0], schema), pattern, limit)
    if op == "reverse":
        child = resolve(u.children[0], schema)
        if not isinstance(child.dtype, T.StringType):
            raise AnalysisException("reverse needs a string operand")
        return S.StringReverse(child)
    if op in ("lpad", "rpad"):
        ln, pad = u.payload
        child = resolve(u.children[0], schema)
        if not isinstance(child.dtype, T.StringType):
            raise AnalysisException(f"{op} needs a string operand")
        return S.StringPad(child, int(ln), str(pad), op == "lpad")
    if op in ("startswith", "endswith", "contains"):
        return S.string_predicate(op, resolve(u.children[0], schema),
                                  resolve(u.children[1], schema))
    if op == "concat":
        return S.Concat([resolve(c, schema) for c in u.children])
    if op == "hash":
        from spark_rapids_tpu.ops.hashing import Murmur3Hash
        return Murmur3Hash([resolve(c, schema) for c in u.children])
    if op == "xxhash64":
        from spark_rapids_tpu.ops.hashing import XxHash64
        return XxHash64([resolve(c, schema) for c in u.children])
    if op == "input_file_name":
        return E.InputFileName()
    if op == "device_udf":
        fn, dt, name = u.payload
        args = tuple(resolve(c, schema) for c in u.children)
        for a in args:
            if isinstance(a.dtype, (T.StringType, T.BinaryType)):
                raise AnalysisException(
                    "device_udf arguments must be numeric/boolean/"
                    f"datetime columns, got {a.dtype.simple_name} "
                    "(string byte-matrix layout is not a stable UDF "
                    "surface)")
        return E.DeviceUDF(fn, args, dt, name)
    if op == "pyudf":
        raise AnalysisException(
            "python UDFs are only supported as top-level select "
            "expressions (optionally aliased)")
    if op == "agg":
        raise AnalysisException(
            f"aggregate function '{u.payload}' is only allowed in agg()")
    if op == "sortorder":
        raise AnalysisException("sort order only allowed in orderBy()")
    raise AnalysisException(f"unknown expression op '{op}'")


_AGG_MAP = {"sum": A.Sum, "min": A.Min, "max": A.Max, "count": A.Count,
            "avg": A.Average, "first": A.First,
            "var_samp": A.VarianceSamp, "var_pop": A.VariancePop,
            "stddev_samp": A.StddevSamp, "stddev_pop": A.StddevPop,
            "count_distinct": A.CountDistinct,
            "collect_list": A.CollectList,
            "collect_set": A.CollectSet}


def resolve_aggregate(u: UExpr, schema: T.StructType
                      ) -> Tuple[A.AggregateFunction, Optional[str]]:
    """Resolve an agg expression (optionally aliased).  Returns (fn, name)."""
    alias = None
    if u.op == "alias":
        alias = u.payload
        u = u.children[0]
    if u.op != "agg":
        raise AnalysisException(
            f"agg() expects aggregate expressions, got {u}")
    kind = u.payload
    args = ()
    if isinstance(kind, tuple):
        kind, args = kind[0], kind[1:]
    child = resolve(u.children[0], schema)
    if kind == "count_star":
        return A.CountStar(child), alias or "count(1)"
    if kind in ("percentile", "approx_percentile"):
        if not T.is_numeric(child.dtype):
            raise AnalysisException(f"{kind} needs a numeric input")
        if isinstance(child.dtype, T.DecimalType):
            if kind == "approx_percentile":
                # result type = input type; the unscaled-int64 decimal
                # representation cannot round-trip through the kernel
                raise AnalysisException(
                    "approx_percentile over decimal input is not "
                    "supported (use percentile, which returns double)")
            child = cast_to(child, T.DoubleT)
        pct = float(args[0])
        if not 0.0 <= pct <= 1.0:
            raise AnalysisException(
                f"{kind} percentage must be in [0, 1], got {pct}")
        if kind == "percentile":
            fn = A.Percentile(child, pct)
        else:
            fn = A.ApproxPercentile(child, pct,
                                    int(args[1]) if len(args) > 1
                                    else 10000)
        return fn, alias or f"{kind}({u.children[0]}, {pct})"
    if kind == "avg":
        child = cast_to(child, T.DoubleT)
    if kind == "sum" and isinstance(child.dtype,
                                    (T.FloatType,)):
        child = cast_to(child, T.DoubleT)
    if kind in ("var_samp", "var_pop", "stddev_samp", "stddev_pop"):
        if not T.is_numeric(child.dtype):
            raise AnalysisException(f"{kind} needs a numeric input")
        child = cast_to(child, T.DoubleT)
    cls = _AGG_MAP.get(kind)
    if cls is None:
        raise AnalysisException(f"unsupported aggregate '{kind}'")
    fn = cls(child)
    if kind == "count_distinct":
        return fn, alias or f"count(DISTINCT {u.children[0]})"
    return fn, alias or f"{kind}({u.children[0]})"


def resolve_window(u: UExpr, schema: T.StructType):
    """Resolve a ``col.over(WindowSpec)`` expression.

    Returns (partition_by, order_by SortOrders, WindowFunctionSpec,
    default name).  [REF: GpuWindowExpression tagging]
    """
    from spark_rapids_tpu.plan import logical as L
    from spark_rapids_tpu.sql.window import Window, WindowSpec

    spec: WindowSpec = u.payload
    fu = u.children[0]
    pby = [resolve(p, schema) for p in spec.partition_by]
    orders = []
    for o in spec.order_by:
        asc, nf = True, True
        if o.op == "sortorder":
            d, n = o.payload
            asc, nf = d == "asc", n == "nulls_first"
            o = o.children[0]
        orders.append(L.SortOrder(resolve(o, schema), asc, nf))
    frame_lo = frame_hi = 0
    if spec.frame is None:
        frame = "range_current" if orders else "partition"
    else:
        kind, lo, hi = spec.frame
        unb_lo = lo == Window.unboundedPreceding
        unb_hi = hi == Window.unboundedFollowing
        if kind == "rows" and unb_lo and hi == 0:
            frame = "rows_current"
        elif unb_lo and unb_hi:
            frame = "partition"
        elif kind == "rows" and lo <= hi:
            # sliding frame, e.g. rowsBetween(-3, 0) — rolling kernels
            # [REF: cudf rolling / GpuWindowExpression bounded frames];
            # an unbounded end clamps to the partition edge in the
            # kernel, so it rides the same path
            frame = "rows_bounded"
            cap = 1 << 30  # past any batch size; int32-safe in kernels
            frame_lo = max(int(lo), -cap)
            frame_hi = min(int(hi), cap)
        elif kind == "range" and unb_lo and hi == 0:
            frame = "range_current"
        elif kind == "range" and lo <= hi:
            frame = "range_bounded"
            frame_lo = None if unb_lo else int(lo)
            frame_hi = None if unb_hi else int(hi)
            if not orders or len(orders) != 1:
                raise AnalysisException(
                    "RANGE frame with offsets requires exactly one "
                    "ORDER BY expression")
            okey = orders[0]
            if not (T.is_integral(okey.expr.dtype)
                    or isinstance(okey.expr.dtype, T.DateType)):
                raise AnalysisException(
                    "RANGE frame offsets need an integral or date "
                    f"ORDER BY key, got {okey.expr.dtype.simple_name}")
        else:
            raise AnalysisException(
                f"unsupported window frame {spec.frame} (supported: "
                "ROWS unboundedPreceding..currentRow, "
                "unbounded..unbounded, bounded rowsBetween(a, b), and "
                "rangeBetween over one integral/date ORDER BY key)")

    if fu.op == "winfn":
        kind = fu.payload[0]
        if not orders:
            raise AnalysisException(f"{kind}() requires an ORDER BY spec")
        if kind in ("row_number", "rank", "dense_rank"):
            wf = L.WindowFunctionSpec(kind, None, T.IntegerT, frame=frame)
            name = f"{kind}()"
        elif kind in ("percent_rank", "cume_dist"):
            wf = L.WindowFunctionSpec(kind, None, T.DoubleT, frame=frame)
            name = f"{kind}()"
        elif kind == "ntile":
            n = int(fu.payload[1])
            if n <= 0:
                raise AnalysisException("ntile() needs a positive bucket "
                                        "count")
            wf = L.WindowFunctionSpec(kind, None, T.IntegerT, offset=n,
                                      frame=frame)
            name = f"ntile({n})"
        else:  # lag / lead
            child = resolve(fu.children[0], schema)
            offset = int(fu.payload[1])
            ignore_nulls = bool(fu.payload[2]) if len(fu.payload) > 2 \
                else False
            # Spark's default name keeps the user's spelling, even when a
            # negative offset normalizes lag <-> lead below
            name = f"{kind}({fu.children[0]}, {fu.payload[1]})"
            if offset < 0:  # Spark: lag(-k) == lead(k) and vice versa
                kind = "lead" if kind == "lag" else "lag"
                offset = -offset
            wf = L.WindowFunctionSpec(kind, child, child.dtype,
                                      offset=offset, frame=frame,
                                      ignore_nulls=ignore_nulls)
    elif fu.op == "agg":
        kind = fu.payload
        if kind == "count_star":
            child = resolve(UExpr("lit", 1), schema)
            kind = "count"
        else:
            child = resolve(fu.children[0], schema)
        if kind not in ("sum", "min", "max", "count", "avg", "first"):
            raise AnalysisException(
                f"unsupported window aggregate '{kind}'")
        if kind in ("sum", "avg") and not T.is_numeric(child.dtype):
            raise AnalysisException(
                f"{kind}() over window needs a numeric input, got "
                f"{child.dtype.simple_name}")
        if kind == "avg":
            child = cast_to(child, T.DoubleT)
        if kind == "sum" and isinstance(child.dtype, T.FloatType):
            child = cast_to(child, T.DoubleT)
        if kind == "count":
            dtype = T.LongT
        elif kind == "avg":
            dtype = T.DoubleT
        elif kind == "sum":
            dtype = A.Sum(child).result_dtype
        else:
            dtype = child.dtype
        wf = L.WindowFunctionSpec(kind, child, dtype, frame=frame,
                                  frame_lo=frame_lo, frame_hi=frame_hi)
        name = f"{kind}({fu.children[0] if fu.children else '1'})"
    else:
        raise AnalysisException(
            f"only window functions and aggregates may be used with "
            f".over(), got {fu}")
    return pby, orders, wf, f"{name} OVER (...)"


def _parse_type(s: str) -> T.DataType:
    m = {"int": T.IntegerT, "integer": T.IntegerT, "long": T.LongT,
         "bigint": T.LongT, "short": T.ShortT, "byte": T.ByteT,
         "float": T.FloatT, "double": T.DoubleT, "string": T.StringT,
         "boolean": T.BooleanT, "date": T.DateT, "timestamp": T.TimestampT}
    key = str(s).strip().lower()
    if key in m:
        return m[key]
    import re as _re
    dm = _re.fullmatch(r"decimal\s*\(\s*(\d+)\s*,\s*(\d+)\s*\)", key)
    if dm:
        p, sc = int(dm.group(1)), int(dm.group(2))
        if not (0 < p <= 38 and 0 <= sc <= p):
            raise AnalysisException(f"invalid decimal type {s!r}")
        return T.DecimalType(p, sc)
    raise AnalysisException(f"cannot parse type string {s!r}")
