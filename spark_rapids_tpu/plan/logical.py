"""Logical plan nodes.

The engine's Catalyst-analog is deliberately thin: the DataFrame API
resolves names and coerces types eagerly (pyspark-style errors at call
site), so logical nodes already hold bound, typed expressions.  Physical
planning (plan/planner.py) maps these 1:1 onto CPU execs; the overrides
engine (plan/overrides.py) then rewrites supported subtrees onto TPU —
exactly the reference's split between Spark's planner and GpuOverrides.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import pyarrow as pa

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.ops.aggregates import AggregateFunction
from spark_rapids_tpu.ops.expressions import Expression


@dataclasses.dataclass
class SortOrder:
    expr: Expression
    ascending: bool = True
    nulls_first: bool = True


class LogicalPlan:
    schema: T.StructType

    @property
    def children(self) -> Tuple["LogicalPlan", ...]:
        return ()

    @property
    def name(self):
        return type(self).__name__


@dataclasses.dataclass
class InMemoryRelation(LogicalPlan):
    table: pa.Table
    schema: T.StructType
    num_partitions: int = 1
    # result-cache input identity: content fingerprint (assigned only
    # inside cache/fingerprints.py / the session catalog — enforced by
    # the cache-safety lint rule) and the catalog name this relation
    # was registered under, if any.
    fingerprint: Optional[str] = None
    source: Optional[str] = None

    @property
    def name(self):
        return "InMemoryRelation"


@dataclasses.dataclass
class ParquetRelation(LogicalPlan):
    """File-source relation (parquet or orc — ``format`` selects).

    ``columns``: pruned data-column names (projection pushdown);
    ``filters``: (name, op, literal) conjuncts for row-group pruning;
    ``partition_values``: hive-style partition values per file (aligned
    with ``paths``); ``file_name_col``: append input_file_name() column.
    Pushdown fields are filled by plan/optimizer.py, not by users.
    """

    paths: List[str]
    schema: T.StructType
    format: str = "parquet"
    columns: Optional[List[str]] = None
    filters: Optional[List[tuple]] = None
    partition_values: Optional[List[dict]] = None
    partition_fields: Tuple = ()
    file_name_col: bool = False
    # dynamic partition pruning: (build-side Project plan yielding the
    # join key column, partition column name) — filled by the optimizer
    dpp: Optional[tuple] = None
    # row-level deletes, aligned with ``paths``: per file a SORTED
    # int64 array of deleted row positions (or None) — filled by the
    # Delta (deletion vectors) / Iceberg (v2 position deletes) loaders,
    # applied as a row mask at scan time
    deletes: Optional[List] = None


@dataclasses.dataclass
class Project(LogicalPlan):
    child: LogicalPlan
    exprs: List[Expression]
    schema: T.StructType

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Filter(LogicalPlan):
    child: LogicalPlan
    condition: Expression

    @property
    def schema(self):
        return self.child.schema

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Aggregate(LogicalPlan):
    child: LogicalPlan
    grouping: List[Expression]
    aggregates: List[AggregateFunction]
    schema: T.StructType  # grouping cols then agg results

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass
class WindowFunctionSpec:
    """One bound window expression [REF: GpuWindowExpression].

    kind: row_number | rank | dense_rank | lag | lead |
          sum | min | max | count | avg | first
    frame: 'range_current' (Spark default with ORDER BY: RANGE unbounded
           preceding..current row, peers included), 'rows_current'
           (ROWS unbounded preceding..current row), or 'partition'
           (whole partition — the default without ORDER BY).
    """

    kind: str
    child: Optional[Expression]
    dtype: T.DataType
    offset: int = 1          # lag/lead offset; ntile bucket count
    frame: str = "partition"
    # rows_bounded frame offsets relative to the current row
    # (negative = preceding), e.g. rowsBetween(-2, 0) → lo=-2, hi=0;
    # for range_bounded they are ORDER-value offsets, and None means
    # unbounded on that end
    frame_lo: Optional[int] = 0
    frame_hi: Optional[int] = 0
    # lead/lag IGNORE NULLS: step over null values
    ignore_nulls: bool = False


@dataclasses.dataclass
class Window(LogicalPlan):
    """Appends window-function result columns to the child's output."""

    child: LogicalPlan
    partition_by: List[Expression]
    order_by: List[SortOrder]
    functions: List[WindowFunctionSpec]
    schema: T.StructType  # child fields + one field per function

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Sort(LogicalPlan):
    child: LogicalPlan
    orders: List[SortOrder]
    global_sort: bool = True

    @property
    def schema(self):
        return self.child.schema

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Limit(LogicalPlan):
    child: LogicalPlan
    n: int

    @property
    def schema(self):
        return self.child.schema

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Join(LogicalPlan):
    left: LogicalPlan
    right: LogicalPlan
    join_type: str  # inner, left, right, full, left_semi, left_anti, cross
    left_keys: List[Expression]
    right_keys: List[Expression]
    condition: Optional[Expression]  # residual; refs bound to left++right
    schema: T.StructType
    # USING join (key columns coalesced once in the output) vs
    # expression join (all left cols ++ all right cols, Spark semantics)
    using: bool = True

    @property
    def children(self):
        return (self.left, self.right)


@dataclasses.dataclass
class Range(LogicalPlan):
    """session.range — generated ids, no backing data."""

    start: int
    end: int
    step: int
    schema: T.StructType
    num_partitions: int = 1


@dataclasses.dataclass
class Sample(LogicalPlan):
    """Bernoulli sample (without replacement)."""

    child: LogicalPlan
    fraction: float
    seed: int

    @property
    def schema(self):
        return self.child.schema

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Expand(LogicalPlan):
    """Grouping-sets row multiplication [REF: Spark Expand]."""

    child: LogicalPlan
    projections: List[List[Expression]]
    schema: T.StructType

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Generate(LogicalPlan):
    """explode/posexplode of an array column, appending pos/element
    columns to the child's output [REF: Spark Generate]."""

    child: LogicalPlan
    generator: Expression  # ArrayType-valued
    with_pos: bool
    outer: bool
    schema: T.StructType

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass
class PythonEval(LogicalPlan):
    """Appends python-UDF result columns [REF: Spark BatchEvalPython /
    ArrowEvalPython]."""

    child: LogicalPlan
    udfs: List  # List[exec.python_udf.PyUDFSpec]
    schema: T.StructType

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass
class MapInPandas(LogicalPlan):
    child: LogicalPlan
    fn: object
    schema: T.StructType

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass
class FlatMapGroupsInPandas(LogicalPlan):
    """Grouped map — child must be co-partitioned on key_indices."""

    child: LogicalPlan
    key_indices: List[int]
    fn: object
    schema: T.StructType

    @property
    def children(self):
        return (self.child,)


@dataclasses.dataclass
class Union(LogicalPlan):
    inputs: List[LogicalPlan]

    @property
    def schema(self):
        return self.inputs[0].schema

    @property
    def children(self):
        return tuple(self.inputs)


@dataclasses.dataclass
class Repartition(LogicalPlan):
    child: LogicalPlan
    num_partitions: int
    keys: Optional[List[Expression]] = None  # None = round robin

    @property
    def schema(self):
        return self.child.schema

    @property
    def children(self):
        return (self.child,)
