"""The plan-rewrite engine: CPU physical plan → TPU plan with fallback.

[REF: sql-plugin/../GpuOverrides.scala :: GpuOverrides (expressions/execs
 rule maps, wrapPlan), RapidsMeta.scala :: SparkPlanMeta.tagForGpu /
 willNotWorkOnGpu / convertToGpu, GpuTransitionOverrides.scala]

Mechanics mirror the reference faithfully because this IS the product's
soul (SURVEY.md §7):

* every exec/expression class has a rule in a registry;
* each plan node is wrapped in a Meta that accumulates human-readable
  "will not work on TPU because ..." reasons (type checks, per-op conf
  kill-switches, missing rules);
* tagged-ok subtrees convert to Tpu execs; transitions are inserted at
  every boundary (HostToDevice/DeviceToHost — the reference's
  Row/ColumnarToRow analog);
* ``spark.rapids.sql.explain=NOT_ON_TPU|ALL`` reports the rewrite, and
  ``spark.rapids.sql.test.enabled`` turns unexpected fallback into an
  exception (the integration-test mode, SURVEY.md §4.1).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Type

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.exec import basic as B
from spark_rapids_tpu.exec.base import CpuExec, ExecNode, TpuExec
from spark_rapids_tpu.exec.transitions import DeviceToHostExec, HostToDeviceExec
from spark_rapids_tpu.ops.expressions import Expression


# ---------------------------------------------------------------------------
# Type support lattice — the TypeSig analog
# [REF: sql-plugin/../TypeChecks.scala :: TypeSig]
# ---------------------------------------------------------------------------

def is_device_supported_type(dt: T.DataType) -> Optional[str]:
    """None if supported on device; else the reason string."""
    if isinstance(dt, T.DecimalType):
        if dt.precision > 38:
            return f"decimal precision {dt.precision} > 38"
        return None
    if isinstance(dt, (T.ArrayType, T.MapType, T.StructType)):
        return f"nested type {dt.simple_name} not yet supported on device"
    if isinstance(dt, (T.BooleanType, T.ByteType, T.ShortType, T.IntegerType,
                       T.LongType, T.FloatType, T.DoubleType, T.StringType,
                       T.BinaryType, T.DateType, T.TimestampType, T.NullType)):
        return None
    return f"type {dt.simple_name} not supported on device"


def is_device_supported_output_type(dt: T.DataType) -> Optional[str]:
    """Exec OUTPUT columns additionally allow array<primitive> — the
    collect_list result (padded element matrix + lengths, D2H-convertible)
    — while expressions over arrays stay unsupported."""
    if isinstance(dt, T.ArrayType):
        et = dt.element_type
        if isinstance(et, (T.ArrayType, T.MapType, T.StructType,
                           T.StringType, T.BinaryType, T.DecimalType)):
            return (f"array element type {et.simple_name} not supported "
                    "on device")
        return None
    return is_device_supported_type(dt)


# ---------------------------------------------------------------------------
# Meta: per-node tagging state
# ---------------------------------------------------------------------------

class ExecMeta:
    def __init__(self, cpu: CpuExec, conf: RapidsConf,
                 children: List["ExecMeta"]):
        self.cpu = cpu
        self.conf = conf
        self.children = children
        self.reasons: List[str] = []
        self.rule: Optional["ExecRule"] = None

    def will_not_work(self, reason: str):
        if reason not in self.reasons:
            self.reasons.append(reason)

    @property
    def can_run_on_tpu(self) -> bool:
        return not self.reasons

    def tag_expressions(self, exprs):
        for e in exprs:
            tag_expression(e, self)

    def tag(self):
        rule = EXEC_RULES.get(type(self.cpu))
        if rule is None:
            self.will_not_work(
                f"no TPU rule for exec {type(self.cpu).__name__}")
            return
        self.rule = rule
        if not self.conf.is_op_enabled("exec", rule.name):
            self.will_not_work(
                f"exec {rule.name} disabled by "
                f"spark.rapids.sql.exec.{rule.name}=false")
        for f in self.cpu.schema.fields:
            r = is_device_supported_output_type(f.dtype)
            if r:
                self.will_not_work(f"output column '{f.name}': {r}")
        # a CPU-falling child feeds this node through an H2D transition —
        # every column of the child's schema must survive the transfer
        # [REF: GpuTransitionOverrides.scala — transition type validation]
        for c in self.children:
            if not c.can_run_on_tpu:
                for f in c.cpu.schema.fields:
                    r = is_device_supported_output_type(f.dtype)
                    if r:
                        self.will_not_work(
                            f"input column '{f.name}' cannot cross the "
                            f"host→device transition: {r}")
        rule.tag(self)


def tag_expression(e: Expression, meta: ExecMeta):
    from spark_rapids_tpu import conf as C
    name = type(e).__name__
    if not meta.conf.is_op_enabled("expression", name):
        meta.will_not_work(
            f"expression {name} disabled by "
            f"spark.rapids.sql.expression.{name}=false")
    incompat = getattr(type(e), "incompat", None)
    if incompat and not meta.conf.get(C.INCOMPATIBLE_OPS):
        meta.will_not_work(
            f"expression {name} is not fully compatible with Spark "
            f"({incompat}); set "
            "spark.rapids.sql.incompatibleOps.enabled=true to enable")
    if meta.conf.ansi_enabled and getattr(type(e), "ansi_sensitive", False):
        meta.will_not_work(
            f"expression {name} under spark.sql.ansi.enabled=true: device "
            "lowering implements non-ANSI semantics (overflow wraps, "
            "invalid input nulls) — CPU fallback until ANSI kernels exist")
    hook = getattr(e, "device_support_reason", None)
    if hook is not None:
        r = hook(meta.conf)
        if r:
            meta.will_not_work(f"expression {name}: {r}")
    from spark_rapids_tpu.ops.expressions import BoundReference, sig_tag
    if isinstance(e, BoundReference):
        # direct column pass-through supports everything a batch can
        # carry (incl. array<numeric>); computed expressions stay
        # restricted to scalar device types
        r = is_device_supported_output_type(e.dtype)
    else:
        r = is_device_supported_type(e.dtype)
    if r:
        meta.will_not_work(f"expression {e}: {r}")
    # per-op TypeSig [REF: TypeChecks.scala]: the class declares which
    # type tags its device lowering produces/accepts — checked here,
    # rendered as the support matrix in docs/supported_ops.md
    cls = type(e)
    tag = sig_tag(e.dtype)
    if tag not in cls.type_sig:
        meta.will_not_work(
            f"expression {name} does not produce {tag} on device "
            f"(type sig: {', '.join(sorted(cls.type_sig))})")
    in_sig = cls.input_sig if cls.input_sig is not None else cls.type_sig
    for c in e.children:
        ctag = sig_tag(c.dtype)
        if ctag not in in_sig:
            meta.will_not_work(
                f"expression {name} does not accept a {ctag} input on "
                f"device (input sig: {', '.join(sorted(in_sig))})")
    if not hasattr(e, "eval_tpu") or (
            type(e).eval_tpu is Expression.eval_tpu):
        meta.will_not_work(f"expression {name} has no TPU implementation")
    for c in e.children:
        tag_expression(c, meta)


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------

class ExecRule:
    """One entry of the GpuOverrides execs map.

    ``convert(cpu, tpu_children, conf)`` — conf lets conversions pick
    distributed variants (e.g. ICI shuffle mode splits aggregates)."""

    def __init__(self, name: str,
                 tag: Callable[[ExecMeta], None],
                 convert: Callable[[CpuExec, List[TpuExec], "RapidsConf"],
                                   TpuExec],
                 desc: str = ""):
        self.name = name
        self._tag = tag
        self.convert = convert
        self.desc = desc

    def tag(self, meta: ExecMeta):
        self._tag(meta)


EXEC_RULES: Dict[Type[CpuExec], ExecRule] = {}


def register_exec(cpu_cls: Type[CpuExec], name: str, desc: str = ""):
    def deco(fns):
        tag, convert = fns
        EXEC_RULES[cpu_cls] = ExecRule(name, tag, convert, desc)
        return fns
    return deco


def _tag_scan(meta: ExecMeta):
    pass


def _convert_scan(cpu: B.CpuScanExec, children, conf):
    from spark_rapids_tpu.parallel.executor import get_executor
    ctx = get_executor()
    executor = ((ctx.process_id, ctx.num_processes) if ctx is not None
                else (0, 1))
    return B.TpuScanExec(cpu.table, cpu.schema, cpu.num_partitions(),
                         cpu.batch_rows, executor=executor)


EXEC_RULES[B.CpuScanExec] = ExecRule(
    "InMemoryScan", _tag_scan, _convert_scan,
    "in-memory table scan landing device-resident columnar batches")

EXEC_RULES[B.CpuProjectExec] = ExecRule(
    "Project",
    lambda m: m.tag_expressions(m.cpu.exprs),
    lambda cpu, ch, conf: B.TpuProjectExec(cpu.exprs, cpu.schema, ch[0]),
    "columnar projection")

EXEC_RULES[B.CpuFilterExec] = ExecRule(
    "Filter",
    lambda m: m.tag_expressions([m.cpu.condition]),
    lambda cpu, ch, conf: B.TpuFilterExec(cpu.condition, ch[0]),
    "columnar filter (predicate folds into the selection mask)")

EXEC_RULES[B.CpuLocalLimitExec] = ExecRule(
    "LocalLimit",
    lambda m: None,
    lambda cpu, ch, conf: B.TpuLocalLimitExec(cpu.n, ch[0]),
    "limit over live rows")

EXEC_RULES[B.CpuGlobalLimitExec] = ExecRule(
    "GlobalLimit",
    lambda m: None,
    lambda cpu, ch, conf: B.TpuGlobalLimitExec(cpu.n, ch[0]),
    "global limit cut across partitions")

EXEC_RULES[B.CpuUnionExec] = ExecRule(
    "Union",
    lambda m: None,
    lambda cpu, ch, conf: B.TpuUnionExec(ch),
    "union of children partitions")


def _tag_aggregate(meta: ExecMeta):
    from spark_rapids_tpu.exec.aggregate import CpuAggregateExec
    from spark_rapids_tpu.ops.aggregates import (
        Average, CollectList, Count, CountStar, First, Max, Min,
        Percentile, Sum, _VarianceBase)
    cpu: CpuAggregateExec = meta.cpu
    meta.tag_expressions(cpu.grouping)
    for fn in cpu.fns:
        if isinstance(fn, Sum) and meta.conf.ansi_enabled:
            meta.will_not_work(
                "sum under spark.sql.ansi.enabled=true: device sum wraps "
                "on overflow (non-ANSI) — CPU fallback")
        if not isinstance(fn, (Sum, Min, Max, Count, CountStar, Average,
                               First, _VarianceBase, CollectList,
                               Percentile)):
            meta.will_not_work(
                f"aggregate function {fn.name} has no TPU implementation")
            continue
        if not isinstance(fn, CountStar):
            meta.tag_expressions([fn.child])
        if isinstance(fn, _VarianceBase) and not T.is_numeric(
                fn.input_dtype):
            meta.will_not_work(f"{fn.name} needs a numeric input")
        if isinstance(fn, CollectList):
            if isinstance(fn.input_dtype,
                          (T.StringType, T.BinaryType, T.DecimalType,
                           T.ArrayType)):
                meta.will_not_work(
                    f"collect_list over {fn.input_dtype.simple_name} not "
                    "on device yet (element matrix is numeric-only)")


def _convert_aggregate(cpu, ch, conf):
    from spark_rapids_tpu import conf as C
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.exec.distributed import ici_active
    from spark_rapids_tpu.ops.aggregates import CollectList, Percentile
    has_nans = bool(conf.get(C.HAS_NANS))
    tuning = dict(has_nans=has_nans,
                  bucket_rows=conf.get(C.AGG_BUCKET_ROWS),
                  skip_ratio=conf.get(C.AGG_SKIP_RATIO))
    from spark_rapids_tpu.exec.aggregate import is_holistic_fn
    # holistic functions (collect/percentile, and min/max/first over
    # multi-limb dtypes) run the single-kernel gathered path — they
    # cannot ride buffer batches through a partial/final split
    has_collect = any(is_holistic_fn(f) for f in cpu.fns)
    if ici_active(conf) and cpu.grouping and not has_collect:
        # distributed: {partial agg → hash exchange on keys → final agg}
        # — one SPMD all_to_all per shuffle stage (SURVEY §5.8)
        from spark_rapids_tpu.exec.distributed import (
            TpuIciShuffleExchangeExec, exchange_opts)
        from spark_rapids_tpu.ops.expressions import BoundReference
        partial = TpuHashAggregateExec(cpu.grouping, cpu.fns, None, ch[0],
                                       mode="partial", **tuning)
        partial.schema = partial._buffer_schema()
        keys = [BoundReference(i, g.dtype)
                for i, g in enumerate(cpu.grouping)]
        exchange = TpuIciShuffleExchangeExec(partial, keys,
                                             **exchange_opts(conf))
        return TpuHashAggregateExec(cpu.grouping, cpu.fns, cpu.schema,
                                    exchange, mode="final", **tuning)
    return TpuHashAggregateExec(cpu.grouping, cpu.fns, cpu.schema, ch[0],
                                **tuning)


def _register_lazy_rules():
    """Rules for exec classes defined in lazily-imported modules."""
    from spark_rapids_tpu.exec.aggregate import CpuAggregateExec
    EXEC_RULES.setdefault(CpuAggregateExec, ExecRule(
        "HashAggregate", _tag_aggregate, _convert_aggregate,
        "sort-based device groupby (lax.sort + segment reduce)"))
    try:
        from spark_rapids_tpu.exec.sort import CpuSortExec
        from spark_rapids_tpu.exec.sort import _tag_sort, _convert_sort
        EXEC_RULES.setdefault(CpuSortExec, ExecRule(
            "Sort", _tag_sort, _convert_sort,
            "device lexicographic sort (lax.sort on orderable keys)"))
    except ImportError:
        pass
    try:
        from spark_rapids_tpu.exec.join import (
            CpuJoinExec, _tag_join, _convert_join)
        EXEC_RULES.setdefault(CpuJoinExec, ExecRule(
            "SortMergeJoin", _tag_join, _convert_join,
            "device sort-merge equi-join"))
    except ImportError:
        pass
    try:
        from spark_rapids_tpu.exec.window import (
            CpuWindowExec, _tag_window, _convert_window)
        EXEC_RULES.setdefault(CpuWindowExec, ExecRule(
            "Window", _tag_window, _convert_window,
            "device window functions (sorted segmented scans)"))
    except ImportError:
        pass
    try:
        from spark_rapids_tpu.exec.exchange import (
            CpuShuffleExchangeExec, _tag_exchange, _convert_exchange)
        EXEC_RULES.setdefault(CpuShuffleExchangeExec, ExecRule(
            "ShuffleExchange", _tag_exchange, _convert_exchange,
            "device hash partitioning (bit-exact Spark murmur3)"))
    except ImportError:
        pass
    try:
        from spark_rapids_tpu.io.parquet import (
            CpuParquetScanExec, _tag_parquet, _convert_parquet)
        EXEC_RULES.setdefault(CpuParquetScanExec, ExecRule(
            "ParquetScan", _tag_parquet, _convert_parquet,
            "parquet scan landing device-resident batches"))
    except ImportError:
        pass
    try:
        from spark_rapids_tpu.exec import misc as M
        EXEC_RULES.setdefault(M.CpuRangeExec, ExecRule(
            "Range", M._tag_range, M._convert_range,
            "device iota id generation (no host data)"))
        EXEC_RULES.setdefault(M.CpuSampleExec, ExecRule(
            "Sample", M._tag_sample, M._convert_sample,
            "hash-Bernoulli sample folded into the sel mask"))
        EXEC_RULES.setdefault(M.CpuExpandExec, ExecRule(
            "Expand", M._tag_expand, M._convert_expand,
            "grouping-sets expansion (one kernel per projection)"))
        EXEC_RULES.setdefault(M.CpuGenerateExec, ExecRule(
            "Generate", M._tag_generate, M._convert_generate,
            "explode/posexplode via element-matrix reshape"))
        EXEC_RULES.setdefault(M.CpuTopNExec, ExecRule(
            "TakeOrderedAndProject", M._tag_topn, M._convert_topn,
            "per-partition device topN + winner merge"))
    except ImportError:
        pass
    try:
        from spark_rapids_tpu.exec import python_udf as PU
        EXEC_RULES.setdefault(PU.CpuArrowEvalPythonExec, ExecRule(
            "ArrowEvalPython", PU._tag_python_eval,
            PU._convert_python_eval,
            "python/pandas UDFs: device args → in-process arrow bridge"))
        EXEC_RULES.setdefault(PU.CpuMapInPandasExec, ExecRule(
            "MapInPandas", PU._tag_map_in_pandas,
            PU._convert_map_in_pandas,
            "mapInPandas over the arrow bridge"))
        EXEC_RULES.setdefault(PU.CpuFlatMapGroupsInPandasExec, ExecRule(
            "FlatMapGroupsInPandas", PU._tag_flat_map_groups,
            PU._convert_flat_map_groups,
            "grouped-map pandas UDF above a device hash exchange"))
    except ImportError:
        pass


# ---------------------------------------------------------------------------
# The rewrite pass
# ---------------------------------------------------------------------------

class OverrideResult:
    def __init__(self, plan: ExecNode, metas: List[ExecMeta]):
        self.plan = plan
        self.metas = metas

    def fallback_report(self) -> List[str]:
        out = []
        for m in self.metas:
            if not m.can_run_on_tpu:
                for r in m.reasons:
                    out.append(
                        f"!Exec <{type(m.cpu).__name__}> cannot run on TPU "
                        f"because {r}")
        return out

    def fallback_summary(self) -> dict:
        """The fallback BUDGET as a metric [REF: ExplainPlanImpl — the
        reference's explain=NOT_ON_GPU output, condensed to the number
        that tracks progress]: how many plan operators run on device vs
        fell back, with reasons."""
        device = sum(1 for m in self.metas if m.can_run_on_tpu)
        fallen = [m for m in self.metas if not m.can_run_on_tpu]
        return {
            "device_ops": device,
            "fallback_ops": len(fallen),
            "device_fraction": round(
                device / max(len(self.metas), 1), 3),
            "fallback_reasons": sorted(
                {f"{type(m.cpu).__name__}: {r}"
                 for m in fallen for r in m.reasons}),
        }


def wrap(cpu: CpuExec, conf: RapidsConf, all_metas: List[ExecMeta]) -> ExecMeta:
    children = [wrap(c, conf, all_metas) for c in cpu.children
                if isinstance(c, CpuExec)]
    meta = ExecMeta(cpu, conf, children)
    meta.tag()
    all_metas.append(meta)
    return meta


def _rebuild_cpu(cpu: CpuExec, new_children: List[ExecNode]) -> CpuExec:
    """Copy a CPU exec onto (possibly transition-wrapped) children.

    A shallow copy, NOT in-place mutation: the original plan nodes stay
    pristine so re-planning/re-executing a DataFrame never sees a
    half-rewritten tree."""
    import copy
    clone = copy.copy(cpu)
    clone._children = tuple(new_children)
    clone.metrics = {k: type(m)(m.name) for k, m in cpu.metrics.items()}
    return clone


def convert_meta(meta: ExecMeta) -> ExecNode:
    """Bottom-up conversion with transition insertion."""
    converted = [convert_meta(c) for c in meta.children]
    if meta.can_run_on_tpu:
        tpu_children = [
            c if isinstance(c, TpuExec) else HostToDeviceExec(c)
            for c in converted
        ]
        return meta.rule.convert(meta.cpu, tpu_children, meta.conf)
    cpu_children = [
        c if isinstance(c, CpuExec) else DeviceToHostExec(c)
        for c in converted
    ]
    return _rebuild_cpu(meta.cpu, cpu_children)


def _estimated_row_bytes(schema: T.StructType,
                         str_width: Optional[int] = None) -> int:
    """Rough bytes/row for batch-size targeting and working-set
    accounting.  ``str_width``: known string-matrix width (the ICI
    exchange passes it); default is a 40-byte planning-time guess."""
    total = 0
    for f in schema.fields:
        if isinstance(f.dtype, (T.StringType, T.BinaryType)):
            total += (max(str_width, 8) + 4) if str_width is not None \
                else 40
        else:
            total += 8
        total += 1  # validity
    return max(total, 1)


def insert_coalesce(node: ExecNode, conf: RapidsConf) -> ExecNode:
    """The GpuTransitionOverrides coalesce pass [REF:
    GpuTransitionOverrides.scala + GpuCoalesceBatches.scala]:

    * a TargetSize coalesce above every H2D transition (CPU-fallback
      sources emit small batches; merge them up to
      ``spark.rapids.sql.batchSizeBytes`` before device operators), and
    * a RequireSingleBatch coalesce under whole-partition consumers
      (sort / join / window), making the batching contract a plan node
      instead of ad-hoc concatenation inside the operator.
    """
    from spark_rapids_tpu.exec.basic import TpuCoalesceBatchesExec
    from spark_rapids_tpu.exec.distributed import TpuIciShuffleExchangeExec
    from spark_rapids_tpu.exec.join import TpuBroadcastExchangeExec
    from spark_rapids_tpu.exec.sort import TpuSortExec
    from spark_rapids_tpu.exec.window import TpuWindowExec
    from spark_rapids_tpu import conf as C

    node._children = tuple(insert_coalesce(c, conf)
                           for c in node.children)
    if isinstance(node, HostToDeviceExec):
        target = max(conf.get(C.BATCH_SIZE_BYTES)
                     // _estimated_row_bytes(node.schema),
                     conf.min_bucket_rows)
        # row-capped at batchRows: static-shape kernels compile per
        # pow-2 bucket, and batchRows is THE documented bound on bucket
        # size — an unbounded byte target (512 MB / 8-byte rows = a
        # 64M-row bucket) must never override it
        target = min(target, conf.batch_rows)
        return TpuCoalesceBatchesExec(node, target_rows=target)
    if isinstance(node, (TpuSortExec, TpuWindowExec)):
        # RequireSingleBatch is only made plan-visible for single-
        # partition children: there it replaces the operator's internal
        # concat 1:1.  Multi-partition children keep the operator's own
        # cross-partition gather (one concat) — a per-partition coalesce
        # below it would copy every row twice.  JOINS are deliberately
        # NOT here (round-5 fix): a pre-concatenated whole side would
        # bypass TpuSortMergeJoin's row-capped sub-partitioning — the
        # single giant gather (6M rows → one 8M bucket on TPC-H q10)
        # is exactly what killed the r4 TPU worker.
        node._children = tuple(
            TpuCoalesceBatchesExec(c, require_single=True)
            if isinstance(c, TpuExec) and c.num_partitions() == 1
            and not isinstance(
                c, (TpuCoalesceBatchesExec, TpuIciShuffleExchangeExec,
                    TpuBroadcastExchangeExec))
            else c
            for c in node._children)
    return node


# multi-executor mode supports the partition-preserving pipeline around
# ICI exchanges; global-gather operators would silently compute on one
# process's slice only, so they fail loudly instead.  The name list
# covers operators wrong-by-semantics even when partition-preserving
# (windows need co-partitioning; broadcast captures one slice; the
# non-collective shuffle exchanges are in-process only); the structural
# checks below catch every gather point and partition-structure change,
# including CPU-fallback nodes.
# Sort/Window/TopN/GlobalLimit are distributable since round 5: Sort
# rides a RANGE exchange + per-partition local sorts, Window a hash
# exchange on partition_by, TopN/GlobalLimit reduce locally then
# rendezvous-allgather their (tiny) winner rows / counts.
_MULTIPROC_UNSUPPORTED = {
    "TpuBroadcastExchangeExec", "TpuExpandExec",
    "TpuGenerateExec", "TpuPythonUDFExec", "TpuSampleExec",
    "CpuSortExec", "CpuGlobalLimitExec", "CpuTakeOrderedAndProjectExec",
    "CpuWindowExec", "CpuSampleExec", "CpuPythonUDFExec",
    "TpuShuffleExchangeExec", "CpuShuffleExchangeExec",
}


def _validate_multiproc(plan) -> None:
    from spark_rapids_tpu.exec.distributed import TpuIciShuffleExchangeExec
    from spark_rapids_tpu.exec.join import CpuJoinExec, TpuSortMergeJoinExec

    def bad(name, why):
        raise NotImplementedError(
            f"{name} is not supported in multi-executor mode "
            f"(executor.count > 1): {why}. Run on a single executor, or "
            "restructure the query around hash exchanges (agg / "
            "co-partitioned equi-join pipelines are supported).")

    def has_exchange(node):
        return isinstance(node, TpuIciShuffleExchangeExec) or any(
            has_exchange(c) for c in node.children)

    def walk(node):
        name = type(node).__name__
        if name in _MULTIPROC_UNSUPPORTED:
            bad(name, "it computes on one executor's slice only")
        if isinstance(node, TpuSortMergeJoinExec) and not node.partitioned:
            bad(name, "only co-partitioned (ICI-exchanged) equi-joins "
                "are distributed; this join would match one slice "
                "against another")
        if isinstance(node, CpuJoinExec):
            bad(name, "CPU-fallback joins gather one slice per process")
        gather_ok = getattr(node, "_multiproc_gather_ok", False)
        for c in node.children:
            # structural guards (catch CPU fallbacks and any operator
            # missed by name): a gather point collapses partitions this
            # process only partly owns; a partition-structure change
            # above an exchange breaks local-partition ownership.
            # Nodes flagged _multiproc_gather_ok (TopN, GlobalLimit)
            # gather via an explicit cross-process allgather instead.
            if (not isinstance(node, TpuIciShuffleExchangeExec)
                    and not gather_ok
                    and c.num_partitions() > 1
                    and node.num_partitions() == 1):
                bad(name, "it gathers all partitions into one, but "
                    "each executor holds only its slice")
            if (has_exchange(c) and not isinstance(
                    node, TpuIciShuffleExchangeExec)
                    and not gather_ok
                    and node.num_partitions() != c.num_partitions()):
                bad(name, "it re-groups partitions above a collective "
                    "exchange, breaking local-partition ownership")
            walk(c)

    walk(plan)


def apply_overrides(cpu_plan: CpuExec, conf: RapidsConf) -> OverrideResult:
    """GpuOverrides.apply + GpuTransitionOverrides in one pass."""
    if not conf.sql_enabled:
        return OverrideResult(cpu_plan, [])
    # configure the HBM budget arbiter from this query's conf (memory
    # keys + OOM fault injection) before any device materialization
    from spark_rapids_tpu.runtime.memory import get_manager
    get_manager(conf)
    from spark_rapids_tpu.runtime.resilience import configure_from_conf
    configure_from_conf(conf)
    _register_lazy_rules()
    metas: List[ExecMeta] = []
    root = wrap(cpu_plan, conf, metas)
    plan = convert_meta(root)
    if isinstance(plan, TpuExec):
        plan = DeviceToHostExec(plan)
    plan = insert_coalesce(plan, conf)
    # whole-stage fusion last: it needs the final converted tree (so the
    # member signatures it records match what an unfused run of this
    # exact plan would execute — see fusion/regions.py)
    from spark_rapids_tpu.fusion import fuse_plan
    plan, _ = fuse_plan(plan, conf)
    from spark_rapids_tpu.parallel.executor import get_executor
    if get_executor() is not None:
        _validate_multiproc(plan)
    from spark_rapids_tpu import conf as C
    lore_tag = str(conf.get(C.LORE_TAG)).strip()
    if lore_tag:
        from spark_rapids_tpu.utils.lore import install_lore_taps
        plan = install_lore_taps(plan, lore_tag,
                                 str(conf.get(C.LORE_DUMP_PATH)))
    result = OverrideResult(plan, metas)

    explain = conf.explain
    report = result.fallback_report()
    if explain == "ALL" or (explain in ("NOT_ON_GPU", "NOT_ON_TPU")
                            and report):
        print("TPU plan rewrite:")
        for line in report:
            print("  " + line)
        if explain == "ALL":
            print(plan.tree_string())

    if conf.test_enabled and report:
        allowed = set(conf.allowed_non_gpu)
        bad = [m for m in metas if not m.can_run_on_tpu
               and type(m.cpu).__name__ not in allowed
               and (EXEC_RULES.get(type(m.cpu)) is None
                    or EXEC_RULES[type(m.cpu)].name not in allowed)]
        if bad:
            lines = "\n".join(r for m in bad for r in m.reasons)
            raise AssertionError(
                "Part of the plan is not columnar (TPU test mode): \n"
                + lines)
    return result
