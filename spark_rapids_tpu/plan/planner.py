"""Physical planning: logical plan → CPU physical plan.

The vanilla-Spark-planner analog.  Produces a ``CpuExec`` tree; the
overrides engine (plan/overrides.py) then rewrites supported subtrees onto
TPU — the same split as Spark's planner + the reference's GpuOverrides
ColumnarRule [REF: sql-plugin/../GpuOverrides.scala :: ColumnarOverrideRules].
"""

from __future__ import annotations

from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.exec import basic as B
from spark_rapids_tpu.exec.base import CpuExec
from spark_rapids_tpu.plan import logical as L


def plan_physical(node: L.LogicalPlan, conf: RapidsConf) -> CpuExec:
    if isinstance(node, L.InMemoryRelation):
        return B.CpuScanExec(node.table, node.schema, node.num_partitions,
                             conf.batch_rows)
    if isinstance(node, L.ParquetRelation):
        from spark_rapids_tpu.io.parquet import CpuParquetScanExec
        return CpuParquetScanExec(node, conf)
    if isinstance(node, L.Project):
        return B.CpuProjectExec(node.exprs, node.schema,
                                plan_physical(node.child, conf))
    if isinstance(node, L.Filter):
        return B.CpuFilterExec(node.condition,
                               plan_physical(node.child, conf))
    if isinstance(node, L.Limit):
        # TakeOrderedAndProject pattern: Limit(Sort) / Limit(Project(Sort))
        # plans a topN instead of a full global sort [REF: GpuTopN]
        from spark_rapids_tpu.exec.misc import CpuTopNExec
        inner = node.child
        proj = None
        if isinstance(inner, L.Project):
            proj, inner = inner, inner.child
        if isinstance(inner, L.Sort) and inner.global_sort:
            topn = CpuTopNExec(inner.orders, node.n,
                               plan_physical(inner.child, conf))
            if proj is not None:
                return B.CpuProjectExec(proj.exprs, proj.schema, topn)
            return topn
        return B.CpuGlobalLimitExec(
            node.n, B.CpuLocalLimitExec(node.n,
                                        plan_physical(node.child, conf)))
    if isinstance(node, L.Range):
        from spark_rapids_tpu.exec.misc import CpuRangeExec
        return CpuRangeExec(node.start, node.end, node.step, node.schema,
                            node.num_partitions, conf.batch_rows)
    if isinstance(node, L.Sample):
        from spark_rapids_tpu.exec.misc import CpuSampleExec
        return CpuSampleExec(node.fraction, node.seed,
                             plan_physical(node.child, conf))
    if isinstance(node, L.Expand):
        from spark_rapids_tpu.exec.misc import CpuExpandExec
        return CpuExpandExec(node.projections, node.schema,
                             plan_physical(node.child, conf))
    if isinstance(node, L.Generate):
        from spark_rapids_tpu.exec.misc import CpuGenerateExec
        return CpuGenerateExec(node.generator, node.with_pos, node.outer,
                               node.schema,
                               plan_physical(node.child, conf))
    if isinstance(node, L.PythonEval):
        from spark_rapids_tpu.exec.python_udf import CpuArrowEvalPythonExec
        return CpuArrowEvalPythonExec(node.udfs, node.schema,
                                      plan_physical(node.child, conf))
    if isinstance(node, L.MapInPandas):
        from spark_rapids_tpu.exec.python_udf import CpuMapInPandasExec
        return CpuMapInPandasExec(node.fn, node.schema,
                                  plan_physical(node.child, conf))
    if isinstance(node, L.FlatMapGroupsInPandas):
        from spark_rapids_tpu.exec.python_udf import (
            CpuFlatMapGroupsInPandasExec)
        return CpuFlatMapGroupsInPandasExec(
            node.key_indices, node.fn, node.schema,
            plan_physical(node.child, conf))
    if isinstance(node, L.Union):
        return B.CpuUnionExec([plan_physical(c, conf) for c in node.inputs])
    if isinstance(node, L.Aggregate):
        from spark_rapids_tpu.exec.aggregate import plan_cpu_aggregate
        return plan_cpu_aggregate(node, plan_physical(node.child, conf), conf)
    if isinstance(node, L.Sort):
        from spark_rapids_tpu.exec.sort import CpuSortExec
        return CpuSortExec(node.orders, plan_physical(node.child, conf))
    if isinstance(node, L.Join):
        from spark_rapids_tpu.exec.join import CpuJoinExec
        return CpuJoinExec(node.join_type, node.left_keys, node.right_keys,
                           node.condition, node.schema,
                           plan_physical(node.left, conf),
                           plan_physical(node.right, conf),
                           using=node.using)
    if isinstance(node, L.Window):
        from spark_rapids_tpu.exec.window import CpuWindowExec
        return CpuWindowExec(node.partition_by, node.order_by,
                             node.functions, node.schema,
                             plan_physical(node.child, conf))
    if isinstance(node, L.Repartition):
        from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
        return CpuShuffleExchangeExec(
            plan_physical(node.child, conf), node.num_partitions, node.keys)
    raise NotImplementedError(f"no physical plan for {node.name}")
