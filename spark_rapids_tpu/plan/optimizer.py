"""Logical-plan optimizer: scan pushdown.

[REF: the reference relies on Spark's own optimizer for column pruning /
 filter pushdown and implements the scan side in GpuParquetScan.scala
 (predicate → row-group pruning) and GpuFileSourceScanExec.scala
 (partition values, input_file_name).  This engine has no Catalyst, so
 the two scan-facing rules live here.]

Rules (bottom-up, single pass):

* **Filter pushdown**: ``Filter* → ParquetRelation`` chains attach their
  simple conjuncts ``(col, cmp, literal)`` to the relation for row-group
  statistics pruning.  The Filter stays in the plan — pruning is
  conservative, exactness comes from the Filter itself.
* **Column pruning**: a ``Project | Aggregate → Filter* → ParquetRelation``
  chain narrows the relation to the referenced columns and remaps every
  bound reference in the chain.  (Head nodes define a fresh schema, so
  ancestors are unaffected.)
* **input_file_name() binding**: markers in the head projection turn on
  the relation's file-name column and rebind to it.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Set, Tuple

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.ops import expressions as E
from spark_rapids_tpu.plan import logical as L


def transform_expr(e: E.Expression, fn) -> E.Expression:
    """Rebuild an expression tree bottom-up; fn(node) may return a
    replacement (or None to keep the rebuilt node)."""
    if dataclasses.is_dataclass(e):
        changes = {}
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            nv = _transform_field(v, fn)
            if nv is not v:
                changes[f.name] = nv
        if changes:
            e = dataclasses.replace(e, **changes)
    out = fn(e)
    return e if out is None else out


def _transform_field(v, fn):
    if isinstance(v, E.Expression):
        return transform_expr(v, fn)
    if isinstance(v, (list, tuple)):
        items = [_transform_field(x, fn) for x in v]
        if all(a is b for a, b in zip(items, v)):
            return v
        return type(v)(items) if isinstance(v, tuple) else items
    return v


def collect_refs(e: E.Expression, out: Set[int]):
    if isinstance(e, E.BoundReference):
        out.add(e.index)
    for c in e.children:
        collect_refs(c, out)


def _has_file_name_marker(exprs) -> bool:
    found = [False]

    def look(e):
        if isinstance(e, E.InputFileName):
            found[0] = True
        for c in e.children:
            look(c)

    for e in exprs:
        look(e)
    return found[0]


_CMP_OPS = {E.EqualTo: "eq", E.LessThan: "lt", E.LessThanOrEqual: "le",
            E.GreaterThan: "gt", E.GreaterThanOrEqual: "ge"}
_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}
_PUSHABLE_LIT = (T.ByteType, T.ShortType, T.IntegerType, T.LongType,
                 T.FloatType, T.DoubleType, T.StringType, T.BooleanType)


def _extract_filters(cond: E.Expression, rel: L.ParquetRelation
                     ) -> List[tuple]:
    """Simple (col-name, op, literal) conjuncts for row-group pruning."""
    n_data = (len(rel.schema.fields) - len(rel.partition_fields)
              - (1 if rel.file_name_col else 0))
    out = []

    def visit(e):
        if isinstance(e, E.And):
            visit(e.left)
            visit(e.right)
            return
        op = _CMP_OPS.get(type(e))
        if op is None:
            return
        ref, lit, flip = None, None, False
        if (isinstance(e.left, E.BoundReference)
                and isinstance(e.right, E.Literal)):
            ref, lit = e.left, e.right
        elif (isinstance(e.right, E.BoundReference)
              and isinstance(e.left, E.Literal)):
            ref, lit, flip = e.right, e.left, True
        if ref is None or lit.value is None or ref.index >= n_data:
            return
        if not isinstance(lit.dtype, _PUSHABLE_LIT):
            return
        v = lit.value
        if isinstance(v, float) and v != v:  # NaN never prunes
            return
        out.append((rel.schema.fields[ref.index].name,
                    _FLIP[op] if flip else op, v))

    visit(cond)
    return out


def _filter_chain(node) -> Tuple[List[L.Filter], Optional[L.ParquetRelation]]:
    filters = []
    while isinstance(node, L.Filter):
        filters.append(node)
        node = node.child
    if isinstance(node, L.ParquetRelation):
        return filters, node
    return filters, None


def _rebuild_chain(filters: List[L.Filter], leaf, remap=None):
    """Re-stack Filter nodes (innermost last) over a new leaf, remapping
    their conditions when the leaf schema changed."""
    node = leaf
    for f in reversed(filters):
        cond = f.condition
        if remap is not None:
            cond = transform_expr(cond, remap)
        node = L.Filter(node, cond)
    return node


def _prune_relation(rel: L.ParquetRelation, required: Set[int],
                    need_file_name: bool):
    """Narrowed relation + old→new index map."""
    fields = rel.schema.fields
    n_data = (len(fields) - len(rel.partition_fields)
              - (1 if rel.file_name_col else 0))
    if n_data and not any(i < n_data for i in required):
        # partition-only / count(*) shapes: always read ≥1 data column —
        # ORC's reader loses the row count on a zero-column read
        required = set(required) | {0}
    keep = sorted(required)
    index_map = {old: new for new, old in enumerate(keep)}
    new_fields = [fields[i] for i in keep]
    columns = [fields[i].name for i in keep if i < n_data]
    part_fields = tuple(fields[i] for i in keep
                        if n_data <= i < n_data + len(rel.partition_fields))
    file_name_col = rel.file_name_col or need_file_name
    if file_name_col:
        new_fields.append(T.StructField("input_file_name()", T.StringT,
                                        False))
        fn_idx = len(new_fields) - 1
    else:
        fn_idx = None
    new_rel = dataclasses.replace(
        rel, schema=T.StructType(tuple(new_fields)), columns=columns,
        partition_fields=part_fields, file_name_col=file_name_col)
    return new_rel, index_map, fn_idx


def _make_remap(index_map, fn_idx):
    def remap(e):
        if isinstance(e, E.BoundReference):
            return E.BoundReference(index_map[e.index], e.dtype,
                                    e.nullable)
        if isinstance(e, E.InputFileName):
            if fn_idx is None:
                return None
            return E.BoundReference(fn_idx, T.StringT, False)
        return None
    return remap


def optimize(plan: L.LogicalPlan) -> L.LogicalPlan:
    plan = _rewrite_children(plan)

    if isinstance(plan, (L.Project, L.Aggregate)):
        filters, rel = _filter_chain(plan.child)
        # the inner Filter rule may already have attached row-group
        # filters (bottom-up order) — pruning only needs columns unset
        if rel is not None and rel.columns is None:
            if isinstance(plan, L.Project):
                head_exprs = list(plan.exprs)
            else:
                head_exprs = (list(plan.grouping)
                              + [f.child for f in plan.aggregates
                                 if getattr(f, "child", None) is not None])
            required: Set[int] = set()
            for e in head_exprs:
                collect_refs(e, required)
            for f in filters:
                collect_refs(f.condition, required)
            need_fn = isinstance(plan, L.Project) and _has_file_name_marker(
                head_exprs)
            pushed = rel.filters
            if pushed is None:
                pushed = []
                for f in filters:
                    pushed.extend(_extract_filters(f.condition, rel))
            new_rel, index_map, fn_idx = _prune_relation(
                rel, required, need_fn)
            if pushed:
                new_rel = dataclasses.replace(new_rel, filters=pushed)
            remap = _make_remap(index_map, fn_idx)
            child = _rebuild_chain(filters, new_rel, remap)
            if isinstance(plan, L.Project):
                exprs = [transform_expr(e, remap) for e in plan.exprs]
                return L.Project(child, exprs, plan.schema)
            grouping = [transform_expr(e, remap) for e in plan.grouping]
            aggs = [transform_expr(a, remap) for a in plan.aggregates]
            return L.Aggregate(child, grouping, aggs, plan.schema)

    if isinstance(plan, L.Filter):
        filters, rel = _filter_chain(plan)
        if rel is not None and rel.filters is None:
            pushed = []
            for f in filters:
                pushed.extend(_extract_filters(f.condition, rel))
            if pushed:
                new_rel = dataclasses.replace(rel, filters=pushed)
                return _rebuild_chain(filters, new_rel)

    return plan


def _rewrite_children(plan: L.LogicalPlan) -> L.LogicalPlan:
    if isinstance(plan, L.Union):
        return L.Union([optimize(c) for c in plan.inputs])
    if isinstance(plan, L.Join):
        return dataclasses.replace(plan, left=optimize(plan.left),
                                   right=optimize(plan.right))
    if hasattr(plan, "child"):
        return dataclasses.replace(plan, child=optimize(plan.child))
    return plan
