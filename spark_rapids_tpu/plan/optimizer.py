"""Logical-plan optimizer: scan pushdown.

[REF: the reference relies on Spark's own optimizer for column pruning /
 filter pushdown and implements the scan side in GpuParquetScan.scala
 (predicate → row-group pruning) and GpuFileSourceScanExec.scala
 (partition values, input_file_name).  This engine has no Catalyst, so
 the two scan-facing rules live here.]

Rules (bottom-up, single pass):

* **Filter pushdown**: ``Filter* → ParquetRelation`` chains attach their
  simple conjuncts ``(col, cmp, literal)`` to the relation for row-group
  statistics pruning.  The Filter stays in the plan — pruning is
  conservative, exactness comes from the Filter itself.
* **Column pruning**: a ``Project | Aggregate → Filter* → ParquetRelation``
  chain narrows the relation to the referenced columns and remaps every
  bound reference in the chain.  (Head nodes define a fresh schema, so
  ancestors are unaffected.)
* **input_file_name() binding**: markers in the head projection turn on
  the relation's file-name column and rebind to it.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Set, Tuple

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.ops import expressions as E
from spark_rapids_tpu.plan import logical as L


def transform_expr(e: E.Expression, fn) -> E.Expression:
    """Rebuild an expression tree bottom-up; fn(node) may return a
    replacement (or None to keep the rebuilt node)."""
    if dataclasses.is_dataclass(e):
        changes = {}
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            nv = _transform_field(v, fn)
            if nv is not v:
                changes[f.name] = nv
        if changes:
            e = dataclasses.replace(e, **changes)
    out = fn(e)
    return e if out is None else out


def _transform_field(v, fn):
    if isinstance(v, E.Expression):
        return transform_expr(v, fn)
    if isinstance(v, (list, tuple)):
        items = [_transform_field(x, fn) for x in v]
        if all(a is b for a, b in zip(items, v)):
            return v
        return type(v)(items) if isinstance(v, tuple) else items
    return v


def collect_refs(e: E.Expression, out: Set[int]):
    if isinstance(e, E.BoundReference):
        out.add(e.index)
    for c in e.children:
        collect_refs(c, out)


def _has_file_name_marker(exprs) -> bool:
    found = [False]

    def look(e):
        if isinstance(e, E.InputFileName):
            found[0] = True
        for c in e.children:
            look(c)

    for e in exprs:
        look(e)
    return found[0]


_CMP_OPS = {E.EqualTo: "eq", E.LessThan: "lt", E.LessThanOrEqual: "le",
            E.GreaterThan: "gt", E.GreaterThanOrEqual: "ge"}
_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}
_PUSHABLE_LIT = (T.ByteType, T.ShortType, T.IntegerType, T.LongType,
                 T.FloatType, T.DoubleType, T.StringType, T.BooleanType)


def _extract_filters(cond: E.Expression, rel: L.ParquetRelation
                     ) -> List[tuple]:
    """Simple (col-name, op, literal) conjuncts for row-group pruning."""
    n_data = (len(rel.schema.fields) - len(rel.partition_fields)
              - (1 if rel.file_name_col else 0))
    out = []

    def visit(e):
        if isinstance(e, E.And):
            visit(e.left)
            visit(e.right)
            return
        op = _CMP_OPS.get(type(e))
        if op is None:
            return
        ref, lit, flip = None, None, False
        if (isinstance(e.left, E.BoundReference)
                and isinstance(e.right, E.Literal)):
            ref, lit = e.left, e.right
        elif (isinstance(e.right, E.BoundReference)
              and isinstance(e.left, E.Literal)):
            ref, lit, flip = e.right, e.left, True
        if ref is None or lit.value is None or ref.index >= n_data:
            return
        if not isinstance(lit.dtype, _PUSHABLE_LIT):
            return
        v = lit.value
        if isinstance(v, float) and v != v:  # NaN never prunes
            return
        out.append((rel.schema.fields[ref.index].name,
                    _FLIP[op] if flip else op, v))

    visit(cond)
    return out


def _filter_chain(node) -> Tuple[List[L.Filter], Optional[L.ParquetRelation]]:
    filters = []
    while isinstance(node, L.Filter):
        filters.append(node)
        node = node.child
    if isinstance(node, L.ParquetRelation):
        return filters, node
    return filters, None


def _rebuild_chain(filters: List[L.Filter], leaf, remap=None):
    """Re-stack Filter nodes (innermost last) over a new leaf, remapping
    their conditions when the leaf schema changed."""
    node = leaf
    for f in reversed(filters):
        cond = f.condition
        if remap is not None:
            cond = transform_expr(cond, remap)
        node = L.Filter(node, cond)
    return node


# Narrowed-arrow-table memo: pa.Table.select is zero-copy but creates a
# NEW object each call, and the device scan cache (exec/basic.py) keys
# on table identity — without this memo every execution of a pruned plan
# would re-transfer H2D.  Entries die with their parent table.
_narrow_memo: dict = {}


def _narrow_table(table, names: Tuple[str, ...]):
    import weakref
    key = (id(table), names)
    hit = _narrow_memo.get(key)
    if hit is not None:
        return hit
    out = table.select(list(names))
    try:
        weakref.finalize(table, _narrow_memo.pop, key, None)
    except TypeError:
        return out
    _narrow_memo[key] = out
    return out


def _prune_inmemory(rel: L.InMemoryRelation, required: Set[int]):
    """Narrowed in-memory relation + old→new index map.  The H2D analog
    of parquet projection pushdown [REF: Spark's ColumnPruning +
    InMemoryTableScanExec partition pruning — here the win is not
    transferring unused columns through the host↔device tunnel]."""
    fields = rel.schema.fields
    if not required:
        required = {0}
    keep = sorted(required)
    index_map = {old: new for new, old in enumerate(keep)}
    names = tuple(fields[i].name for i in keep)
    new_rel = dataclasses.replace(
        rel, table=_narrow_table(rel.table, names),
        schema=T.StructType(tuple(fields[i] for i in keep)))
    return new_rel, index_map


def _prune_relation(rel: L.ParquetRelation, required: Set[int],
                    need_file_name: bool):
    """Narrowed relation + old→new index map."""
    fields = rel.schema.fields
    n_data = (len(fields) - len(rel.partition_fields)
              - (1 if rel.file_name_col else 0))
    if n_data and not any(i < n_data for i in required):
        # partition-only / count(*) shapes: always read ≥1 data column —
        # ORC's reader loses the row count on a zero-column read
        required = set(required) | {0}
    keep = sorted(required)
    index_map = {old: new for new, old in enumerate(keep)}
    new_fields = [fields[i] for i in keep]
    columns = [fields[i].name for i in keep if i < n_data]
    part_fields = tuple(fields[i] for i in keep
                        if n_data <= i < n_data + len(rel.partition_fields))
    file_name_col = rel.file_name_col or need_file_name
    if file_name_col:
        new_fields.append(T.StructField("input_file_name()", T.StringT,
                                        False))
        fn_idx = len(new_fields) - 1
    else:
        fn_idx = None
    new_rel = dataclasses.replace(
        rel, schema=T.StructType(tuple(new_fields)), columns=columns,
        partition_fields=part_fields, file_name_col=file_name_col)
    return new_rel, index_map, fn_idx


def _make_remap(index_map, fn_idx):
    def remap(e):
        if isinstance(e, E.BoundReference):
            return E.BoundReference(index_map[e.index], e.dtype,
                                    e.nullable)
        if isinstance(e, E.InputFileName):
            if fn_idx is None:
                return None
            return E.BoundReference(fn_idx, T.StringT, False)
        return None
    return remap


def _head_required_refs(plan, filters) -> Tuple[List, Set[int]]:
    """(head exprs, referenced column indexes) of a Project|Aggregate
    head over a Filter* chain — shared by the parquet and in-memory
    pruning rules so the two can never disagree on required columns."""
    if isinstance(plan, L.Project):
        head_exprs = list(plan.exprs)
    else:
        head_exprs = (list(plan.grouping)
                      + [f.child for f in plan.aggregates
                         if getattr(f, "child", None) is not None])
    required: Set[int] = set()
    for e in head_exprs:
        collect_refs(e, required)
    for f in filters:
        collect_refs(f.condition, required)
    return head_exprs, required


def _inmemory_prune_head(plan) -> Optional[L.LogicalPlan]:
    """Project|Aggregate → Filter* → InMemoryRelation: narrow the arrow
    table to referenced columns before the H2D transfer."""
    filters = []
    node = plan.child
    while isinstance(node, L.Filter):
        filters.append(node)
        node = node.child
    if not isinstance(node, L.InMemoryRelation):
        return None
    head_exprs, required = _head_required_refs(plan, filters)
    if len(required) >= len(node.schema.fields):
        return None
    if _has_file_name_marker(head_exprs):
        return None
    new_rel, index_map = _prune_inmemory(node, required)
    remap = _make_remap(index_map, None)
    child = _rebuild_chain(filters, new_rel, remap)
    if isinstance(plan, L.Project):
        exprs = [transform_expr(e, remap) for e in plan.exprs]
        return L.Project(child, exprs, plan.schema)
    grouping = [transform_expr(e, remap) for e in plan.grouping]
    aggs = [transform_expr(a, remap) for a in plan.aggregates]
    return L.Aggregate(child, grouping, aggs, plan.schema)


def optimize(plan: L.LogicalPlan, conf=None) -> L.LogicalPlan:
    plan = _rewrite_children(plan, conf)

    if isinstance(plan, (L.Project, L.Aggregate)):
        mem = _inmemory_prune_head(plan)
        if mem is not None:
            return mem
        filters, rel = _filter_chain(plan.child)
        # the inner Filter rule may already have attached row-group
        # filters (bottom-up order) — pruning only needs columns unset
        if rel is not None and rel.columns is None:
            head_exprs, required = _head_required_refs(plan, filters)
            need_fn = isinstance(plan, L.Project) and _has_file_name_marker(
                head_exprs)
            pushed = rel.filters
            if pushed is None:
                pushed = []
                for f in filters:
                    pushed.extend(_extract_filters(f.condition, rel))
            new_rel, index_map, fn_idx = _prune_relation(
                rel, required, need_fn)
            if pushed:
                new_rel = dataclasses.replace(new_rel, filters=pushed)
            remap = _make_remap(index_map, fn_idx)
            child = _rebuild_chain(filters, new_rel, remap)
            if isinstance(plan, L.Project):
                exprs = [transform_expr(e, remap) for e in plan.exprs]
                return L.Project(child, exprs, plan.schema)
            grouping = [transform_expr(e, remap) for e in plan.grouping]
            aggs = [transform_expr(a, remap) for a in plan.aggregates]
            return L.Aggregate(child, grouping, aggs, plan.schema)

    if isinstance(plan, L.Filter):
        filters, rel = _filter_chain(plan)
        if rel is not None and rel.filters is None:
            pushed = []
            for f in filters:
                pushed.extend(_extract_filters(f.condition, rel))
            if pushed:
                new_rel = dataclasses.replace(rel, filters=pushed)
                return _rebuild_chain(filters, new_rel)

    if isinstance(plan, L.Join):
        from spark_rapids_tpu import conf as C
        if conf is None or conf.get(C.DPP_ENABLED):
            threshold = (conf.get(C.BROADCAST_THRESHOLD) if conf
                         else 10 << 20)
            plan = _dynamic_partition_pruning(plan, threshold)

    return plan


def _estimated_plan_bytes(plan) -> Optional[int]:
    """Rough output-size upper bound of a logical plan (None=unknown)."""
    import os
    if isinstance(plan, L.InMemoryRelation):
        return plan.table.nbytes
    if isinstance(plan, L.ParquetRelation):
        try:
            return sum(os.path.getsize(p) for p in plan.paths) * 4
        except OSError:
            return None
    if isinstance(plan, (L.Filter, L.Project, L.Sample, L.Limit,
                         L.Sort)):
        return _estimated_plan_bytes(plan.children[0])
    return None


def _dynamic_partition_pruning(join: L.Join,
                               threshold: int) -> L.Join:
    """Attach a DPP subquery to a partitioned probe-side scan.

    [REF: GpuSubqueryBroadcastExec / DPP integration, SURVEY §2.1 #26]
    When one join side is a hive-partitioned file relation whose join
    key IS a partition column, the other side's distinct keys (computed
    once, host-side, before the scan pumps) prune entire files.  Valid
    for join types that drop probe rows without a match."""
    candidates = []
    if join.join_type in ("inner", "left_semi", "right"):
        candidates.append(("left", join.left, join.left_keys,
                           join.right, join.right_keys))
    if join.join_type in ("inner", "left"):
        candidates.append(("right", join.right, join.right_keys,
                           join.left, join.left_keys))
    for side, probe, probe_keys, build, build_keys in candidates:
        # column pruning may have left a Project head over the (already
        # narrowed) relation — peel it and map key indices through its
        # exprs; _prune_relation preserves the [data..., partition...,
        # file_name] layout, so the index math below still holds (a
        # Project head between scan and join used to disable DPP
        # entirely — missed file pruning)
        proj = None
        inner = probe
        if isinstance(inner, L.Project):
            proj = inner
            inner = inner.child
        filters, rel = _filter_chain(inner)
        if (rel is None or not rel.partition_values
                or rel.dpp is not None):
            continue
        # the subquery executes host-side before the scan pumps — only
        # worth it (and only safe) for broadcast-sized build sides, the
        # same gate Spark uses for DPP-without-broadcast-reuse
        est = _estimated_plan_bytes(build)
        if threshold <= 0 or est is None or est > threshold:
            continue
        n_data = (len(rel.schema.fields) - len(rel.partition_fields)
                  - (1 if rel.file_name_col else 0))
        for ki, key in enumerate(probe_keys):
            if not isinstance(key, E.BoundReference):
                continue
            if proj is not None:
                key = proj.exprs[key.index]
                if not isinstance(key, E.BoundReference):
                    continue
            if not (n_data <= key.index
                    < n_data + len(rel.partition_fields)):
                continue
            col_name = rel.schema.fields[key.index].name
            bkey = build_keys[ki]
            sub = L.Project(
                build, [bkey],
                T.StructType((T.StructField("_dpp_key", bkey.dtype),)))
            new_rel = dataclasses.replace(rel, dpp=(sub, col_name))
            new_probe = _rebuild_chain(filters, new_rel)
            if proj is not None:
                new_probe = dataclasses.replace(proj, child=new_probe)
            if side == "left":
                return dataclasses.replace(join, left=new_probe)
            return dataclasses.replace(join, right=new_probe)
    return join


def _rewrite_children(plan: L.LogicalPlan, conf=None) -> L.LogicalPlan:
    if isinstance(plan, L.Union):
        return L.Union([optimize(c, conf) for c in plan.inputs])
    if isinstance(plan, L.Join):
        return dataclasses.replace(plan, left=optimize(plan.left, conf),
                                   right=optimize(plan.right, conf))
    if hasattr(plan, "child"):
        return dataclasses.replace(plan, child=optimize(plan.child, conf))
    return plan
