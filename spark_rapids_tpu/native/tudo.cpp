// tudo — the kudo-analog columnar shuffle wire format, C++ hot path.
//
// [REF: NVIDIA/spark-rapids-jni :: src/main/cpp/src/kudo/ — KudoSerializer,
//  a partitioned-write columnar wire format for shuffle]
//
// TPU re-design notes: kudo serializes cuDF device tables; here the
// serializer runs on HOST buffers (TPU shuffle data crosses the host on
// the MULTITHREADED path — the device path is the ICI collective), so the
// hot loop is a per-partition row gather from host column arrays into one
// contiguous output buffer per partition.  The format is laid out so the
// *reader* needs no native code at all: every section is a contiguous
// dtype run that numpy can view with frombuffer (zero-copy deserialize).
//
// Layout per partition buffer (little-endian, no alignment padding):
//   [u32 magic 'TUD0'][u32 version=1][i64 nrows][u32 ncols]
//   per column:
//     [u8 kind: 0=fixed 1=string][u8 has_validity][u16 itemsize]
//     fixed : [data nrows*itemsize]
//     string: [lengths nrows*i32][bytes sum(lengths)]
//     if has_validity: [validity nrows u8]
//
// Exposed C ABI (ctypes):
//   tudo_partition_sizes   — pass 1: exact byte size per partition
//   tudo_partition_write   — pass 2: gather+serialize, threaded over
//                            partitions (spark.rapids.shuffle.
//                            multiThreaded.writer.threads)

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

struct ColDesc {
  const uint8_t* data;      // fixed: nrows*itemsize; string: byte matrix
  const uint8_t* validity;  // u8 per row (1=valid) or null
  const int32_t* lengths;   // string: byte length per row, else null
  int32_t kind;             // 0=fixed width, 1=string (padded byte matrix)
  int32_t itemsize;         // fixed: element bytes; string: matrix width
};

static const uint32_t MAGIC = 0x30445554u;  // "TUD0"

static int64_t header_size(int ncols) {
  return 4 + 4 + 8 + 4 + (int64_t)ncols * 4;
}

// exact serialized size of one partition (rows selected by idx[lo..hi))
static int64_t part_size(int ncols, const ColDesc* cols,
                         const int32_t* idx, int64_t n) {
  int64_t sz = header_size(ncols);
  for (int c = 0; c < ncols; ++c) {
    const ColDesc& col = cols[c];
    if (col.kind == 0) {
      sz += n * (int64_t)col.itemsize;
    } else {
      sz += n * 4;  // lengths
      for (int64_t i = 0; i < n; ++i) sz += col.lengths[idx[i]];
    }
    if (col.validity) sz += n;
  }
  return sz;
}

static void write_part(int ncols, const ColDesc* cols, const int32_t* idx,
                       int64_t n, uint8_t* out) {
  uint8_t* p = out;
  std::memcpy(p, &MAGIC, 4); p += 4;
  uint32_t ver = 1; std::memcpy(p, &ver, 4); p += 4;
  int64_t nr = n; std::memcpy(p, &nr, 8); p += 8;
  uint32_t nc = (uint32_t)ncols; std::memcpy(p, &nc, 4); p += 4;
  for (int c = 0; c < ncols; ++c) {
    const ColDesc& col = cols[c];
    uint8_t kind = (uint8_t)col.kind;
    uint8_t hasv = col.validity ? 1 : 0;
    uint16_t isz = (uint16_t)col.itemsize;
    std::memcpy(p, &kind, 1); p += 1;
    std::memcpy(p, &hasv, 1); p += 1;
    std::memcpy(p, &isz, 2); p += 2;
  }
  for (int c = 0; c < ncols; ++c) {
    const ColDesc& col = cols[c];
    if (col.kind == 0) {
      const int64_t isz = col.itemsize;
      switch (isz) {  // common widths get tight loops
        case 1:
          for (int64_t i = 0; i < n; ++i) p[i] = col.data[idx[i]];
          p += n;
          break;
        case 4: {
          uint32_t* o = (uint32_t*)p;
          const uint32_t* d = (const uint32_t*)col.data;
          for (int64_t i = 0; i < n; ++i) o[i] = d[idx[i]];
          p += n * 4;
          break;
        }
        case 8: {
          uint64_t* o = (uint64_t*)p;
          const uint64_t* d = (const uint64_t*)col.data;
          for (int64_t i = 0; i < n; ++i) o[i] = d[idx[i]];
          p += n * 8;
          break;
        }
        default:
          for (int64_t i = 0; i < n; ++i)
            std::memcpy(p + i * isz, col.data + (int64_t)idx[i] * isz, isz);
          p += n * isz;
      }
    } else {
      int32_t* lens = (int32_t*)p;
      for (int64_t i = 0; i < n; ++i) lens[i] = col.lengths[idx[i]];
      p += n * 4;
      const int64_t width = col.itemsize;  // padded matrix row stride
      for (int64_t i = 0; i < n; ++i) {
        const int32_t len = col.lengths[idx[i]];
        std::memcpy(p, col.data + (int64_t)idx[i] * width, len);
        p += len;
      }
    }
    if (col.validity) {
      for (int64_t i = 0; i < n; ++i) p[i] = col.validity[idx[i]];
      p += n;
    }
  }
}

// pass 0: bucket rows by partition id → per-partition row-index lists.
// Returns counts; fills idx_out (size nrows) ordered by partition with
// starts[] giving each partition's slice (counting sort, stable).
void tudo_bucket_rows(const int32_t* pids, const uint8_t* live,
                      int64_t nrows, int32_t nparts,
                      int32_t* idx_out, int64_t* starts /* nparts+1 */) {
  std::vector<int64_t> counts(nparts, 0);
  for (int64_t i = 0; i < nrows; ++i)
    if (!live || live[i]) ++counts[pids[i]];
  starts[0] = 0;
  for (int32_t p = 0; p < nparts; ++p) starts[p + 1] = starts[p] + counts[p];
  std::vector<int64_t> cur(starts, starts + nparts);
  for (int64_t i = 0; i < nrows; ++i)
    if (!live || live[i]) idx_out[cur[pids[i]]++] = (int32_t)i;
}

void tudo_partition_sizes(int ncols, const ColDesc* cols,
                          const int32_t* idx, const int64_t* starts,
                          int32_t nparts, int64_t* sizes_out) {
  for (int32_t p = 0; p < nparts; ++p)
    sizes_out[p] = part_size(ncols, cols, idx + starts[p],
                             starts[p + 1] - starts[p]);
}

void tudo_partition_write(int ncols, const ColDesc* cols,
                          const int32_t* idx, const int64_t* starts,
                          int32_t nparts, uint8_t* out,
                          const int64_t* out_offsets, int32_t nthreads) {
  if (nthreads < 1) nthreads = 1;
  if (nthreads > nparts) nthreads = nparts;
  std::vector<std::thread> pool;
  pool.reserve(nthreads);
  for (int32_t t = 0; t < nthreads; ++t) {
    pool.emplace_back([=]() {
      for (int32_t p = t; p < nparts; p += nthreads)
        write_part(ncols, cols, idx + starts[p],
                   starts[p + 1] - starts[p], out + out_offsets[p]);
    });
  }
  for (auto& th : pool) th.join();
}

// ---------------------------------------------------------------------------
// Scatter path: one streaming pass per column section instead of a
// per-partition random gather.  A gather reads source rows in
// permutation order — every 8-byte load pulls a fresh cache line and
// uses 8 of its 64 bytes; the scatter reads the source SEQUENTIALLY
// (full cache-line utilization, hardware prefetch) and appends to one
// write cursor per partition (nparts open cache lines — fine for the
// 16-64 partitions shuffles use).  Measured 3-4x on the single-core
// hosts this runs on, where thread-pooling the gather can't help.
// Wire format identical to write_part (the reader can't tell).
//
// work layout (int64): [counts nparts][strbytes ncols*nparts]
// ---------------------------------------------------------------------------

void tudo_scatter_sizes(int ncols, const ColDesc* cols,
                        const int32_t* pids, const uint8_t* live,
                        int64_t nrows, int32_t nparts,
                        int64_t* sizes_out, int64_t* work) {
  int64_t* counts = work;
  int64_t* strbytes = work + nparts;
  for (int32_t p = 0; p < nparts; ++p) counts[p] = 0;
  for (int64_t i = 0; i < (int64_t)ncols * nparts; ++i) strbytes[i] = 0;
  for (int64_t i = 0; i < nrows; ++i)
    if (!live || live[i]) ++counts[pids[i]];
  for (int c = 0; c < ncols; ++c) {
    if (cols[c].kind != 1) continue;
    int64_t* sb = strbytes + (int64_t)c * nparts;
    const int32_t* lens = cols[c].lengths;
    for (int64_t i = 0; i < nrows; ++i)
      if (!live || live[i]) sb[pids[i]] += lens[i];
  }
  for (int32_t p = 0; p < nparts; ++p) {
    int64_t sz = header_size(ncols);
    for (int c = 0; c < ncols; ++c) {
      const ColDesc& col = cols[c];
      if (col.kind == 0) {
        sz += counts[p] * (int64_t)col.itemsize;
      } else {
        sz += counts[p] * 4 + strbytes[(int64_t)c * nparts + p];
      }
      if (col.validity) sz += counts[p];
    }
    sizes_out[p] = sz;
  }
}

void tudo_scatter_write(int ncols, const ColDesc* cols,
                        const int32_t* pids, const uint8_t* live,
                        int64_t nrows, int32_t nparts, uint8_t* out,
                        const int64_t* out_offsets, const int64_t* work) {
  const int64_t* counts = work;
  const int64_t* strbytes = work + nparts;
  // headers + per-(partition) section cursor table
  std::vector<uint8_t*> cursor((size_t)nparts);
  for (int32_t p = 0; p < nparts; ++p) {
    uint8_t* o = out + out_offsets[p];
    std::memcpy(o, &MAGIC, 4); o += 4;
    uint32_t ver = 1; std::memcpy(o, &ver, 4); o += 4;
    int64_t nr = counts[p]; std::memcpy(o, &nr, 8); o += 8;
    uint32_t nc = (uint32_t)ncols; std::memcpy(o, &nc, 4); o += 4;
    for (int c = 0; c < ncols; ++c) {
      const ColDesc& col = cols[c];
      uint8_t kind = (uint8_t)col.kind;
      uint8_t hasv = col.validity ? 1 : 0;
      uint16_t isz = (uint16_t)col.itemsize;
      std::memcpy(o, &kind, 1); o += 1;
      std::memcpy(o, &hasv, 1); o += 1;
      std::memcpy(o, &isz, 2); o += 2;
    }
    cursor[p] = o;
  }
  std::vector<uint8_t*> cur((size_t)nparts);
  std::vector<uint8_t*> bytes_cur((size_t)nparts);
  for (int c = 0; c < ncols; ++c) {
    const ColDesc& col = cols[c];
    if (col.kind == 0) {
      const int64_t isz = col.itemsize;
      for (int32_t p = 0; p < nparts; ++p) cur[p] = cursor[p];
      switch (isz) {
        case 1:
          for (int64_t i = 0; i < nrows; ++i)
            if (!live || live[i]) *cur[pids[i]]++ = col.data[i];
          break;
        case 4: {
          const uint32_t* d = (const uint32_t*)col.data;
          for (int64_t i = 0; i < nrows; ++i)
            if (!live || live[i]) {
              uint8_t*& cp = cur[pids[i]];
              *(uint32_t*)cp = d[i];
              cp += 4;
            }
          break;
        }
        case 8: {
          const uint64_t* d = (const uint64_t*)col.data;
          for (int64_t i = 0; i < nrows; ++i)
            if (!live || live[i]) {
              uint8_t*& cp = cur[pids[i]];
              *(uint64_t*)cp = d[i];
              cp += 8;
            }
          break;
        }
        default:
          for (int64_t i = 0; i < nrows; ++i)
            if (!live || live[i]) {
              uint8_t*& cp = cur[pids[i]];
              std::memcpy(cp, col.data + i * isz, isz);
              cp += isz;
            }
      }
      for (int32_t p = 0; p < nparts; ++p)
        cursor[p] += counts[p] * isz;
    } else {
      // lengths section, then the variable bytes section
      for (int32_t p = 0; p < nparts; ++p) {
        cur[p] = cursor[p];
        bytes_cur[p] = cursor[p] + counts[p] * 4;
      }
      const int64_t width = col.itemsize;
      const int32_t* lens = col.lengths;
      for (int64_t i = 0; i < nrows; ++i)
        if (!live || live[i]) {
          const int32_t pp = pids[i];
          const int32_t len = lens[i];
          *(int32_t*)cur[pp] = len;
          cur[pp] += 4;
          std::memcpy(bytes_cur[pp], col.data + i * width, len);
          bytes_cur[pp] += len;
        }
      for (int32_t p = 0; p < nparts; ++p)
        cursor[p] += counts[p] * 4 + strbytes[(int64_t)c * nparts + p];
    }
    if (col.validity) {
      for (int32_t p = 0; p < nparts; ++p) cur[p] = cursor[p];
      for (int64_t i = 0; i < nrows; ++i)
        if (!live || live[i]) *cur[pids[i]]++ = col.validity[i];
      for (int32_t p = 0; p < nparts; ++p) cursor[p] += counts[p];
    }
  }
}

}  // extern "C"
