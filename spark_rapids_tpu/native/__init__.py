"""Native (C++) runtime components, loaded via ctypes.

SURVEY §2.2: the reference's serializers/runtime are native; the build
mandate is "tpu-native equivalents in C++, not Python-only wrappers".
Libraries compile on demand with the baked-in g++ toolchain and cache as
shared objects next to the sources (or under $SPARK_RAPIDS_TPU_NATIVE_DIR).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_lock = threading.Lock()
_libs = {}

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))


def _build_dir() -> str:
    d = os.environ.get("SPARK_RAPIDS_TPU_NATIVE_DIR")
    if not d:
        d = os.path.join(os.path.expanduser("~"), ".cache",
                         "spark_rapids_tpu", "native")
    os.makedirs(d, exist_ok=True)
    return d


def load_library(name: str) -> Optional[ctypes.CDLL]:
    """Compile (once) and dlopen lib<name>.so from <name>.cpp.

    Returns None when no C++ toolchain is available — callers must keep a
    Python fallback path and flag themselves non-accelerated."""
    with _lock:
        if name in _libs:
            return _libs[name]
        src = os.path.join(_SRC_DIR, f"{name}.cpp")
        out = os.path.join(_build_dir(), f"lib{name}.so")
        try:
            if (not os.path.exists(out)
                    or os.path.getmtime(out) < os.path.getmtime(src)):
                cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
                       "-std=c++17", "-pthread", src, "-o", out + ".tmp"]
                subprocess.run(cmd, check=True, capture_output=True)
                os.replace(out + ".tmp", out)
            lib = ctypes.CDLL(out)
        except (OSError, subprocess.CalledProcessError):
            lib = None
        _libs[name] = lib
        return lib
