"""The adaptive execution plane — stats-driven replanning.

PAPER.md's reference accelerator leans on Spark AQE to pick join
strategies and heal skew at runtime; PR 7's stats plane gave this
engine the measurement half (cluster-merged rows/bytes/per-partition
sizes with skew factors keyed by stable plan signatures), and this
package is the half that SPENDS those stats: a cost model + replanner
that rewrites the physical plan at stage boundaries.

Three decisions, each conf-gated under ``spark.rapids.tpu.adaptive.*``:

* **join strategy** (``joinStrategy.enabled``) — broadcast vs
  shuffled-hash per join from observed build-side cardinality:
  profile-store history for warm queries, upstream pump counts for
  cold ones.  A build side that fits the broadcast threshold
  eliminates the exchange entirely (exec/join.py
  ``TpuAdaptiveLocalJoinExec``).
* **skew splitting** (``skewSplit.enabled``) — when an exchange's
  recorded skew factor exceeds the threshold, split the hot stream
  partition(s) into rank-interleaved sub-partitions and replicate the
  build side's matching partition (exec/join.py partitioned
  ``TpuSortMergeJoinExec``).  This spreads a SINGLE hot key — the one
  case hash sub-partitioning provably cannot.
* **batch retargeting** (``batchRetarget.enabled``) — the AQE shuffle
  read replans its coalesce/split target from observed bytes/row
  instead of the static schema estimate, snapped to the shape plane's
  bucket ladder (exec/aqe.py).

Purity contract (enforced by the ``adaptive-purity`` lint rule): code
in this package decides from RECORDED stats, history, and conf only —
never a fresh device sync.  Anything that must touch the device to
measure (gathering a build side, counting partition rows) lives in the
exec layer, which hands the numbers in.

Every decision taken is recorded on the deciding exec node in the
stats plane (so it flows into EXPLAIN ANALYZE ``adaptive=...``
annotations, the event log, profile-store records, and bench
TPCH_SF1_STATS) and counted in ``tpuq_adaptive_decisions_total{kind}``.
"""

from __future__ import annotations

import dataclasses

from spark_rapids_tpu.runtime import stats
from spark_rapids_tpu.runtime import telemetry as TM

_TM_DECISIONS = TM.REGISTRY.labeled_counter(
    "tpuq_adaptive_decisions_total",
    "adaptive-plane replanning decisions applied, by kind "
    "(broadcast / shuffled / skew-split / batch-retarget)",
    label="kind")


@dataclasses.dataclass(frozen=True)
class AdaptivePolicy:
    """One immutable adaptive policy (the conf snapshot, parsed).

    Built per query at plan-conversion time (``policy_from_conf``) so
    per-query conf overrides land in the plan that query runs, same as
    every other planner input."""

    enabled: bool = False
    join_strategy: bool = True
    skew_split: bool = True
    batch_retarget: bool = True
    skew_threshold: float = 2.0        # hottest/mean, resolved (never 0)
    max_splits: int = 8                # fan-out cap per hot partition
    target_rows: int = 1 << 18         # sub-partition row goal
    broadcast_threshold: int = 10 << 20
    history_path: str = ""             # "" = no warm-query history

    @property
    def wants_join(self) -> bool:
        return self.enabled and self.join_strategy

    @property
    def wants_skew(self) -> bool:
        return self.enabled and self.skew_split

    @property
    def wants_retarget(self) -> bool:
        return self.enabled and self.batch_retarget


def policy_from_conf(conf) -> AdaptivePolicy:
    """Parse a RapidsConf into an AdaptivePolicy snapshot."""
    from spark_rapids_tpu import conf as C
    skew = float(conf.get(C.ADAPTIVE_SKEW_THRESHOLD))
    if skew <= 0:  # 0 = inherit the stats plane's skew flagging bar
        skew = float(conf.get(C.STATS_SKEW_THRESHOLD))
    thresh = conf.get(C.BROADCAST_THRESHOLD)
    return AdaptivePolicy(
        enabled=bool(conf.get(C.ADAPTIVE_PLANE_ENABLED)),
        join_strategy=bool(conf.get(C.ADAPTIVE_JOIN_STRATEGY)),
        skew_split=bool(conf.get(C.ADAPTIVE_SKEW_SPLIT)),
        batch_retarget=bool(conf.get(C.ADAPTIVE_BATCH_RETARGET)),
        skew_threshold=skew,
        max_splits=int(conf.get(C.ADAPTIVE_MAX_SPLITS)),
        target_rows=int(conf.get(C.JOIN_TARGET_ROWS)),
        broadcast_threshold=int(thresh) if thresh else 0,
        history_path=(str(conf.get(C.ADAPTIVE_HISTORY_PATH))
                      or str(conf.get(C.STATS_STORE_PATH))))


def record_decision(node, kind: str, **detail) -> None:
    """Count one applied decision and attach it to the deciding exec
    node's stats record (rendered by EXPLAIN ANALYZE and rolled up
    into the query profile's ``adaptive_decisions``).

    Exec nodes constructed at runtime (the replanner's rewritten
    subtree) are invisible to the plan walk, so they forward to a
    ``_decision_owner`` — the adaptive node that IS in the plan."""
    _TM_DECISIONS.labels(kind).inc()
    owner = getattr(node, "_decision_owner", node)
    st = stats.current()
    if st is not None:
        st.record_decision(owner, kind, detail)
