"""Replanner: decision orchestration for the adaptive plane.

Each function takes an ``AdaptivePolicy`` plus numbers the exec layer
already holds (recorded partition counts, observed build bytes) and
returns the decision the exec node should APPLY, together with the
triggering stat — the dict that ``adaptive.record_decision`` attaches
to the plan node, so every decision is explainable from its inputs.

Pure by contract (``adaptive-purity`` lint): decisions come from
recorded stats, history, and conf — never a fresh device sync.  The
exec layer measures; this module decides; the exec layer applies.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from spark_rapids_tpu.adaptive import AdaptivePolicy, cost_model
from spark_rapids_tpu.runtime import stats

# Join types for which a stream-side row can be decided independently
# against a fully present build side — the correctness condition for
# both broadcast-streaming AND rank-interleaved skew splitting (each
# stream slice sees the WHOLE matching build partition).
STREAMABLE_JOINS = ("inner", "left", "left_semi", "left_anti")


def decide_join_from_history(pol: AdaptivePolicy, build_sig: str
                             ) -> Optional[Tuple[str, Dict]]:
    """Warm path: (strategy, trigger detail) from the profile store's
    most recent build-side measurement for this plan signature, or
    None when there is no usable history (caller then measures)."""
    if not pol.wants_join or pol.broadcast_threshold <= 0:
        return None
    hist = cost_model.history_build_bytes(pol.history_path, build_sig)
    if hist is None:
        return None
    strategy = cost_model.choose_join_strategy(hist,
                                               pol.broadcast_threshold)
    return strategy, {"build_bytes": hist,
                      "threshold": pol.broadcast_threshold,
                      "build_sig": build_sig,
                      "source": "history"}


def decide_join_from_measurement(pol: AdaptivePolicy, build_sig: str,
                                 build_bytes: int) -> Tuple[str, Dict]:
    """Cold path: (strategy, trigger detail) from build-side bytes the
    exec layer measured off the upstream pump."""
    strategy = cost_model.choose_join_strategy(build_bytes,
                                               pol.broadcast_threshold)
    return strategy, {"build_bytes": int(build_bytes),
                      "threshold": pol.broadcast_threshold,
                      "build_sig": build_sig,
                      "source": "measured"}


def plan_skew_reads(pol: AdaptivePolicy, join_type: str,
                    counts: Sequence[int]
                    ) -> Optional[Tuple[List[Tuple[int, int, int]], Dict]]:
    """Skew-healing read plan for a partitioned join's stream side.

    Returns (specs, trigger detail) where specs is one ``(p, j, k)``
    per output partition — slice j of k over exchange partition p
    (k == 1 for partitions read whole) — or None when nothing is hot
    enough to split (the join keeps its 1:1 partition mapping)."""
    if not pol.wants_skew or join_type not in STREAMABLE_JOINS:
        return None
    splits = cost_model.plan_skew_splits(
        counts, pol.skew_threshold, pol.target_rows, pol.max_splits)
    if not splits:
        return None
    specs: List[Tuple[int, int, int]] = []
    for p in range(len(counts)):
        k = splits.get(p, 1)
        specs.extend((p, j, k) for j in range(k))
    detail = {"partitions": sorted(splits),
              "splits": [splits[p] for p in sorted(splits)],
              "skew_factor": round(stats.skew_factor(counts), 4),
              "threshold": pol.skew_threshold,
              "rows": [int(counts[p]) for p in sorted(splits)]}
    return specs, detail


def retarget_read_rows(pol: AdaptivePolicy, target_bytes: int,
                       static_row_bytes: int, observed_rows: int,
                       observed_bytes: int
                       ) -> Optional[Tuple[int, Dict]]:
    """(new row target, trigger detail) for an AQE shuffle read, from
    observed bytes/row — snapped to the shape plane's bucket ladder so
    coalesce targets land on compile-cached batch shapes — or None
    when the static estimate was close enough (or nothing observed)."""
    if not pol.wants_retarget:
        return None
    rows = cost_model.retarget_rows(target_bytes, observed_rows,
                                    observed_bytes, static_row_bytes)
    if rows is None:
        return None
    from spark_rapids_tpu.runtime import shapes
    target = shapes.retarget_bucket(rows)
    detail = {"target_rows": target,
              "static_row_bytes": int(static_row_bytes),
              "observed_row_bytes": round(observed_bytes
                                          / max(observed_rows, 1), 2),
              "observed_rows": int(observed_rows)}
    return target, detail
