"""Cost model: the numeric half of the adaptive plane.

Pure functions over numbers the caller already holds — recorded
partition counts, observed byte totals, conf thresholds.  Nothing here
touches the device (the ``adaptive-purity`` lint rule enforces it);
measurement lives in the exec layer, history lives in the stats
plane's JSONL profile store.
"""

from __future__ import annotations

import hashlib
from typing import Dict, Optional, Sequence

from spark_rapids_tpu.runtime import stats


# -- join strategy -----------------------------------------------------------

def choose_join_strategy(build_bytes: int, threshold: int) -> str:
    """"broadcast" when the observed build side fits the broadcast
    threshold (the exchange disappears), else "shuffled"."""
    if threshold > 0 and build_bytes <= threshold:
        return "broadcast"
    return "shuffled"


def subtree_signature(node) -> str:
    """Stable signature of a physical subtree — op names + schema
    fields in pre-order, same recipe as ``stats.plan_signature`` but
    covering the whole subtree so it identifies a join's build side
    across runs of the same query shape."""
    parts = []

    def walk(n, path):
        try:
            fields = ",".join(n.schema.field_names())
        except Exception:
            fields = ""
        parts.append(f"{path}/{n.name}({fields})")
        for i, c in enumerate(getattr(n, "children", ()) or ()):
            walk(c, f"{path}.{i}")

    walk(node, "0")
    return hashlib.sha1("|".join(parts).encode()).hexdigest()[:12]


def history_build_bytes(path: str, build_sig: str) -> Optional[int]:
    """Most recent recorded build-side bytes for a join signature from
    the profile store — the warm-query path: a query shape seen before
    decides its strategy without re-measuring.  None when the store is
    unset, unreadable, or has no record for this signature."""
    if not path or not build_sig:
        return None
    try:
        records = stats.load_profiles(path)
    except Exception:
        return None
    for rec in reversed(records):
        for d in rec.get("adaptive_decisions", ()) or ():
            if (d.get("build_sig") == build_sig
                    and d.get("build_bytes") is not None):
                return int(d["build_bytes"])
    return None


# -- skew splitting ----------------------------------------------------------

def plan_skew_splits(counts: Sequence[int], skew_threshold: float,
                     target_rows: int, max_splits: int) -> Dict[int, int]:
    """{partition: k} for partitions hot enough to split.

    A partition is hot when it exceeds ``skew_threshold`` x the mean
    (the stats plane's skew-factor definition, applied per partition)
    AND holds more than ``target_rows`` rows — tiny-but-lopsided
    exchanges are not worth the replication cost.  k aims each slice
    at ``target_rows``, capped at ``max_splits``."""
    counts = [int(c) for c in counts]
    total = sum(counts)
    if total <= 0 or not counts:
        return {}
    mean = total / len(counts)
    out: Dict[int, int] = {}
    for p, c in enumerate(counts):
        if c > skew_threshold * mean and c > target_rows:
            k = min(int(max_splits), -(-c // max(int(target_rows), 1)))
            if k >= 2:
                out[p] = k
    return out


# -- batch retargeting -------------------------------------------------------

# Observed bytes/row must disagree with the static estimate by at
# least this ratio before a retarget is worth a decision record — the
# schema estimate is already right for fixed-width rows.
RETARGET_MIN_RATIO = 1.25


def retarget_rows(target_bytes: int, observed_rows: int,
                  observed_bytes: int, static_row_bytes: int
                  ) -> Optional[int]:
    """Row target from OBSERVED bytes/row, or None when the static
    estimate is already within ``RETARGET_MIN_RATIO`` of reality (or
    nothing was observed)."""
    if observed_rows <= 0 or observed_bytes <= 0:
        return None
    bpr = max(observed_bytes / observed_rows, 1.0)
    est = max(int(static_row_bytes), 1)
    ratio = bpr / est if bpr > est else est / bpr
    if ratio < RETARGET_MIN_RATIO:
        return None
    return max(int(target_bytes // bpr), 1)
