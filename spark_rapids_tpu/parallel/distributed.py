"""Distributed query step: the full SPMD shuffle+aggregate pipeline.

One jitted program per shuffle stage (SURVEY §5.8): each device holds a
row shard; the step hash-partitions rows with the bit-exact Spark murmur3,
exchanges slices over the mesh with ``lax.all_to_all`` (ICI on hardware),
and finishes with the local sort-based groupby.  This is the
collective-only inversion of the reference's p2p UCX shuffle
[REF: RapidsShuffleInternalManagerBase.scala, GpuHashPartitioning.scala].
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.ops import hashing as HH


def _local_partition(keys: jnp.ndarray, values: jnp.ndarray,
                     sel: jnp.ndarray, num_parts: int
                     ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Bucket local rows by murmur3(key) % num_parts into a [P, C] layout.

    C = local capacity; slots beyond each partition's fill are dead
    (valid=False).  Static shapes throughout: this is the device-side
    GpuHashPartitioning analog.
    """
    b = keys.shape[0]
    h = HH.hash_column(
        (keys.astype(jnp.int64), None), T.LongT,
        jnp.full((b,), 42, jnp.uint32), jnp.ones((b,), jnp.bool_), jnp)
    pid = HH.partition_ids_from_hash(h, num_parts, jnp)
    pid = jnp.where(sel, pid, num_parts)  # dead rows to overflow bucket
    # stable sort rows by pid → contiguous runs per partition
    order = jnp.argsort(pid, stable=True)
    pid_s = jnp.take(pid, order)
    keys_s = jnp.take(keys, order)
    vals_s = jnp.take(values, order)
    live_s = pid_s < num_parts
    counts = jax.ops.segment_sum(jnp.ones((b,), jnp.int32), pid_s,
                                 num_segments=num_parts + 1)[:num_parts]
    starts = jnp.cumsum(counts) - counts
    offset = jnp.arange(b, dtype=jnp.int32) - jnp.take(
        starts, jnp.clip(pid_s, 0, num_parts - 1))
    slot = jnp.where(live_s, jnp.clip(pid_s, 0, num_parts - 1) * b + offset,
                     num_parts * b)
    out_k = jnp.zeros((num_parts * b,), keys.dtype).at[slot].set(
        keys_s, mode="drop").reshape(num_parts, b)
    out_v = jnp.zeros((num_parts * b,), values.dtype).at[slot].set(
        vals_s, mode="drop").reshape(num_parts, b)
    out_live = jnp.zeros((num_parts * b,), jnp.bool_).at[slot].set(
        live_s, mode="drop").reshape(num_parts, b)
    return out_k, out_v, out_live


def _local_groupby_sum(keys: jnp.ndarray, values: jnp.ndarray,
                       live: jnp.ndarray
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sorted segment-sum groupby on flat local arrays (int64 keys)."""
    n = keys.shape[0]
    dead = (~live).astype(jnp.uint64)
    ukey = keys.astype(jnp.int64).astype(jnp.uint64) ^ jnp.uint64(1 << 63)
    iota = jnp.arange(n, dtype=jnp.int32)
    d_s, k_s, perm = jax.lax.sort((dead, ukey, iota), num_keys=3)[:3]
    keys_s = jnp.take(keys, perm)
    vals_s = jnp.take(values, perm)
    live_s = d_s == 0
    prev_k = jnp.concatenate([k_s[:1], k_s[:-1]])
    prev_d = jnp.concatenate([d_s[:1], d_s[:-1]])
    boundary = ((k_s != prev_k) | (d_s != prev_d)).at[0].set(True)
    gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    ngroups = jnp.sum((boundary & live_s).astype(jnp.int32))
    sums = jax.ops.segment_sum(
        jnp.where(live_s, vals_s, jnp.zeros((), vals_s.dtype)), gid,
        num_segments=n)
    rep = jnp.where(boundary & live_s, gid, n)
    out_keys = jnp.zeros((n,), keys.dtype).at[rep].set(keys_s, mode="drop")
    out_live = jnp.arange(n, dtype=jnp.int32) < ngroups
    return out_keys, sums, out_live


def distributed_filter_groupby(mesh: jax.sharding.Mesh,
                               keys: jax.Array, values: jax.Array,
                               sel: jax.Array, threshold):
    """The full multichip step, jitted once over the mesh:

      shard scan (dp) → filter → murmur3 hash partition →
      ``all_to_all`` over ICI (the shuffle) → local sort-groupby (sum).

    Inputs are globally-shaped [N] arrays sharded on the mesh axis.
    Returns per-device group keys/sums/liveness as [D, B]-sharded arrays.
    """
    axis = mesh.axis_names[0]
    nparts = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))

    def step(k, v, s):  # local shard view: [B_local]
        s = s & (v > threshold)  # the filter stage
        pk, pv, pl = _local_partition(k, v, s, nparts)
        # exchange: device d sends pk[p] to device p  (ICI collective)
        pk = jax.lax.all_to_all(pk, axis, 0, 0, tiled=False)
        pv = jax.lax.all_to_all(pv, axis, 0, 0, tiled=False)
        pl = jax.lax.all_to_all(pl, axis, 0, 0, tiled=False)
        gk, gs, gl = _local_groupby_sum(
            pk.reshape(-1), pv.reshape(-1), pl.reshape(-1))
        return gk[None], gs[None], gl[None]

    spec = jax.sharding.PartitionSpec(axis)
    fn = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(spec, spec, spec)))
    return fn(keys, values, sel)
