"""Per-process executor context for multi-executor (multi-process) runs.

[REF: sql-plugin/../Plugin.scala :: RapidsExecutorPlugin — the
reference's executor plugin initializes the device runtime once per
executor JVM; SURVEY §5.8 — the rendezvous turns Spark's
independently-scheduled tasks into collective participants.]

One ``ExecutorContext`` per process, created by ``TpuSession`` when
``spark.rapids.executor.count > 1``:

* joins the **global device mesh** via ``jax.distributed.initialize``
  (each process addresses only its local devices; collectives span all),
* holds the ``RendezvousClient`` every ICI exchange uses for shape
  agreement and collective entry,
* assigns deterministic per-process stage ids: all executors plan the
  same query with the same deterministic planner, so the Nth exchange
  materialized in one process is the Nth in every process (the analog of
  Spark's driver-assigned shuffle ids).
"""

from __future__ import annotations

import itertools
import threading
from typing import List, Optional

from spark_rapids_tpu.parallel.rendezvous import RendezvousClient


class ExecutorContext:
    def __init__(self, process_id: int, num_processes: int,
                 coordinator_address: str, rendezvous_address: str,
                 timeout: float):
        import jax
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
        self.process_id = process_id
        self.num_processes = num_processes
        self.timeout = timeout
        self.client = RendezvousClient(rendezvous_address, process_id)
        self._stage_counter = itertools.count()

    def next_stage_id(self) -> str:
        """Deterministic across processes (same planner, same order)."""
        return f"stage-{next(self._stage_counter)}"

    def local_partition_ids(self, mesh) -> List[int]:
        """Global mesh-partition indices whose device this process owns."""
        import jax
        pi = jax.process_index()
        return [i for i, d in enumerate(mesh.devices.flatten())
                if d.process_index == pi]


_CTX: Optional[ExecutorContext] = None
_LOCK = threading.Lock()


def init_executor(conf) -> Optional[ExecutorContext]:
    """Create (or return) the process's executor context per conf.

    Idempotent; raises if a second session asks for a conflicting
    topology (jax.distributed can only initialize once per process)."""
    from spark_rapids_tpu import conf as C
    global _CTX
    count = int(conf.get(C.EXECUTOR_COUNT))
    if count <= 1:
        return None
    coord = str(conf.get(C.COORDINATOR_ADDRESS)).strip()
    rdv = str(conf.get(C.RENDEZVOUS_ADDRESS)).strip()
    if not coord or not rdv:
        raise ValueError(
            "executor.count > 1 requires both "
            "spark.rapids.executor.coordinator.address and "
            "spark.rapids.shuffle.rendezvous.address")
    if conf.shuffle_mode != "ICI":
        raise ValueError(
            "multi-executor mode requires spark.rapids.shuffle.mode=ICI "
            f"(got {conf.shuffle_mode})")
    pid = int(conf.get(C.EXECUTOR_ID))
    timeout = float(conf.get(C.RENDEZVOUS_TIMEOUT))
    with _LOCK:
        if _CTX is not None:
            if (_CTX.process_id, _CTX.num_processes) != (pid, count):
                raise ValueError(
                    "executor context already initialized as "
                    f"({_CTX.process_id}/{_CTX.num_processes}); cannot "
                    f"re-initialize as ({pid}/{count})")
            _CTX.timeout = timeout
            return _CTX
        _CTX = ExecutorContext(pid, count, coord, rdv, timeout)
        return _CTX


def get_executor() -> Optional[ExecutorContext]:
    return _CTX
