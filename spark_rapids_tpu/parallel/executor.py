"""Per-process executor context for multi-executor (multi-process) runs.

[REF: sql-plugin/../Plugin.scala :: RapidsExecutorPlugin — the
reference's executor plugin initializes the device runtime once per
executor JVM; SURVEY §5.8 — the rendezvous turns Spark's
independently-scheduled tasks into collective participants.]

One ``ExecutorContext`` per process, created by ``TpuSession`` when
``spark.rapids.executor.count > 1``:

* joins the **global device mesh** via ``jax.distributed.initialize``
  (each process addresses only its local devices; collectives span all),
* holds the ``RendezvousClient`` every ICI exchange uses for shape
  agreement and collective entry,
* assigns deterministic per-process stage ids: all executors plan the
  same query with the same deterministic planner, so the Nth exchange
  materialized in one process is the Nth in every process (the analog of
  Spark's driver-assigned shuffle ids).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, List, Optional, Sequence

from spark_rapids_tpu.parallel.rendezvous import RendezvousClient
from spark_rapids_tpu.runtime import telemetry as TM


class ExecutorContext:
    def __init__(self, process_id: int, num_processes: int,
                 coordinator_address: str, rendezvous_address: str,
                 timeout: float, heartbeat_s: float = 0.0):
        # register under the coordinator's heartbeat lease BEFORE the
        # jax.distributed handshake: a peer that dies mid-init is then
        # already visible to the reaper
        self.client = RendezvousClient(rendezvous_address, process_id,
                                       default_timeout=timeout)
        if heartbeat_s > 0:
            self.client.start_heartbeat(heartbeat_s)
        import jax
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
        self.process_id = process_id
        self.num_processes = num_processes
        self.timeout = timeout
        self._stage_counter = itertools.count()

    def next_stage_id(self) -> str:
        """Deterministic across processes (same planner, same order)."""
        return f"stage-{next(self._stage_counter)}"

    def local_partition_ids(self, mesh) -> List[int]:
        """Global mesh-partition indices whose device this process owns."""
        import jax
        pi = jax.process_index()
        return [i for i, d in enumerate(mesh.devices.flatten())
                if d.process_index == pi]


_CTX: Optional[ExecutorContext] = None
_LOCK = threading.Lock()


def rendezvous_timeout_s(conf) -> float:
    """Stage deadline in seconds: ``rendezvous.timeoutMs``, unless the
    legacy ``rendezvous.timeoutSec`` key was set explicitly (it wins)."""
    from spark_rapids_tpu import conf as C
    legacy = conf.get_raw(C.RENDEZVOUS_TIMEOUT.key)
    if legacy is not None:
        return float(legacy)
    return float(conf.get(C.RENDEZVOUS_TIMEOUT_MS)) / 1000.0


def init_executor(conf) -> Optional[ExecutorContext]:
    """Create (or return) the process's executor context per conf.

    Idempotent; raises if a second session asks for a conflicting
    topology (jax.distributed can only initialize once per process)."""
    from spark_rapids_tpu import conf as C
    global _CTX
    count = int(conf.get(C.EXECUTOR_COUNT))
    if count <= 1:
        return None
    coord = str(conf.get(C.COORDINATOR_ADDRESS)).strip()
    rdv = str(conf.get(C.RENDEZVOUS_ADDRESS)).strip()
    if not coord or not rdv:
        raise ValueError(
            "executor.count > 1 requires both "
            "spark.rapids.executor.coordinator.address and "
            "spark.rapids.shuffle.rendezvous.address")
    if conf.shuffle_mode != "ICI":
        raise ValueError(
            "multi-executor mode requires spark.rapids.shuffle.mode=ICI "
            f"(got {conf.shuffle_mode})")
    pid = int(conf.get(C.EXECUTOR_ID))
    timeout = rendezvous_timeout_s(conf)
    heartbeat_s = float(conf.get(C.RENDEZVOUS_HEARTBEAT_MS)) / 1000.0
    with _LOCK:
        if _CTX is not None:
            if (_CTX.process_id, _CTX.num_processes) != (pid, count):
                raise ValueError(
                    "executor context already initialized as "
                    f"({_CTX.process_id}/{_CTX.num_processes}); cannot "
                    f"re-initialize as ({pid}/{count})")
            _CTX.timeout = timeout
            _CTX.client.default_timeout = timeout
            return _CTX
        _CTX = ExecutorContext(pid, count, coord, rdv, timeout,
                               heartbeat_s)
        return _CTX


def get_executor() -> Optional[ExecutorContext]:
    return _CTX


# ---------------------------------------------------------------------------
# instrumented partition-pump pool (the Spark-task-slot analog's
# process-level observability: queue depth + task latency)
# ---------------------------------------------------------------------------

_pump_lock = threading.Lock()
_pump_inflight = 0  # tasks submitted and not yet completed

_TM_PUMP_TASKS = TM.REGISTRY.counter(
    "tpuq_pump_tasks_total", "partition pump tasks completed")
_TM_PUMP_TASK_S = TM.REGISTRY.histogram(
    "tpuq_pump_task_seconds",
    "per-task pump execution time (incl. semaphore wait)")
TM.REGISTRY.gauge(
    "tpuq_pump_queue_depth",
    "pump tasks submitted but not yet completed",
    fn=lambda: _pump_inflight)


def run_pump_tasks(fn: Callable, items: Sequence,
                   max_workers: int = 1) -> List:
    """Run ``fn`` over ``items`` preserving order — inline when a single
    worker suffices, else on a transient thread pool — with queue-depth
    and task-latency accounting either way."""
    global _pump_inflight
    items = list(items)
    if not items:
        return []
    started = [0]

    def timed(item):
        global _pump_inflight
        with _pump_lock:
            started[0] += 1
        t0 = time.perf_counter()
        try:
            return fn(item)
        finally:
            _TM_PUMP_TASK_S.observe(time.perf_counter() - t0)
            _TM_PUMP_TASKS.inc()
            with _pump_lock:
                _pump_inflight -= 1

    with _pump_lock:
        _pump_inflight += len(items)
    try:
        if max_workers <= 1 or len(items) == 1:
            return [timed(i) for i in items]
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=max_workers) as pool:
            return list(pool.map(timed, items))
    finally:
        # tasks cancelled before starting (an earlier task raised)
        # never ran their own decrement — settle the gauge exactly
        with _pump_lock:
            _pump_inflight -= len(items) - started[0]
