"""Multi-executor shuffle rendezvous — the collective/task impedance fix.

[REF: sql-plugin/../shuffle/ucx/ :: RapidsShuffleServer/Client — the
reference's executors pull shuffle blocks point-to-point, so reduce tasks
start independently.  SURVEY §5.8 names the TPU inversion "the hardest
novel piece": an ICI ``all_to_all`` needs EVERY participant to enter the
same XLA program, but Spark schedules executor tasks independently.]

Design (docs/rendezvous.md has the full write-up):

* One **coordinator** (driver-side): a tiny TCP service holding per-stage
  registration state.  Its one primitive is ``allgather(stage, payload)``
  — a barrier that returns every participant's payload.  Used twice per
  shuffle stage:
    1. shape agreement: local per-partition row counts → everyone
       computes the same global pow-2 ``cap`` (the static all_to_all
       shape — XLA programs must hash identically across processes);
    2. entry barrier: once agreed, every executor calls the SAME jitted
       ``{layout → all_to_all}`` program over the global mesh; the
       actual data rides XLA's cross-process collective (gloo on CPU
       hosts, ICI on a TPU pod slice).
* **Executors**: `DistributedShuffleExecutor` wraps
  ``jax.distributed.initialize`` (global mesh spanning processes — each
  process addresses only its local devices) + the rendezvous client +
  the batch-general shuffle programs from parallel/shuffle.py, which
  work unchanged over a multi-process mesh.
* **Failure policy** (SURVEY §5.3: a hung collective wedges the slice):
  every rendezvous has a deadline; the coordinator fails ALL waiters of
  an incomplete stage so every executor aborts together instead of a
  subset entering a collective that can never complete.

Coordinated fault tolerance on top of that fail-together core:

* **Liveness**: executors register under a heartbeat lease
  (``spark.rapids.tpu.rendezvous.{heartbeatMs,leaseMs}``).  A reaper
  thread declares a silent peer dead after one lease and immediately
  poisons every in-flight stage with a peer-tagged, non-transient
  abort — survivors unwind in ~one lease instead of N independent full
  stage deadlines.  Registration opts a pid into the lease: a client
  that registers must heartbeat.
* **Epochs**: stages are ``(stage, epoch)``-keyed.  A transient fault
  (coordinator restart, injected ``rendezvous`` fault, requested abort)
  makes every participant re-enter the same stage at epoch+1 through
  the shared ``RetryPolicy`` (``run_stage_epochs``).  Aborts leave
  bounded tombstones so stragglers still parked on a failed epoch get
  the abort (with a ``min_epoch`` hint) instead of a fresh deadline.
* **GC**: each stage refcounts its waiters; the last one out deletes
  the entry (the coordinator's ``_stages`` is empty after every
  completed query — no leak, and a stage can be re-run at a new epoch
  instead of dead-ending on "registered twice").
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from spark_rapids_tpu.runtime import telemetry as TM

_TM_ABORTS = TM.REGISTRY.labeled_counter(
    "tpuq_rendezvous_aborts_total",
    "Rendezvous stage aborts by reason (timeout, requested, peer_dead)",
    label="reason")
_TM_EPOCH_RETRIES = TM.REGISTRY.counter(
    "tpuq_rendezvous_epoch_retries_total",
    "Stage re-entries at a bumped epoch after a transient rendezvous "
    "fault")
_TM_HB_MISSES = TM.REGISTRY.counter(
    "tpuq_rendezvous_heartbeat_misses_total",
    "Executor heartbeats that could not reach the coordinator")
_TM_PEERS_DEAD = TM.REGISTRY.counter(
    "tpuq_rendezvous_peers_dead_total",
    "Executors declared dead by the coordinator's heartbeat lease")
_TM_STAGES = TM.REGISTRY.counter(
    "tpuq_rendezvous_stages_total",
    "Rendezvous stages completed (all participants delivered)")

_COORDS: "weakref.WeakSet[RendezvousCoordinator]" = weakref.WeakSet()
TM.REGISTRY.gauge(
    "tpuq_rendezvous_live_stages",
    "In-flight rendezvous stages across live coordinators (nonzero at "
    "rest indicates a stage leak)",
    fn=lambda: float(sum(len(c._stages) for c in list(_COORDS))))


def counters_snapshot() -> dict:
    """Rendezvous counter rollup for bench records / reports."""
    return {
        "aborts": _TM_ABORTS.child_values(),
        "epoch_retries": _TM_EPOCH_RETRIES.value,
        "heartbeat_misses": _TM_HB_MISSES.value,
        "peers_dead": _TM_PEERS_DEAD.value,
        "stages_completed": _TM_STAGES.value,
    }


def _send_msg(sock: socket.socket, obj) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack("<I", len(data)) + data)


def _recv_msg(sock: socket.socket):
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("rendezvous peer closed")
        hdr += chunk
    (n,) = struct.unpack("<I", hdr)
    data = b""
    while len(data) < n:
        chunk = sock.recv(n - len(data))
        if not chunk:
            raise ConnectionError("rendezvous peer closed")
        data += chunk
    return json.loads(data)


class RendezvousError(RuntimeError):
    """Base of the rendezvous failure family."""


class RendezvousTimeout(RendezvousError):
    """Stage did not assemble before the deadline (or the coordinator is
    unreachable) — slice-wide abort, retryable at the next epoch."""

    rendezvous_retryable = True


class RendezvousAborted(RendezvousError):
    """Stage was poisoned: by a peer's explicit abort (transient — retry
    at ``min_epoch``) or by the coordinator's lease reaper declaring
    ``peer`` dead (non-transient — every survivor fails together)."""

    def __init__(self, msg: str, peer: Optional[int] = None,
                 transient: bool = True, min_epoch: int = 0):
        super().__init__(msg)
        self.peer = peer
        self.transient = bool(transient)
        self.min_epoch = int(min_epoch)
        # only the transient family may re-enter the retry loop
        self.rendezvous_retryable = self.transient


class RendezvousProtocolError(RendezvousError):
    """A caller bug (duplicate registration, malformed request) — never
    retried; retrying cannot fix a protocol violation."""


class _Stage:
    def __init__(self, expected: int):
        self.expected = expected
        self.payloads: Dict[int, Any] = {}
        self.cv = threading.Condition()
        self.failed: Optional[str] = None
        self.kind: Optional[str] = None       # timeout | aborted | peer_dead
        self.peer: Optional[int] = None
        self.transient = True
        self.waiters = 0
        self.delivered = 0

    def fail(self, kind: str, msg: str, peer: Optional[int] = None,
             transient: bool = True) -> bool:
        """First failure wins; returns True on the transition."""
        if self.failed is not None:
            return False
        self.failed, self.kind = msg, kind
        self.peer, self.transient = peer, transient
        return True


def coordinator_from_conf(conf, num_processes: int,
                          host: str = "127.0.0.1",
                          port: int = 0) -> "RendezvousCoordinator":
    """Driver-side constructor: heartbeat lease and handler socket
    timeout from ``spark.rapids.tpu.rendezvous.{leaseMs,socketTimeoutMs}``."""
    from spark_rapids_tpu import conf as C
    return RendezvousCoordinator(
        num_processes, host=host, port=port,
        lease_s=float(conf.get(C.RENDEZVOUS_LEASE_MS)) / 1000.0,
        socket_timeout_s=float(
            conf.get(C.RENDEZVOUS_SOCKET_TIMEOUT_MS)) / 1000.0)


class TenancyArbiter:
    """The cluster half of tenancy enforcement, hosted by the
    coordinator (docs/serving.md "Cluster-wide enforcement & SLOs").

    Executors piggyback a per-tenant report (running/queued depth,
    starvation age, largest-runtime query) on each heartbeat;
    ``observe`` folds the report in, recomputes cluster-wide fair
    shares (weight share of the summed run slots), and returns the
    epoch-tagged directives pending for that executor — suspend the
    most over-share tenant's largest-runtime query wherever it runs,
    resume it once starvation clears, shed a tenant that is over share
    with nothing left to preempt.  Directives ride the heartbeat
    RESPONSE (the protocol stays request/response — no server push),
    so fan-out latency is bounded by ~one heartbeat period.

    Every suspend is a LEASE: it is re-issued (same directive id) on
    each heartbeat while still warranted, and executors let the token
    force-resume when renewals stop (coordinator restart, arbiter
    decision lost) — a directive can delay work but never wedge it.
    Reports from reaped executors are dropped, and their hosted
    suspensions are forgotten (the dead executor's tokens TTL-resume
    on their own)."""

    def __init__(self, grace_s: float = 0.5, suspend_ttl_s: float = 1.0,
                 report_ttl_s: float = 30.0):
        self.grace_s = float(grace_s)
        self.suspend_ttl_s = float(suspend_ttl_s)
        self.report_ttl_s = float(report_ttl_s)
        self._lock = threading.Lock()
        self._reports: Dict[int, Tuple[float, dict]] = {}
        self._pending: Dict[int, List[dict]] = {}
        # query_id -> {"pid", "tenant", "id"} for live suspend leases
        self._suspended: Dict[int, dict] = {}
        self._shed: Dict[str, str] = {}      # tenant -> directive id
        self._next_id = 0
        self.issued: Dict[str, int] = {"suspend": 0, "resume": 0,
                                       "shed": 0, "unshed": 0}

    def _mk(self, epoch: int, kind: str, tenant: str,
            query_id: Optional[int], detail: str,
            directive_id: Optional[str] = None) -> dict:
        if directive_id is None:
            self._next_id += 1
            directive_id = f"{epoch}-{self._next_id}"
            self.issued[kind] = self.issued.get(kind, 0) + 1
        return {"id": directive_id, "epoch": epoch, "kind": kind,
                "tenant": tenant, "query_id": query_id,
                "ttl_ms": self.suspend_ttl_s * 1000.0,
                "detail": detail, "issued_wall": time.time()}

    def observe(self, pid: int, report: dict, dead=(),
                epoch: int = 0) -> List[dict]:
        """Fold one executor's heartbeat report in, arbitrate, and
        drain that executor's pending directives."""
        with self._lock:
            for d in dead:
                self._reports.pop(d, None)
                self._pending.pop(d, None)
                for qid in [q for q, s in self._suspended.items()
                            if s["pid"] == d]:
                    del self._suspended[qid]
            self._reports[pid] = (time.monotonic(), dict(report or {}))
            self._arbitrate_locked(epoch)
            return self._pending.pop(pid, [])

    def _arbitrate_locked(self, epoch: int) -> None:
        now = time.monotonic()
        for pid in [p for p, (ts, _r) in self._reports.items()
                    if now - ts > self.report_ttl_s]:
            del self._reports[pid]
        slots = 0
        agg: Dict[str, dict] = {}
        victims: Dict[str, List[tuple]] = {}   # tenant -> (run_s,qid,pid)
        for pid, (_ts, rep) in self._reports.items():
            slots += int(rep.get("slots", 0))
            for name, n in (rep.get("breaches") or {}).items():
                a = agg.setdefault(name, {"weight": 1.0, "running": 0,
                                          "queued": 0, "suspended": 0,
                                          "oldest_wait_s": 0.0,
                                          "breaches": 0})
                a["breaches"] = a.get("breaches", 0) + int(n)
            for name, t in (rep.get("tenants") or {}).items():
                a = agg.setdefault(name, {"weight": 1.0, "running": 0,
                                          "queued": 0, "suspended": 0,
                                          "oldest_wait_s": 0.0,
                                          "breaches": 0})
                a["weight"] = max(a["weight"],
                                  float(t.get("weight", 1.0)))
                a["running"] += int(t.get("running", 0))
                a["queued"] += int(t.get("queued", 0))
                a["suspended"] += int(t.get("suspended", 0))
                wait = t.get("oldest_wait_s")
                if wait is not None:
                    a["oldest_wait_s"] = max(a["oldest_wait_s"],
                                             float(wait))
                qid = t.get("largest_qid")
                if qid is not None and qid not in self._suspended:
                    victims.setdefault(name, []).append(
                        (float(t.get("largest_run_s", 0.0)), qid, pid))
        if not agg or slots <= 0:
            return
        demanding = {n: a for n, a in agg.items()
                     if a["running"] + a["queued"] + a["suspended"] > 0}
        total_w = sum(a["weight"] for a in demanding.values()) or 1.0
        share = {n: max(1, round(a["weight"] / total_w * slots))
                 for n, a in demanding.items()}
        starved = [n for n, a in demanding.items()
                   if a["oldest_wait_s"] > self.grace_s
                   and a["running"] < share[n]]
        over = sorted(
            (n for n, a in demanding.items()
             if a["running"] > share[n] and n not in starved),
            key=lambda n: demanding[n]["running"] / demanding[n]["weight"],
            reverse=True)
        # 1. renew or release existing suspend leases: a suspension
        #    exists to relieve starvation, so it holds exactly while
        #    some tenant still starves (the victim tenant's own
        #    running count fell when it was suspended — judging the
        #    lease by "still over share" would oscillate)
        for qid, s in list(self._suspended.items()):
            if bool(starved):
                self._pending.setdefault(s["pid"], []).append(self._mk(
                    epoch, "suspend", s["tenant"], qid,
                    "lease renewal", directive_id=s["id"]))
            else:
                self._pending.setdefault(s["pid"], []).append(self._mk(
                    epoch, "resume", s["tenant"], qid,
                    "cluster starvation cleared"))
                del self._suspended[qid]
        # 2. new suspensions: most over-share tenant's largest-runtime
        #    query, wherever in the cluster it runs
        if starved:
            for name in over:
                cands = victims.get(name)
                if not cands:
                    continue
                run_s, qid, vpid = max(cands)
                d = self._mk(
                    epoch, "suspend", name, qid,
                    f"tenant {name} over cluster share "
                    f"({agg[name]['running']}/{share[name]} slots), "
                    f"starved waiter: {starved[0]}")
                self._pending.setdefault(vpid, []).append(d)
                self._suspended[qid] = {"pid": vpid, "tenant": name,
                                        "id": d["id"]}
                break
        # 2b. HBM-breach relays: a tenant over its byte budget with no
        #     LOCAL victim — suspend its largest-runtime query wherever
        #     it runs so its residency spills and reservations unwind
        for name, a in agg.items():
            if a.get("breaches", 0) <= 0:
                continue
            cands = victims.get(name)
            if not cands:
                continue
            run_s, qid, vpid = max(cands)
            if qid in self._suspended:
                continue
            d = self._mk(epoch, "suspend", name, qid,
                         f"tenant {name} HBM budget breach relayed "
                         "from another executor")
            self._pending.setdefault(vpid, []).append(d)
            self._suspended[qid] = {"pid": vpid, "tenant": name,
                                    "id": d["id"]}
        # 3. shed: over share, starving others, nothing preemptible
        for name in over:
            if (starved and not victims.get(name)
                    and agg[name]["suspended"] > 0
                    and name not in self._shed):
                d = self._mk(epoch, "shed", name, None,
                             "over cluster share with nothing left to "
                             "preempt — shaping admission")
                self._shed[name] = d["id"]
                for pid in self._reports:
                    self._pending.setdefault(pid, []).append(dict(d))
        for name in list(self._shed):
            if name not in over or not starved:
                d = self._mk(epoch, "unshed", name, None,
                             "cluster pressure cleared")
                del self._shed[name]
                for pid in self._reports:
                    self._pending.setdefault(pid, []).append(dict(d))

    def stats(self) -> dict:
        with self._lock:
            return {"issued": dict(self.issued),
                    "live_suspends": len(self._suspended),
                    "shed_tenants": sorted(self._shed),
                    "reporting_executors": len(self._reports)}


class RendezvousCoordinator:
    """Driver-side rendezvous service (the MapOutputTracker analog for
    collective entry).  Thread-per-connection TCP; message = one JSON
    request ``{op, stage, pid, payload, timeout, epoch}`` →
    ``{ok, payloads | kind, error, peer, transient, min_epoch}``.

    Ops: ``allgather`` (the barrier primitive), ``register`` (join the
    heartbeat lease; re-registering a dead pid revives it and bumps the
    generation), ``heartbeat`` (renew the lease), ``abort`` (poison one
    stage family at one epoch)."""

    _TOMB_CAP = 256

    def __init__(self, num_processes: int, host: str = "127.0.0.1",
                 port: int = 0, *, lease_s: float = 15.0,
                 socket_timeout_s: float = 10.0):
        self.num_processes = num_processes
        self.lease_s = float(lease_s)
        self.socket_timeout_s = float(socket_timeout_s)
        self._stages: Dict[Tuple[str, int], _Stage] = {}
        self._tombs: "OrderedDict[Tuple[str, int], dict]" = OrderedDict()
        self._peers: Dict[int, float] = {}    # pid -> last heartbeat
        self._dead: Dict[int, str] = {}       # pid -> why
        self._generation = 0
        self._lock = threading.Lock()
        self._halt = threading.Event()
        # cluster tenancy arbiter — engaged only when heartbeats carry
        # a tenancy report (tenancy.enabled on the executors)
        self.tenancy = TenancyArbiter()
        coord = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    # a half-open client must not pin this thread forever
                    self.request.settimeout(coord.socket_timeout_s)
                    req = _recv_msg(self.request)
                    resp = coord._handle(req)
                    _send_msg(self.request, resp)
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.address = "{}:{}".format(*self._server.server_address[:2])
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        self._reaper = threading.Thread(
            target=self._reap_loop, daemon=True,
            name="tpuq-rendezvous-reaper")
        self._reaper.start()
        _COORDS.add(self)

    # -- liveness -------------------------------------------------------

    def _reap_loop(self):
        while not self._halt.wait(max(self.lease_s / 4.0, 0.01)):
            now = time.monotonic()
            newly: List[Tuple[int, str]] = []
            live: List[Tuple[Tuple[str, int], _Stage]] = []
            with self._lock:
                for pid, seen in self._peers.items():
                    if pid in self._dead:
                        continue
                    if now - seen > self.lease_s:
                        why = (f"executor {pid} missed its heartbeat "
                               f"lease ({self.lease_s:.1f}s) — presumed "
                               "dead")
                        self._dead[pid] = why
                        newly.append((pid, why))
                if newly:
                    live = list(self._stages.items())
            for pid, why in newly:
                _TM_PEERS_DEAD.inc()
                # poison EVERY in-flight stage: survivors unwind in ~one
                # lease instead of each waiting out its own deadline
                for _, st in live:
                    with st.cv:
                        if st.fail("peer_dead", why, peer=pid,
                                   transient=False):
                            _TM_ABORTS.inc("peer_dead")
                        st.cv.notify_all()

    def _op_register(self, req) -> dict:
        pid = int(req["pid"])
        with self._lock:
            if pid in self._dead:
                # a revived executor starts a new generation; stages of
                # the old one stay poisoned/tombstoned
                del self._dead[pid]
                self._generation += 1
            self._peers[pid] = time.monotonic()
            return {"ok": True, "generation": self._generation}

    def _op_heartbeat(self, req) -> dict:
        pid = int(req["pid"])
        report = req.get("tenancy")
        with self._lock:
            if pid in self._dead:
                # too late: survivors may already be unwinding on this
                # pid's death — it must re-register to rejoin
                return {"ok": False, "kind": "dead",
                        "error": self._dead[pid]}
            self._peers[pid] = time.monotonic()
            gen = self._generation
            dead = sorted(self._dead)
        resp = {"ok": True, "generation": gen, "dead": dead}
        if report is not None:
            # arbitrate OUTSIDE the coordinator lock (the arbiter has
            # its own) and fan this executor's directives out on the
            # response — bounded by one heartbeat period end to end
            resp["tenancy_epoch"] = gen
            resp["directives"] = self.tenancy.observe(
                pid, report, dead=dead, epoch=gen)
        return resp

    # -- stage fault plumbing -------------------------------------------

    def _tomb(self, key: Tuple[str, int], kind: str, error: str,
              peer: Optional[int], transient: bool) -> bool:
        # callers hold self._lock; returns True if the tombstone is new
        if key in self._tombs:
            return False
        self._tombs[key] = {"kind": kind, "error": error, "peer": peer,
                            "transient": transient}
        while len(self._tombs) > self._TOMB_CAP:
            self._tombs.popitem(last=False)
        return True

    @staticmethod
    def _covers(prefix: str, stage: str) -> bool:
        # "stage-1" covers "stage-1" and "stage-1:counts",
        # NOT "stage-10:counts"
        return stage == prefix or stage.startswith(prefix + ":")

    def _match_tomb(self, stage: str, epoch: int) -> Optional[dict]:
        # callers hold self._lock
        for (p, e), t in self._tombs.items():
            if e == epoch and self._covers(p, stage):
                return t
        return None

    def _min_epoch(self, stage: str) -> int:
        # callers hold self._lock: the first epoch with no tombstone for
        # this stage family — the convergence hint retrying clients use
        root = stage.split(":", 1)[0]
        best = -1
        for (p, e), _ in self._tombs.items():
            if p.split(":", 1)[0] == root:
                best = max(best, e)
        return best + 1

    def _abort_resp(self, kind: str, error: str, peer: Optional[int],
                    transient: bool, min_epoch: int) -> dict:
        return {"ok": False, "kind": kind, "error": error, "peer": peer,
                "transient": transient, "min_epoch": min_epoch}

    def _op_abort(self, req) -> dict:
        prefix = str(req["prefix"])
        epoch = int(req.get("epoch", 0))
        transient = bool(req.get("transient", True))
        peer = req.get("peer")
        reason = req.get("reason") or (
            f"stage {prefix}@e{epoch} aborted by pid {req.get('pid')}")
        with self._lock:
            fresh = self._tomb((prefix, epoch), "aborted", reason, peer,
                               transient)
            live = [st for (s, e), st in self._stages.items()
                    if e == epoch and self._covers(prefix, s)]
        for st in live:
            with st.cv:
                st.fail("aborted", reason, peer=peer, transient=transient)
                st.cv.notify_all()
        if fresh:
            _TM_ABORTS.inc("requested")
        return {"ok": True}

    # -- the barrier primitive ------------------------------------------

    def _op_allgather(self, req) -> dict:
        stage = str(req["stage"])
        pid = int(req["pid"])
        epoch = int(req.get("epoch", 0))
        timeout = float(req.get("timeout", 60.0))
        key = (stage, epoch)
        with self._lock:
            if self._dead:
                dpid = sorted(self._dead)[0]
                return self._abort_resp(
                    "peer_dead", self._dead[dpid], dpid, False,
                    self._min_epoch(stage))
            tomb = self._match_tomb(stage, epoch)
            if tomb is not None:
                return self._abort_resp(
                    tomb["kind"], tomb["error"], tomb["peer"],
                    tomb["transient"], self._min_epoch(stage))
            st = self._stages.get(key)
            if st is None:
                st = _Stage(self.num_processes)
                self._stages[key] = st
        deadline = time.monotonic() + timeout
        with st.cv:
            st.waiters += 1
            try:
                if st.failed is None and pid in st.payloads:
                    # caller bug; the stage itself is unaffected
                    return {"ok": False, "kind": "protocol",
                            "error": f"pid {pid} registered twice for "
                                     f"{stage}@e{epoch}"}
                if st.failed is None:
                    st.payloads[pid] = req.get("payload")
                    if len(st.payloads) == st.expected:
                        st.cv.notify_all()
                    else:
                        while (len(st.payloads) < st.expected
                               and st.failed is None):
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                # fail EVERY waiter: nobody may enter
                                # the collective alone
                                if st.fail(
                                        "timeout",
                                        f"stage {stage}@e{epoch}: only "
                                        f"{len(st.payloads)}/"
                                        f"{st.expected} executors "
                                        "arrived before the deadline"):
                                    _TM_ABORTS.inc("timeout")
                                st.cv.notify_all()
                                break
                            st.cv.wait(timeout=min(remaining, 1.0))
                if st.failed is not None:
                    return self._abort_resp(st.kind, st.failed, st.peer,
                                            st.transient, epoch + 1)
                st.delivered += 1
                payloads = [st.payloads[i] for i in range(st.expected)]
                return {"ok": True, "payloads": payloads}
            finally:
                st.waiters -= 1
                self._maybe_gc(key, st)

    def _maybe_gc(self, key: Tuple[str, int], st: _Stage) -> None:
        # callers hold st.cv; last waiter out deletes the stage —
        # failed stages leave a tombstone so stragglers get the abort
        done = st.failed is not None or st.delivered >= st.expected
        if st.waiters > 0 or not done:
            return
        with self._lock:
            if self._stages.pop(key, None) is None:
                return
            if st.failed is not None:
                self._tomb(key, st.kind or "aborted", st.failed,
                           st.peer, st.transient)
        if st.failed is None:
            _TM_STAGES.inc()

    def _handle(self, req) -> dict:
        op = req.get("op", "allgather")
        if op == "allgather":
            return self._op_allgather(req)
        if op == "register":
            return self._op_register(req)
        if op == "heartbeat":
            return self._op_heartbeat(req)
        if op == "abort":
            return self._op_abort(req)
        return {"ok": False, "kind": "protocol",
                "error": f"unknown rendezvous op {op!r}"}

    def shutdown(self):
        self._halt.set()
        self._server.shutdown()
        self._server.server_close()


class RendezvousClient:
    """One executor's handle on the coordinator.

    ``default_timeout`` (conf: ``spark.rapids.tpu.rendezvous.timeoutMs``)
    applies wherever a call site passes ``timeout=None``.  A client that
    ``start_heartbeat``s registers under the coordinator's lease and
    renews it from a daemon thread; ``simulate_death`` (the ``peer_loss``
    chaos hook) silences the heartbeat so the lease expires for real."""

    def __init__(self, address: str, pid: int,
                 default_timeout: float = 60.0):
        host, port = address.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.pid = pid
        self.default_timeout = float(default_timeout)
        self.dead = False
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_halt = threading.Event()
        # tenancy piggyback hooks (set by start_heartbeat)
        self._hb_payload_fn: Optional[Callable[[], dict]] = None
        self._hb_on_response: Optional[Callable[[dict], None]] = None
        self._hb_on_miss: Optional[Callable[[], None]] = None

    def _request(self, obj, io_timeout: float):
        with socket.create_connection((self.host, self.port),
                                      timeout=io_timeout) as sock:
            sock.settimeout(io_timeout)
            _send_msg(sock, obj)
            return _recv_msg(sock)

    # -- liveness -------------------------------------------------------

    def register(self, timeout: float = 5.0) -> int:
        try:
            resp = self._request({"op": "register", "pid": self.pid},
                                 timeout)
        except OSError as e:
            raise RendezvousTimeout(
                f"pid {self.pid}: cannot reach coordinator to register: "
                f"{e}") from e
        if not resp.get("ok"):
            raise RendezvousProtocolError(
                resp.get("error", "register failed"))
        return int(resp.get("generation", 0))

    def start_heartbeat(self, period_s: float,
                        payload_fn: Optional[Callable[[], dict]] = None,
                        on_response: Optional[
                            Callable[[dict], None]] = None,
                        on_miss: Optional[Callable[[], None]] = None
                        ) -> None:
        """Register, then renew the lease every ``period_s`` (<= 0:
        register only — no liveness tracking).

        The tenancy piggyback: ``payload_fn()`` rides each heartbeat
        as the executor's per-tenant report, ``on_response(resp)``
        receives the coordinator's reply (tenancy epoch + pending
        directives), ``on_miss()`` fires on each unreachable
        coordinator (the degraded-mode trigger)."""
        self.register()
        if period_s <= 0 or self._hb_thread is not None:
            return
        self._hb_payload_fn = payload_fn
        self._hb_on_response = on_response
        self._hb_on_miss = on_miss
        self._hb_halt.clear()
        t = threading.Thread(
            target=self._hb_loop, args=(float(period_s),), daemon=True,
            name=f"tpuq-rendezvous-heartbeat-{self.pid}")
        self._hb_thread = t
        t.start()

    def _hb_loop(self, period_s: float) -> None:
        while not self._hb_halt.wait(period_s):
            req = {"op": "heartbeat", "pid": self.pid}
            fn = self._hb_payload_fn
            if fn is not None:
                try:
                    req["tenancy"] = fn()
                except Exception:
                    pass  # a broken report must not stop the lease
            try:
                resp = self._request(req, 5.0)
            except OSError:
                _TM_HB_MISSES.inc()
                cb = self._hb_on_miss
                if cb is not None:
                    try:
                        cb()
                    except Exception:
                        pass
                continue
            cb = self._hb_on_response
            if cb is not None:
                try:
                    cb(resp)
                except Exception:
                    pass

    def stop_heartbeat(self) -> None:
        self._hb_halt.set()
        t, self._hb_thread = self._hb_thread, None
        if t is not None:
            t.join(timeout=2.0)

    def simulate_death(self) -> None:
        """peer_loss chaos hook: go silent and let the lease expire."""
        self.dead = True
        self.stop_heartbeat()

    # -- the barrier primitive ------------------------------------------

    def allgather(self, stage_id: str, payload=None,
                  timeout: Optional[float] = None,
                  epoch: int = 0) -> List[Any]:
        from spark_rapids_tpu.runtime import cancel
        from spark_rapids_tpu.runtime import resilience as R
        cancel.check()  # don't enter a barrier the query already left
        R.INJECTOR.on("rendezvous")
        if self.dead:
            raise RendezvousAborted(
                f"pid {self.pid} is simulated-dead", peer=self.pid,
                transient=False)
        timeout = (self.default_timeout if timeout is None
                   else float(timeout))
        try:
            resp = self._request(
                {"op": "allgather", "stage": stage_id, "pid": self.pid,
                 "payload": payload, "timeout": timeout, "epoch": epoch},
                timeout + 10)
        except OSError as e:
            raise RendezvousTimeout(
                f"stage {stage_id}@e{epoch}: coordinator unreachable: "
                f"{e}") from e
        if resp.get("ok"):
            return resp["payloads"]
        kind = resp.get("kind", "timeout")
        err = resp.get("error", "rendezvous failed")
        if kind == "protocol":
            raise RendezvousProtocolError(err)
        if kind == "timeout":
            raise RendezvousTimeout(err)
        raise RendezvousAborted(
            err, peer=resp.get("peer"),
            transient=bool(resp.get("transient", True)),
            min_epoch=int(resp.get("min_epoch", 0)))

    def barrier(self, stage_id: str, timeout: Optional[float] = None,
                epoch: int = 0) -> None:
        self.allgather(stage_id, None, timeout, epoch=epoch)

    def abort(self, stage_id: str, epoch: int, reason: str,
              transient: bool = True, peer: Optional[int] = None) -> None:
        """Best-effort stage poison (a dead coordinator cannot deliver
        aborts anyway — peers then fall back to their own deadlines)."""
        try:
            self._request(
                {"op": "abort", "prefix": stage_id, "epoch": epoch,
                 "pid": self.pid, "reason": reason,
                 "transient": transient, "peer": peer}, 5.0)
        except OSError:
            pass


def run_stage_epochs(client: RendezvousClient, stage_id: str,
                     attempt_fn: Callable[[int], Any], *,
                     policy=None, token=None) -> Any:
    """Run ``attempt_fn(epoch)`` under the shared ``RetryPolicy`` with
    epoch bumping — the distributed analog of ``RetryPolicy.run``.

    Every transient rendezvous fault (deadline, coordinator restart,
    peer-requested abort, injected ``rendezvous`` fault) aborts the
    current epoch for everyone — so peers stop waiting — and re-enters
    at epoch+1 (or the coordinator's ``min_epoch`` hint, so restarted
    clients converge instead of leapfrogging).  A confirmed-dead peer
    surfaces as a peer-tagged ``TerminalDeviceError('peer_loss')`` on
    every survivor; a ``peer_loss`` injection on THIS client simulates
    the death itself.

    ``token`` is this participant's CancelToken (defaults to the active
    query's).  A cancel fast-aborts the stage for EVERYONE — the
    cancelled participant poisons the epoch non-transiently (tagged
    with its own pid, so survivors fail like they would on a dead peer)
    and raises ``QueryCancelled`` instead of re-entering."""
    from spark_rapids_tpu.runtime import cancel as _cancel
    from spark_rapids_tpu.runtime import resilience as R

    pol = policy if policy is not None else R.get_policy()
    tok = token if token is not None else _cancel.current()
    state = {"epoch": 0}

    def _cancel_abort() -> None:
        # runs on the cancel thread, waking peers (and this
        # participant) out of a parked allgather; the coordinator's
        # tombstone is first-wins, so a repeated abort is harmless
        client.abort(
            stage_id, state["epoch"],
            f"pid {client.pid} cancelled during {stage_id}",
            transient=False, peer=client.pid)

    def _advance(min_epoch: int, why: str) -> None:
        nxt = max(state["epoch"] + 1, min_epoch)
        _TM_EPOCH_RETRIES.inc()
        client.abort(stage_id, state["epoch"],
                     f"pid {client.pid} retrying {stage_id} at epoch "
                     f"{nxt}: {why}")
        state["epoch"] = nxt

    def attempt():
        epoch = state["epoch"]
        if tok is not None and tok.cancelled():
            _cancel_abort()
            tok.check()  # raises QueryCancelled
        try:
            R.INJECTOR.on("peer_loss")
        except R.InjectedDeviceError as e:
            client.simulate_death()
            raise R.TerminalDeviceError("peer_loss", e) from e
        unhook = tok.on_cancel(_cancel_abort) if tok is not None else None
        try:
            return attempt_fn(epoch)
        except RendezvousAborted as e:
            if tok is not None and tok.cancelled():
                tok.check()  # our own cancel-abort came back around
            if not e.transient:
                dom = "peer_loss" if e.peer is not None else "rendezvous"
                raise R.TerminalDeviceError(dom, e) from e
            _advance(e.min_epoch, str(e))
            raise
        except RendezvousTimeout as e:
            if tok is not None and tok.cancelled():
                tok.check()
            _advance(0, str(e))
            raise
        except R.InjectedDeviceError as e:
            if getattr(e, "where", "") == "rendezvous":
                if e.transient:
                    _advance(0, str(e))
                else:
                    # fail together: peers must not wait out their full
                    # deadline on a fault that will never clear
                    client.abort(
                        stage_id, state["epoch"],
                        f"terminal rendezvous fault on pid "
                        f"{client.pid}: {e}", transient=False)
            raise
        except _cancel.QueryCancelled:
            # a nested cancellation point fired mid-stage: poison the
            # epoch peer-tagged, like a dead peer — survivors fail
            # together instead of waiting out their deadline
            _cancel_abort()
            raise
        except BaseException as e:
            # non-rendezvous failure mid-stage (compile error, local
            # crash): poison the epoch so peers fail now instead of
            # waiting out their full deadline on a stage that can no
            # longer complete
            client.abort(stage_id, state["epoch"],
                         f"pid {client.pid} failed mid-stage: {e}",
                         transient=False)
            raise
        finally:
            if unhook is not None:
                unhook()

    return pol.run("rendezvous", attempt, op=stage_id)


class DistributedShuffleExecutor:
    """One executor process of a multi-process shuffle slice.

    Wraps jax.distributed init (global mesh over all processes' devices)
    and runs rendezvous-coordinated collective shuffle stages with the
    SAME batch-general programs the single-process ICI exchange uses."""

    def __init__(self, coordinator_addr: str, rendezvous_addr: str,
                 process_id: int, num_processes: int, *,
                 timeout: float = 60.0, heartbeat_s: float = 0.0):
        self.client = RendezvousClient(rendezvous_addr, process_id,
                                       default_timeout=timeout)
        if heartbeat_s > 0:
            self.client.start_heartbeat(heartbeat_s)
        import jax
        jax.distributed.initialize(
            coordinator_address=coordinator_addr,
            num_processes=num_processes, process_id=process_id)
        import numpy as np
        self.process_id = process_id
        self.num_processes = num_processes
        self.devices = jax.devices()          # global
        self.local_devices = jax.local_devices()
        self.mesh = jax.sharding.Mesh(np.array(self.devices), ("x",))

    @property
    def nparts(self) -> int:
        return len(self.devices)

    def shuffle_stage(self, stage_id: str, local_shards, schema, keys,
                      timeout: Optional[float] = None):
        """Run one collective shuffle stage.

        ``local_shards``: one DeviceBatch per LOCAL device (uniform
        capacity, committed to that device).  Returns one received
        DeviceBatch per local device (that device's hash partition).
        Transient rendezvous faults re-enter at the next epoch; the
        inputs are assembled once outside the epoch loop, so a retried
        stage reruns over identical data (bit-identical recovery).
        """
        import jax
        import numpy as np

        from spark_rapids_tpu.parallel import shuffle as SH
        from spark_rapids_tpu.columnar.column import round_up_pow2
        d = self.nparts
        # 1. local counts (plain per-device jit, no collective)
        pid_fn = SH.make_pid_fn(keys, d)
        # jit-exempt: one throwaway counting program per rendezvous epoch
        cnt = jax.jit(lambda b: SH.local_partition_counts(
            b, pid_fn(b), d))
        local_max = 0
        for shard in local_shards:
            local_max = max(local_max,
                            int(np.asarray(cnt(shard)).max()))
        # 2. assemble the global array from every process's local shards
        #    (epoch-independent: kept alive across retries)
        sharding = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec("x"))
        flat = [jax.tree.flatten(s) for s in local_shards]
        treedef = flat[0][1]
        local_b = flat[0][0][0].shape[0]
        leaves = []
        for i in range(len(flat[0][0])):
            arrs = [flat[k][0][i] for k in range(len(local_shards))]
            shape = (d * local_b,) + arrs[0].shape[1:]
            leaves.append(jax.make_array_from_single_device_arrays(
                shape, sharding, arrs))
        sharded = jax.tree.unflatten(treedef, leaves)

        def attempt(epoch: int):
            # 3. SHAPE AGREEMENT through the rendezvous: the all_to_all
            #    cap must be identical in every process or the XLA
            #    programs (and their collectives) won't match
            counts = self.client.allgather(
                stage_id + ":counts", local_max, timeout, epoch=epoch)
            cap = round_up_pow2(max(max(counts), 1), 8)
            # 4. entry barrier, then the collective program (identical
            #    everywhere: same cap, same keys, same mesh)
            self.client.barrier(stage_id + ":enter", timeout,
                                epoch=epoch)
            fn = SH.build_shuffle_program(self.mesh, keys, d, cap)
            return fn(sharded)

        result = run_stage_epochs(self.client, stage_id, attempt)
        # 5. split back into per-local-device received batches
        out = []
        res_leaves, res_def = jax.tree.flatten(result)
        for dev in self.local_devices:
            dev_leaves = []
            for leaf in res_leaves:
                shard = next(s for s in leaf.addressable_shards
                             if s.device == dev)
                dev_leaves.append(shard.data)
            out.append(jax.tree.unflatten(res_def, dev_leaves))
        return out
