"""Multi-executor shuffle rendezvous — the collective/task impedance fix.

[REF: sql-plugin/../shuffle/ucx/ :: RapidsShuffleServer/Client — the
reference's executors pull shuffle blocks point-to-point, so reduce tasks
start independently.  SURVEY §5.8 names the TPU inversion "the hardest
novel piece": an ICI ``all_to_all`` needs EVERY participant to enter the
same XLA program, but Spark schedules executor tasks independently.]

Design (docs/rendezvous.md has the full write-up):

* One **coordinator** (driver-side): a tiny TCP service holding per-stage
  registration state.  Its one primitive is ``allgather(stage, payload)``
  — a barrier that returns every participant's payload.  Used twice per
  shuffle stage:
    1. shape agreement: local per-partition row counts → everyone
       computes the same global pow-2 ``cap`` (the static all_to_all
       shape — XLA programs must hash identically across processes);
    2. entry barrier: once agreed, every executor calls the SAME jitted
       ``{layout → all_to_all}`` program over the global mesh; the
       actual data rides XLA's cross-process collective (gloo on CPU
       hosts, ICI on a TPU pod slice).
* **Executors**: `DistributedShuffleExecutor` wraps
  ``jax.distributed.initialize`` (global mesh spanning processes — each
  process addresses only its local devices) + the rendezvous client +
  the batch-general shuffle programs from parallel/shuffle.py, which
  work unchanged over a multi-process mesh.
* **Failure policy** (SURVEY §5.3: a hung collective wedges the slice):
  every rendezvous has a deadline; the coordinator fails ALL waiters of
  an incomplete stage so every executor aborts together instead of a
  subset entering a collective that can never complete.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, List, Optional


def _send_msg(sock: socket.socket, obj) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack("<I", len(data)) + data)


def _recv_msg(sock: socket.socket):
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("rendezvous peer closed")
        hdr += chunk
    (n,) = struct.unpack("<I", hdr)
    data = b""
    while len(data) < n:
        chunk = sock.recv(n - len(data))
        if not chunk:
            raise ConnectionError("rendezvous peer closed")
        data += chunk
    return json.loads(data)


class RendezvousTimeout(RuntimeError):
    """Stage did not assemble before the deadline — slice-wide abort."""


class _Stage:
    def __init__(self, expected: int):
        self.expected = expected
        self.payloads: Dict[int, Any] = {}
        self.cv = threading.Condition()
        self.failed: Optional[str] = None


class RendezvousCoordinator:
    """Driver-side rendezvous service (the MapOutputTracker analog for
    collective entry).  Thread-per-connection TCP; message = one JSON
    request {stage, pid, payload, timeout} → {ok, payloads | error}."""

    def __init__(self, num_processes: int, host: str = "127.0.0.1",
                 port: int = 0):
        self.num_processes = num_processes
        self._stages: Dict[str, _Stage] = {}
        self._lock = threading.Lock()
        coord = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    req = _recv_msg(self.request)
                    resp = coord._handle(req)
                    _send_msg(self.request, resp)
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.address = "{}:{}".format(*self._server.server_address[:2])
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def _handle(self, req) -> dict:
        stage_id = req["stage"]
        pid = req["pid"]
        timeout = float(req.get("timeout", 60.0))
        with self._lock:
            st = self._stages.setdefault(
                stage_id, _Stage(self.num_processes))
        deadline = time.monotonic() + timeout
        with st.cv:
            if pid in st.payloads:
                return {"ok": False,
                        "error": f"pid {pid} registered twice for "
                                 f"{stage_id}"}
            st.payloads[pid] = req.get("payload")
            if len(st.payloads) == st.expected:
                st.cv.notify_all()
            else:
                while (len(st.payloads) < st.expected
                       and st.failed is None):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not st.cv.wait(
                            timeout=min(remaining, 1.0)):
                        if time.monotonic() >= deadline:
                            # fail EVERY waiter: nobody may enter the
                            # collective alone
                            st.failed = (
                                f"stage {stage_id}: only "
                                f"{len(st.payloads)}/{st.expected} "
                                "executors arrived before the deadline")
                            st.cv.notify_all()
                            break
            if st.failed is not None:
                return {"ok": False, "error": st.failed}
            payloads = [st.payloads[i] for i in range(st.expected)]
        return {"ok": True, "payloads": payloads}

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()


class RendezvousClient:
    def __init__(self, address: str, pid: int):
        host, port = address.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.pid = pid

    def allgather(self, stage_id: str, payload=None,
                  timeout: float = 60.0) -> List[Any]:
        with socket.create_connection((self.host, self.port),
                                      timeout=timeout + 10) as sock:
            _send_msg(sock, {"stage": stage_id, "pid": self.pid,
                             "payload": payload, "timeout": timeout})
            resp = _recv_msg(sock)
        if not resp.get("ok"):
            raise RendezvousTimeout(resp.get("error", "rendezvous failed"))
        return resp["payloads"]

    def barrier(self, stage_id: str, timeout: float = 60.0) -> None:
        self.allgather(stage_id, None, timeout)


class DistributedShuffleExecutor:
    """One executor process of a multi-process shuffle slice.

    Wraps jax.distributed init (global mesh over all processes' devices)
    and runs rendezvous-coordinated collective shuffle stages with the
    SAME batch-general programs the single-process ICI exchange uses."""

    def __init__(self, coordinator_addr: str, rendezvous_addr: str,
                 process_id: int, num_processes: int):
        import jax
        jax.distributed.initialize(
            coordinator_address=coordinator_addr,
            num_processes=num_processes, process_id=process_id)
        import numpy as np
        self.process_id = process_id
        self.num_processes = num_processes
        self.devices = jax.devices()          # global
        self.local_devices = jax.local_devices()
        self.mesh = jax.sharding.Mesh(np.array(self.devices), ("x",))
        self.client = RendezvousClient(rendezvous_addr, process_id)

    @property
    def nparts(self) -> int:
        return len(self.devices)

    def shuffle_stage(self, stage_id: str, local_shards, schema, keys,
                      timeout: float = 60.0):
        """Run one collective shuffle stage.

        ``local_shards``: one DeviceBatch per LOCAL device (uniform
        capacity, committed to that device).  Returns one received
        DeviceBatch per local device (that device's hash partition).
        """
        import jax
        import numpy as np

        from spark_rapids_tpu.parallel import shuffle as SH
        from spark_rapids_tpu.columnar.column import round_up_pow2
        d = self.nparts
        # 1. local counts (plain per-device jit, no collective)
        pid_fn = SH.make_pid_fn(keys, d)
        cnt = jax.jit(lambda b: SH.local_partition_counts(
            b, pid_fn(b), d))
        local_max = 0
        for shard in local_shards:
            local_max = max(local_max,
                            int(np.asarray(cnt(shard)).max()))
        # 2. SHAPE AGREEMENT through the rendezvous: the all_to_all cap
        #    must be identical in every process or the XLA programs
        #    (and their collectives) won't match
        counts = self.client.allgather(
            stage_id + ":counts", local_max, timeout)
        cap = round_up_pow2(max(max(counts), 1), 8)
        # 3. assemble the global array from every process's local shards
        sharding = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec("x"))
        flat = [jax.tree.flatten(s) for s in local_shards]
        treedef = flat[0][1]
        local_b = flat[0][0][0].shape[0]
        leaves = []
        for i in range(len(flat[0][0])):
            arrs = [flat[k][0][i] for k in range(len(local_shards))]
            shape = (d * local_b,) + arrs[0].shape[1:]
            leaves.append(jax.make_array_from_single_device_arrays(
                shape, sharding, arrs))
        sharded = jax.tree.unflatten(treedef, leaves)
        # 4. entry barrier, then the collective program (identical
        #    everywhere: same cap, same keys, same mesh)
        self.client.barrier(stage_id + ":enter", timeout)
        fn = SH.build_shuffle_program(self.mesh, keys, d, cap)
        result = fn(sharded)
        # 5. split back into per-local-device received batches
        out = []
        res_leaves, res_def = jax.tree.flatten(result)
        for dev in self.local_devices:
            dev_leaves = []
            for leaf in res_leaves:
                shard = next(s for s in leaf.addressable_shards
                             if s.device == dev)
                dev_leaves.append(shard.data)
            out.append(jax.tree.unflatten(res_def, dev_leaves))
        return out
