"""Device mesh + ICI collective shuffle layer.

[REF: sql-plugin/../shuffle/ucx/UCX.scala, RapidsShuffleServer/Client] —
re-designed as the SURVEY §2.4 inversion: the reference moves shuffle
blocks point-to-point over UCX (RDMA/NVLink); on TPU the idiomatic
transport is a **collective**: every shuffle stage is one SPMD program
`{hash-partition → all_to_all → local regroup}` over the ICI mesh
(`BASELINE.json` north star).  Multi-chip hardware is not available in
this environment, so the same code paths run on a virtual CPU mesh
(``--xla_force_host_platform_device_count=N``) in tests and are
dry-run-compiled by the driver via ``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.runtime.device import ensure_initialized

# jax promoted shard_map out of experimental in 0.6; support both so the
# collective layer runs on the full range of baked-in jax versions
try:
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map

SHUFFLE_AXIS = "shuffle"


def make_mesh(n_devices: Optional[int] = None,
              axis: str = SHUFFLE_AXIS) -> jax.sharding.Mesh:
    """1-D mesh over the first n devices (data+shuffle axis).

    SQL shuffle parallelism is 1-D by nature (partitions); wider meshes
    (e.g. per-chip model axes) are not needed for this engine — SURVEY
    §2.3: partition/shuffle parallelism IS the distribution mechanism.
    """
    ensure_initialized()
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return jax.sharding.Mesh(np.array(devs), (axis,))


def named_sharding(mesh: jax.sharding.Mesh,
                   spec: Optional[jax.sharding.PartitionSpec] = None
                   ) -> jax.sharding.NamedSharding:
    """Row-sharded ``NamedSharding`` over the mesh's shuffle axis — the
    one placement every exchange array (batch leaves, index tables,
    receive-count rows) uses.  ``spec`` overrides for replicated
    operands (``PartitionSpec()``)."""
    if spec is None:
        spec = jax.sharding.PartitionSpec(mesh.axis_names[0])
    return jax.sharding.NamedSharding(mesh, spec)


def all_to_all_shuffle(mesh: jax.sharding.Mesh, parts: jax.Array
                       ) -> jax.Array:
    """The ICI shuffle exchange.

    ``parts``: per-device partitioned rows, shape [D, P, ...] sharded on
    axis 0 (D = mesh size = P): parts[d, p] is the slice device d holds
    destined for device p.  Returns [D, P, ...] where out[d, p] is the
    slice device d received FROM device p — one ``lax.all_to_all`` riding
    ICI, the UCX-fetch analog.
    """
    axis = mesh.axis_names[0]

    def body(x):  # x: [1, P, ...] local block
        y = jax.lax.all_to_all(x[0], axis, split_axis=0, concat_axis=0,
                               tiled=False)
        return y[None]  # [1, P, ...]: row p = slice received from device p

    spec = jax.sharding.PartitionSpec(axis)
    return shard_map(body, mesh=mesh, in_specs=spec, out_specs=spec)(parts)
