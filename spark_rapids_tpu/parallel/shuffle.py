"""Batch-general ICI shuffle: SPMD repartitioning of whole DeviceBatches.

[REF: sql-plugin/../GpuShuffleExchangeExecBase.scala,
 RapidsShuffleInternalManagerBase.scala] — the collective inversion of the
reference's p2p UCX shuffle (SURVEY §2.4/§5.8): one shuffle stage is ONE
SPMD program over the mesh:

  {bit-exact Spark murmur3 pids → scatter-free partition layout
   → ``lax.all_to_all`` (ICI on hardware) → flat received batch}

Everything is static-shape and scatter-free (XLA lowers scatter to a
serial loop on TPU): rows are laid out per destination partition by a
stable ``lax.sort`` on pid followed by a gather from per-partition start
offsets (``searchsorted`` over the sorted pids).

Shapes are bucketed in two phases, the TPU-idiom answer to data-dependent
partition sizes: a cheap *count* program first measures the max rows any
(device, partition) cell holds; the *shuffle* program is then compiled
for the pow-2 bucket of that max (re-used across calls with the same
bucket).  Worst-case skew (every row to one partition) stays correct —
the bucket just grows.

The COMPILED exchange (``spark.rapids.tpu.exchange.mode``) splits the
stage seam differently — producer-side *prepare* vs seam-side
*boundary* — so the collective program itself carries no partitioning
work at all:

* ``build_prepare_program`` — once per accumulated batch: murmur3 pids,
  a sort-free stable within-partition rank (byte-packed uint64 chunked
  cumsum — 8 partition counters ride one u64 lane, so ranking costs two
  cumsums instead of a multi-operand ``lax.sort``), and ONE scatter that
  inverts the ranks into a per-destination gather index table.  Emits
  the [nparts·B] index table AND the per-partition counts in the same
  launch — no separate count program, no second pass over the keys.
* ``build_boundary_program`` — the only program on the stage seam:
  slice the index table to the agreed cap, clip-mode gather every leaf,
  one tiled ``lax.all_to_all`` over the mesh axis, receiver liveness
  from host-fed receive counts.  Pid-agnostic (the index table already
  encodes routing), so hash and range exchanges share one cached
  program per (schema, cap) — and its input buffers are DONATED: the
  sharded stage output is consumed by the wire, not copied across it.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.column import (
    DeviceBatch, DeviceColumn, round_up_pow2)
from spark_rapids_tpu.ops import hashing as HH
from spark_rapids_tpu.ops.expressions import Expression
from spark_rapids_tpu.parallel.mesh import shard_map
from spark_rapids_tpu.runtime import telemetry as TM

# one increment per SPMD program *build* — each build is a fresh XLA
# compilation candidate, so a growing rate flags shape-bucket churn
_TM_ICI_PROGRAMS = TM.REGISTRY.counter(
    "tpuq_ici_programs_built_total",
    "SPMD count/shuffle programs constructed (pre-compile)")
_TM_ICI_EX_PROGRAMS = TM.REGISTRY.counter(
    "tpuq_ici_exchange_programs_built_total",
    "compiled-exchange SPMD programs constructed (prepare + boundary)")


def _hash_f64_tpu_safe(data: jnp.ndarray, h: jnp.ndarray) -> jnp.ndarray:
    """Mix a float64 column into the running hash WITHOUT a 64-bit
    bitcast (the TPU x64-rewrite cannot compile one — probed on the real
    chip; ops/ordering.py carries the same constraint).

    The value is canonicalized (NaN → one NaN, -0.0 → 0.0 — Spark
    normalizes float keys before hash partitioning) and decomposed into
    f32 hi/lo parts whose u32 bit patterns feed the murmur3 long-mix.
    NOT bit-exact with Spark's hash of the raw f64 bits — irrelevant for
    partitioning, which only needs every participant to agree on pids
    (and f64 on TPU hardware is itself an f32 hi/lo pair, so the
    original bits don't exist on device anyway)."""
    isn = jnp.isnan(data)
    x = jnp.where(isn, jnp.zeros((), data.dtype), data)
    x = jnp.where(x == 0.0, jnp.zeros((), data.dtype), x)
    hi = x.astype(jnp.float32)
    lo = (x - hi.astype(data.dtype)).astype(jnp.float32)
    hi_b = jnp.where(isn, jnp.uint32(0x7FC00000),
                     HH.jax_bitcast(hi, jnp.uint32))
    lo_b = jnp.where(isn, jnp.uint32(0), HH.jax_bitcast(lo, jnp.uint32))
    h1 = HH._mix_h1(h, HH._mix_k1(lo_b, jnp), jnp)
    h1 = HH._mix_h1(h1, HH._mix_k1(hi_b, jnp), jnp)
    return HH._fmix(h1, 8, jnp)


def make_pid_fn(keys: Sequence[Expression], nparts: int,
                canon_int64: Sequence[bool] = (),
                seed: Optional[int] = None):
    """batch → int32 partition ids via the bit-exact Spark murmur3.

    ``seed`` overrides the Spark shuffle seed — join sub-partitioning
    re-hashes with a DIFFERENT seed so rows of one exchange partition
    spread across sub-partitions [REF: GpuSubPartitionHashJoin].

    ``canon_int64[i]`` widens key i's int-family column to int64 before
    hashing — needed when the two sides of a join carry different int
    widths (murmur3 of int32 and int64 differ for the same value; both
    exchanges must agree on a pid, Spark-exactness is moot for a
    mixed-width join Spark itself would cast).

    Float keys are normalized (-0.0 → 0.0, one NaN) before hashing:
    downstream operators treat the normalized values as one key
    (NormalizeFloatingNumbers), so equal keys MUST land on one device.
    """
    canon = tuple(canon_int64) or (False,) * len(keys)
    seed_v = HH.SEED if seed is None else seed

    def pids(batch: DeviceBatch) -> jnp.ndarray:
        h = jnp.full((batch.capacity,), jnp.uint32(seed_v), jnp.uint32)
        for e, widen in zip(keys, canon):
            c = e.eval_tpu(batch)
            dt = c.dtype
            data = c.data
            valid = c.valid_mask()
            if widen and not isinstance(dt, T.LongType):
                data, dt = data.astype(jnp.int64), T.LongT
            if isinstance(dt, T.DoubleType):
                h = jnp.where(valid, _hash_f64_tpu_safe(data, h), h)
                continue
            if isinstance(dt, T.FloatType):
                data = jnp.where(data == 0.0,
                                 jnp.zeros((), data.dtype), data)
            h = HH.hash_column((data, c.lengths), dt, h, valid, jnp)
        h_i32 = HH.jax_bitcast(h, jnp.int32)
        return HH.partition_ids_from_hash(h_i32, nparts, jnp)

    return pids


def _sorted_pids(batch: DeviceBatch, pid: jnp.ndarray, nparts: int
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stable sort rows by destination pid (dead rows → overflow bucket).

    Returns (sorted pid, permutation).  One 2-operand ``lax.sort``."""
    b = batch.capacity
    pid = jnp.where(batch.sel, pid, nparts).astype(jnp.int32)
    iota = jnp.arange(b, dtype=jnp.int32)
    pid_s, perm = jax.lax.sort((pid, iota), num_keys=2)
    return pid_s, perm


def _partition_bounds(pid_s: jnp.ndarray, nparts: int) -> jnp.ndarray:
    """starts/ends of each pid run in the sorted order: int32[nparts+1]."""
    probe = jnp.arange(nparts + 1, dtype=jnp.int32)
    return jnp.searchsorted(pid_s, probe, side="left").astype(jnp.int32)


def local_partition_counts(batch: DeviceBatch, pid: jnp.ndarray,
                           nparts: int) -> jnp.ndarray:
    """Live-row count per destination partition: int32[nparts]."""
    pid_s, _ = _sorted_pids(batch, pid, nparts)
    bounds = _partition_bounds(pid_s, nparts)
    return bounds[1:] - bounds[:-1]


def partition_layout(batch: DeviceBatch, pid: jnp.ndarray, nparts: int,
                     cap: int) -> DeviceBatch:
    """Local [B] batch → [nparts*cap] batch: slot (p, c) holds the c-th
    local row destined for partition p (dead beyond each count).

    Scatter-free: one sort + one gather.  Rows beyond ``cap`` per
    partition are silently dropped — callers MUST pick cap ≥ the counts
    (the count program exists for exactly this).
    """
    b = batch.capacity
    pid_s, perm = _sorted_pids(batch, pid, nparts)
    bounds = _partition_bounds(pid_s, nparts)
    starts, ends = bounds[:-1], bounds[1:]
    c_idx = jnp.arange(cap, dtype=jnp.int32)
    src = starts[:, None] + c_idx[None, :]               # [P, cap]
    live = src < ends[:, None]
    src_flat = jnp.clip(src.reshape(-1), 0, b - 1)
    row_idx = jnp.take(perm, src_flat)
    cols = tuple(c.gather(row_idx) for c in batch.columns)
    return DeviceBatch(batch.schema, cols, live.reshape(-1))


def exchange_collective(batch_laid: DeviceBatch, axis: str, nparts: int,
                        cap: int) -> DeviceBatch:
    """The wire: all_to_all every leaf of a [nparts*cap] laid-out batch.

    Device d's slot block p travels to device p; the result's block p
    holds rows received FROM device p.  Rides ICI on hardware."""
    def coll(x):
        x = x.reshape((nparts, cap) + x.shape[1:])
        y = jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                               tiled=False)
        return y.reshape((nparts * cap,) + y.shape[2:])

    return jax.tree.map(coll, batch_laid)


def range_pid_fn(orders):
    """batch, boundary-limbs → int32 partition ids by RANGE: each row's
    orderable key limbs lexicographically searchsorted against nparts-1
    sampled boundary rows [REF: GpuRangePartitioning.scala — there a
    sorted-table bound search on the CPU; here the same search runs
    vectorized on device, sharing the sort machinery's key encoding]."""
    def pids(batch: DeviceBatch, blimbs) -> jnp.ndarray:
        from spark_rapids_tpu.exec.join import _lex_search
        from spark_rapids_tpu.exec.sort import _encode_key_limbs
        limbs = _encode_key_limbs(batch, orders)
        bl = [jnp.asarray(b) for b in blimbs]
        return _lex_search(bl, limbs, "right").astype(jnp.int32)

    return pids


def build_range_count_program(mesh: jax.sharding.Mesh, orders,
                              nparts: int):
    """Phase-1 SPMD program for the RANGE exchange: per-device
    per-partition live-row counts.  Boundary limbs ride as traced,
    mesh-replicated arguments (data-dependent — never baked into the
    cached executable)."""
    axis = mesh.axis_names[0]
    pid_fn = range_pid_fn(orders)

    def step(batch: DeviceBatch, blimbs) -> jnp.ndarray:
        return local_partition_counts(batch, pid_fn(batch, blimbs),
                                      nparts)

    spec = jax.sharding.PartitionSpec(axis)
    rep = jax.sharding.PartitionSpec()
    _TM_ICI_PROGRAMS.inc()
    # jit-exempt: mesh-bound shard_map SPMD program, cached per exchange
    return jax.jit(shard_map(step, mesh=mesh, in_specs=(spec, rep),
                                 out_specs=spec))


def build_range_shuffle_program(mesh: jax.sharding.Mesh, orders,
                                nparts: int, cap: int):
    """Phase-2 SPMD program for the RANGE exchange: layout → all_to_all
    → flat received batch (partition p holds key range p)."""
    axis = mesh.axis_names[0]
    pid_fn = range_pid_fn(orders)

    def step(batch: DeviceBatch, blimbs) -> DeviceBatch:
        laid = partition_layout(batch, pid_fn(batch, blimbs), nparts,
                                cap)
        return exchange_collective(laid, axis, nparts, cap)

    spec = jax.sharding.PartitionSpec(axis)
    rep = jax.sharding.PartitionSpec()
    _TM_ICI_PROGRAMS.inc()
    # jit-exempt: mesh-bound shard_map SPMD program, cached per exchange
    return jax.jit(shard_map(step, mesh=mesh, in_specs=(spec, rep),
                                 out_specs=spec))


def build_count_program(mesh: jax.sharding.Mesh, keys, nparts: int,
                        canon_int64=()):
    """Phase-1 SPMD program: per-device per-partition live-row counts."""
    axis = mesh.axis_names[0]
    pid_fn = make_pid_fn(keys, nparts, canon_int64)

    def step(batch: DeviceBatch) -> jnp.ndarray:
        return local_partition_counts(batch, pid_fn(batch), nparts)

    spec = jax.sharding.PartitionSpec(axis)
    _TM_ICI_PROGRAMS.inc()
    # jit-exempt: mesh-bound shard_map SPMD program, cached per exchange
    return jax.jit(shard_map(step, mesh=mesh, in_specs=(spec,),
                                 out_specs=spec))


def build_shuffle_program(mesh: jax.sharding.Mesh, keys, nparts: int,
                          cap: int, canon_int64=()):
    """Phase-2 SPMD program: layout → all_to_all → flat received batch."""
    axis = mesh.axis_names[0]
    pid_fn = make_pid_fn(keys, nparts, canon_int64)

    def step(batch: DeviceBatch) -> DeviceBatch:
        laid = partition_layout(batch, pid_fn(batch), nparts, cap)
        return exchange_collective(laid, axis, nparts, cap)

    spec = jax.sharding.PartitionSpec(axis)
    _TM_ICI_PROGRAMS.inc()
    # jit-exempt: mesh-bound shard_map SPMD program, cached per exchange
    return jax.jit(shard_map(step, mesh=mesh, in_specs=(spec,),
                                 out_specs=spec))


# ---------------------------------------------------------------------------
# Compiled exchange: prepare (producer side) + boundary (stage seam)
# ---------------------------------------------------------------------------

# rows per ranking chunk: each destination's within-chunk count rides one
# byte lane of a packed uint64, so a chunk may hold at most 255 rows
_RANK_CHUNK = 128


def exchange_cap(max_count: int, local_b: int) -> int:
    """Wire-cell row capacity for a measured (device, partition) max.

    NOT the pow-2 ladder the rest of the shape plane uses: every padded
    row here is a row on the wire, and rounding 1.05× a bucket boundary
    up to the next power of two would nearly double the collective's
    bytes.  The exchange ladder steps at 1/32 of the enclosing pow-2
    bucket (≤ ~3.2% pad), which still bounds distinct boundary-program
    shapes to 32 per octave."""
    mc = max(int(max_count), 1)
    step = max(round_up_pow2(mc, 1) // 32, 8)
    return min(-(-mc // step) * step, local_b)


def _exchange_rank(pid: jnp.ndarray, sel: jnp.ndarray, nparts: int,
                   b: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stable within-partition rank of every live row + per-partition
    live counts — sort-free.

    Eight destinations pack into one uint64 (one byte lane each): an
    intra-chunk inclusive cumsum of the packed one-hot encodings counts
    all eight lanes at once, chunk totals unpack to int32 and a second
    (tiny, [b/CH, lanes]) cumsum yields chunk base offsets.  Dead rows
    encode as 0 — they advance no lane and get no rank.  Destinations
    beyond 8 run as additional packed groups."""
    ngroups = -(-nparts // 8)
    ch = min(_RANK_CHUNK, b)
    nch = b // ch
    ranks, counts = [], []
    for g in range(ngroups):
        lanes = min(8, nparts - 8 * g)
        lane = pid - 8 * g
        in_g = sel & (lane >= 0) & (lane < 8)
        lane_c = jnp.clip(lane, 0, 7).astype(jnp.uint64)
        enc = jnp.where(in_g, jnp.uint64(1) << (jnp.uint64(8) * lane_c),
                        jnp.uint64(0))
        chunks = enc.reshape(nch, ch)
        incl = jnp.cumsum(chunks, axis=1)
        shifts = jnp.uint64(8) * jnp.arange(lanes, dtype=jnp.uint64)
        tot = ((incl[:, -1][:, None] >> shifts[None, :])
               & jnp.uint64(0xFF)).astype(jnp.int32)      # [nch, lanes]
        base = jnp.cumsum(tot, axis=0) - tot              # chunk bases
        excl = incl - chunks
        lane_ch = lane_c.reshape(nch, ch)
        within = ((excl >> (jnp.uint64(8) * lane_ch))
                  & jnp.uint64(0xFF)).astype(jnp.int32)
        cbase = jnp.take_along_axis(
            base, jnp.clip(lane_ch.astype(jnp.int32), 0, lanes - 1),
            axis=1)
        ranks.append((within + cbase).reshape(b))
        counts.append(base[-1] + tot[-1])
    rank = ranks[0]
    for g in range(1, ngroups):
        rank = jnp.where(pid // 8 == g, ranks[g], rank)
    return rank, jnp.concatenate(counts)[:nparts]


def _prepare_index(batch: DeviceBatch, pid: jnp.ndarray, nparts: int
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(gather index table int32[nparts*B], live counts int32[nparts]).

    Slot (p, r) holds the source row of partition p's r-th live row
    (source order — the bit-identity contract), B beyond each count (a
    clip-gather sentinel).  ONE scatter builds the table: live rows
    write their slot, dead rows aim at distinct out-of-range slots and
    drop, so the write set is provably unique."""
    b = batch.capacity
    rank, counts = _exchange_rank(pid, batch.sel, nparts, b)
    iota = jnp.arange(b, dtype=jnp.int32)
    slot = jnp.where(batch.sel, pid * b + rank, nparts * b + iota)
    idx = jnp.full(nparts * b, b, jnp.int32).at[slot].set(
        iota, mode="drop", unique_indices=True)
    return idx, counts


def build_prepare_program(mesh: jax.sharding.Mesh, keys, nparts: int,
                          canon_int64=()):
    """Producer-side compiled-exchange program: per device, the gather
    index table + per-partition live counts, one launch, no sort."""
    axis = mesh.axis_names[0]
    pid_fn = make_pid_fn(keys, nparts, canon_int64)

    def step(batch: DeviceBatch):
        return _prepare_index(batch, pid_fn(batch), nparts)

    spec = jax.sharding.PartitionSpec(axis)
    _TM_ICI_EX_PROGRAMS.inc()
    # jit-exempt: mesh-bound shard_map SPMD program, cached per exchange
    return jax.jit(shard_map(step, mesh=mesh, in_specs=(spec,),
                             out_specs=(spec, spec)))


def build_range_prepare_program(mesh: jax.sharding.Mesh, orders,
                                nparts: int):
    """RANGE flavor of the prepare program: boundary limbs ride as
    traced, mesh-replicated arguments (data-dependent — never baked
    into the cached executable)."""
    axis = mesh.axis_names[0]
    pid_fn = range_pid_fn(orders)

    def step(batch: DeviceBatch, blimbs):
        return _prepare_index(batch, pid_fn(batch, blimbs), nparts)

    spec = jax.sharding.PartitionSpec(axis)
    rep = jax.sharding.PartitionSpec()
    _TM_ICI_EX_PROGRAMS.inc()
    # jit-exempt: mesh-bound shard_map SPMD program, cached per exchange
    return jax.jit(shard_map(step, mesh=mesh, in_specs=(spec, rep),
                             out_specs=(spec, spec)))


def build_boundary_program(mesh: jax.sharding.Mesh, nparts: int,
                           cap: int, donate: bool = True):
    """The stage seam: ONE launch moves every leaf across the mesh.

    Pid-agnostic — the prepare program's index table already encodes
    routing, so hash and range exchanges share one cached boundary per
    (schema, cap).  Per device: slice the index table to ``cap`` rows
    per destination, clip-mode gather each leaf ([nparts·cap] cells,
    the sentinel clips to a junk row hidden by the receive mask), one
    tiled ``lax.all_to_all``, then liveness from the host-fed receive
    counts (crecv[p][s] = rows partition p receives from source s —
    known host-side from prepare's counts, so no extra collective).

    ``donate`` hands the input batch's buffers to XLA: the stage output
    backing the exchange is consumed by the wire instead of co-resident
    with it.  Donated buffers are GONE after a dispatch that reached
    XLA — the ``collective`` failure-domain injector fires BEFORE
    dispatch, so transient-retry semantics hold; a real mid-collective
    fault escalates past retry to the host-transport degrade, which
    re-executes the child."""
    axis = mesh.axis_names[0]

    def step(batch: DeviceBatch, idx: jnp.ndarray, crecv: jnp.ndarray
             ) -> DeviceBatch:
        table = jax.lax.slice(idx.reshape(nparts, -1), (0, 0),
                              (nparts, cap)).reshape(nparts * cap)

        def move(x):
            g = jnp.take(x, table, axis=0, mode="clip")
            return jax.lax.all_to_all(g, axis, 0, 0, tiled=True)

        cols = jax.tree.map(move, batch.columns)
        recv = crecv.reshape(nparts)
        live = (jnp.arange(cap, dtype=jnp.int32)[None, :]
                < recv[:, None]).reshape(nparts * cap)
        return DeviceBatch(batch.schema, cols, live)

    spec = jax.sharding.PartitionSpec(axis)
    prog = shard_map(step, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)
    _TM_ICI_EX_PROGRAMS.inc()
    # jit-exempt: mesh-bound shard_map SPMD program, cached per exchange
    return jax.jit(prog, donate_argnums=(0,) if donate else ())


def shard_batch(mesh: jax.sharding.Mesh, batch: DeviceBatch) -> DeviceBatch:
    """Place a global batch row-sharded across the mesh (capacity must be
    divisible by the mesh size)."""
    from spark_rapids_tpu.parallel.mesh import named_sharding
    return jax.device_put(batch, named_sharding(mesh))


def split_to_spillables(batches, ids_fn, nbuckets: int, mgr, key: tuple,
                        aux=None, chunk_rows: int = 1 << 20):
    """Bucket-split batches and register each slice as an unreserved
    spillable (the out-of-core sort/join spill pool).

    Dispatch-bounded design: the naive per-(batch × bucket) eager mask/
    compact/sync loop costs O(batches · buckets) kernel dispatches AND
    host syncs — ~2k tunnel round trips on TPC-H q10, the breadth-query
    killer.  Instead the batches coalesce into ≤``chunk_rows`` chunks
    and each chunk runs ONE cached counting-sort kernel (rows grouped
    by bucket id + per-bucket counts), ONE [nbuckets] host sync, and
    one cached gather per non-empty bucket (cut kernels cached per
    pow-2 slice size, so the compile set is tiny and shared).

    ``key`` must fingerprint ``ids_fn``'s behavior (the kernels are
    cached on it); data-dependent state (e.g. range bounds) must ride
    ``aux`` — it is passed to ``ids_fn(batch, aux)`` as a traced
    argument, NOT baked into the compiled kernel.

    CONSUMES ``batches`` in place (front pop): an upstream generator
    frame usually still references the same list object, so an in-place
    drain is the only way the original batches actually free as their
    slices are carved — `del` in the callee would just drop an alias.
    Chunk coalescing keeps concat order identical to the in-core path
    (the counting sort is stable, so intra-bucket order is input
    order)."""
    from spark_rapids_tpu.columnar.column import DeviceBatch, compact
    from spark_rapids_tpu.exec.basic import concat_device_batches
    from spark_rapids_tpu.runtime.kernel_cache import (
        cached_kernel, fingerprint)
    from spark_rapids_tpu.runtime.memory import SpillableBatch
    out = [[] for _ in range(nbuckets)]
    if not batches:
        return out
    schema = batches[0].schema
    base_key = ("split", nbuckets, fingerprint(schema)) + tuple(key)
    # this path usually runs AFTER a RetryOOM: the chunk (plus its
    # sorted copy) must fit the arbiter budget, so cap chunk rows by
    # the estimated row width
    row_b = max(1, batches[0].nbytes() // max(batches[0].capacity, 1))
    budget_rows = max(1024, int(mgr.budget) // (4 * row_b))
    chunk_rows = min(chunk_rows,
                     1 << max(10, budget_rows.bit_length() - 1))

    def build_sort():
        def run(m, aux):
            pid = ids_fn(m, aux)
            pid_s, perm = _sorted_pids(m, pid, nbuckets)
            bounds = _partition_bounds(pid_s, nbuckets)
            cols = tuple(c.gather(perm) for c in m.columns)
            sel = (jnp.arange(m.capacity, dtype=jnp.int32)
                   < bounds[-1])
            counts = bounds[1:] - bounds[:-1]
            return DeviceBatch(m.schema, cols, sel,
                               compacted=True), counts
        return run

    def build_cut(size):
        def run(m, lo, count):
            idx = jnp.clip(lo + jnp.arange(size, dtype=jnp.int32),
                           0, m.capacity - 1)
            cols = tuple(c.gather(idx) for c in m.columns)
            sel = jnp.arange(size, dtype=jnp.int32) < count
            return DeviceBatch(m.schema, cols, sel, compacted=True)
        return run

    while batches:
        chunk, acc = [], 0
        while batches and (not chunk
                           or acc + batches[0].capacity <= chunk_rows):
            b = compact(batches.pop(0))
            chunk.append(b)
            acc += b.capacity
        merged = (chunk[0] if len(chunk) == 1 else
                  concat_device_batches(schema, chunk))
        del chunk
        sort_fn = cached_kernel(("split_sort",) + base_key, build_sort)
        laid, counts = sort_fn(merged, aux)
        counts = np.asarray(counts)  # the chunk's ONE host sync
        offs = np.concatenate([[0], np.cumsum(counts)])
        for i in range(nbuckets):
            n = int(counts[i])
            if n == 0:
                continue
            size = max(8, 1 << (n - 1).bit_length())
            cut_fn = cached_kernel(
                ("split_cut", size) + base_key,
                lambda s=size: build_cut(s))
            part = cut_fn(laid, int(offs[i]), n)
            sp = SpillableBatch(part, mgr, reserve=False)
            # the split KNOWS each slice's live count — downstream
            # concats read it instead of paying a device round trip
            sp.live_rows = n
            out[i].append(sp)
        del laid, merged
    return out


def slice_batch(batch: DeviceBatch, lo: int, cap: int) -> DeviceBatch:
    """Row-slice [lo, lo+cap) of every leaf (static bounds)."""
    def cut(x):
        return x[lo:lo + cap]

    cols = tuple(
        DeviceColumn(c.dtype, cut(c.data),
                     None if c.validity is None else cut(c.validity),
                     None if c.lengths is None else cut(c.lengths),
                     None if c.evalid is None else cut(c.evalid))
        for c in batch.columns)
    return DeviceBatch(batch.schema, cols, cut(batch.sel))
