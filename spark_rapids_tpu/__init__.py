"""spark_rapids_tpu — TPU-native columnar SQL accelerator.

A from-scratch, TPU-first re-design of the RAPIDS Accelerator for Apache
Spark (reference: JustPlay/spark-rapids).  Where the reference pairs a JVM
plan-rewrite plugin with cuDF/CUDA kernels over JNI, this framework pairs a
Python plan-rewrite engine with XLA/Pallas kernels over JAX, device columns
are XLA buffers instead of cuDF columns, and shuffle repartitions columnar
batches over ICI via ``lax.all_to_all`` instead of UCX point-to-point.

Layer map (mirrors SURVEY.md §1):

* ``plan/``     — L5: overrides/rewrite engine, type checking, transitions
* ``exec/``     — L4: columnar physical operators (TPU + CPU-fallback)
* ``ops/``      — L4: expression library lowered to jax/XLA
* ``io/``       — L4: Parquet/CSV/JSON scan + write framing
* ``shuffle/``  — L3: partitioning, serialization, shuffle managers (host + ICI)
* ``runtime/``  — L2: device manager, semaphore, spill, OOM-retry
* ``columnar/`` — L2: column/batch data model (static-shape, bucketed)
* ``parallel/`` — mesh/collective layer (ICI/DCN)
* ``sql/``      — L7: DataFrame/SQL user API (benchmark pipelines live
  in ``bench.py`` at the repo root — TPC-H through the public API)

Reference parity citations use the form ``[REF: <upstream path> :: <Symbol>]``
per SURVEY.md (the reference mount was empty; citations are upstream search
keys).
"""

__version__ = "0.1.0"

from spark_rapids_tpu.conf import RapidsConf  # noqa: F401
from spark_rapids_tpu.runtime.device import ensure_initialized  # noqa: F401
