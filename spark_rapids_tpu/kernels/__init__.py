"""Kernel plane — backend selection and dispatch for the fused kernels.

[REF: the reference picks between libcudf CUDA kernels and a JIT'd
 fallback per operator; this plane is the TPU analog, conf-selected.]

Three backends per kernel (hash join, segmented sort, hash agg),
``spark.rapids.tpu.kernel.backend``:

* ``jnp``    — the pure jax.numpy reference (bit-exact baseline);
* ``fused``  — single-program XLA kernels built on the hash-grouped /
  tiled-rank layouts (kernels/hash_layout.py) — no scatter, no extra
  host round-trips;
* ``pallas`` — fused structure with the hash mixing loop as a Mosaic
  VPU kernel (kernels/pallas_backend.py); TPU only;
* ``auto``   — pallas on TPU; off-TPU, fused for join/agg (measured
  faster on the CPU harness too) but jnp for sort, whose tiled form
  only pays where sort operand count dominates (see resolve()).

Degrade ladder: ``pallas → fused → jnp``.  Rungs descend on a
detected 64-bit hash collision (the kernels are exact-or-fallback —
see hash_layout), via the ``ok`` scalar every non-jnp kernel returns,
or when the rung declares itself ineligible at trace time (``ok`` is
None: unhashable keys ran the reference inside the rung).  Execution
failures are NOT a ladder concern: every rung runs through
``cached_kernel``'s execute chokepoint, which already retries
transients, trips the per-op breaker, and host-degrades per the PR 3
policy — an error that escapes that machinery is domain-tagged and
must surface, not silently produce a different rung's answer.
Fallbacks count ``tpuq_kernel_fallback_total``; every accepted
dispatch counts
``tpuq_kernel_dispatch_total{backend}`` with the backend that actually
produced the result, which is also recorded on the op's stats row
(``kernel_backend`` in ``df.explain("analyze")``).

The module-global policy mirrors runtime/shapes.py: the session
snapshots conf once at init (sql/session.py) and hot paths read one
attribute.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Optional

from spark_rapids_tpu.runtime import telemetry as TM

BACKENDS = ("auto", "pallas", "fused", "jnp")

_LADDERS = {
    "pallas": ("pallas", "fused", "jnp"),
    "fused": ("fused", "jnp"),
    "jnp": ("jnp",),
}

_TM_DISPATCH = TM.REGISTRY.labeled_counter(
    "tpuq_kernel_dispatch_total",
    "kernel-plane dispatches by the backend that produced the result",
    label="backend")
_TM_FALLBACK = TM.REGISTRY.labeled_counter(
    "tpuq_kernel_fallback_total",
    "kernel dispatches that descended the backend ladder (hash "
    "collision, unhashable keys, or a failed rung)",
    label="kernel")


@dataclasses.dataclass(frozen=True)
class KernelPolicy:
    """One immutable kernel-plane policy (the conf snapshot, parsed)."""

    backend: str = "auto"   # spark.rapids.tpu.kernel.backend
    pump_depth: int = 2     # spark.rapids.tpu.exec.pumpDepth


_POLICY = KernelPolicy()
_LOCK = threading.Lock()


def configure(conf) -> KernelPolicy:
    """Install the policy from a RapidsConf snapshot (session init)."""
    from spark_rapids_tpu import conf as C
    pol = KernelPolicy(
        backend=str(conf.get(C.KERNEL_BACKEND)).lower(),
        pump_depth=int(conf.get(C.EXEC_PUMP_DEPTH)))
    global _POLICY
    with _LOCK:
        _POLICY = pol
    return pol


def current_policy() -> KernelPolicy:
    return _POLICY


def resolve(kernel: str, supports_pallas: bool = True) -> str:
    """Conf backend → the concrete rung this dispatch starts from.

    ``auto`` means pallas on TPU, fused elsewhere; an explicit
    ``pallas`` off-TPU (or for a kernel with no pallas rung yet)
    degrades statically to fused — the run-time ladder handles only
    run-time failures.
    """
    be = _POLICY.backend
    if be == "auto":
        from spark_rapids_tpu.kernels import pallas_backend as PB
        if PB.available():
            be = "pallas"
        elif kernel == "sort":
            # the tiled sort trades extra rank-merge arithmetic for
            # fewer sort operands — a win on TPU where operand count
            # dominates compile AND run cost, a measured ~12x loss on
            # the CPU harness (KERNEL_BENCH @128k) — so auto takes it
            # only on the real chip; explicit `fused` still forces it
            be = "jnp"
        else:
            be = "fused"
    if be == "pallas":
        from spark_rapids_tpu.kernels import pallas_backend as PB
        if not supports_pallas or not PB.available():
            be = "fused"
    return be


def count(kernel: str, backend: str, node=None) -> None:
    """Record one accepted dispatch: telemetry + the op's stats row."""
    _TM_DISPATCH.inc(backend)
    if node is not None:
        from spark_rapids_tpu.runtime import stats
        st = stats.current()
        if st is not None:
            st.node_stats(node).set_kernel_backend(backend)


def dispatch(kernel: str, backend: str,
             runner: Callable[[str], Callable], node=None):
    """Run one kernel down the degrade ladder; returns its payload.

    ``runner(be)`` returns a zero-arg callable producing
    ``(payload, ok)``: ``ok`` is a device bool scalar from the non-jnp
    rungs (False = hash collision → descend), or None when the rung
    itself ran the reference path (unhashable keys) or IS the jnp
    reference.  The one host sync here (``bool(ok)``) is the fused
    kernels' price of exactness; it reads a scalar that is ready as
    soon as the layout phase finishes, not after the full result.

    Exceptions propagate: each rung already executes under
    ``cached_kernel``'s retry/breaker/degrade chokepoint, so anything
    that escapes it is a domain-tagged failure the query must see —
    swallowing it here would let an injected/terminal device fault
    masquerade as a successful fallback.
    """
    for be in _LADDERS[backend]:
        call = runner(be)
        if be == "jnp":
            payload, _ = call()
            count(kernel, "jnp", node)
            return payload
        payload, okf = call()
        if okf is None:
            # the rung declared itself ineligible at trace time and
            # ran the reference computation inside its own kernel
            count(kernel, "jnp", node)
            return payload
        if not bool(okf):
            _TM_FALLBACK.inc(kernel)
            continue
        count(kernel, be, node)
        return payload
    raise AssertionError(f"kernel ladder for {backend!r} has no floor")
