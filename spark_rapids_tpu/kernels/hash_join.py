"""Fused build+probe hash join — match ranges over ONE hash limb.

The jnp reference (exec.join._match_ranges) stably sorts the build
side by the full (exclusion-flag + key-limbs) encoding and runs TWO
lexicographic bisections (lower + upper bound) over all of those limbs
per probe row.  The fused kernel collapses both costs:

* build: sort ONE uint64 limb — the 63-bit key hash with the exclusion
  flag in the top bit, so excluded (dead/null) rows sort after every
  probe value and can never be landed on;
* probe: ONE single-limb lower-bound bisection; the upper bound is
  free — a segmented count over the build side pre-computes every hash
  run's length, and the probe just gathers it at the run start;
* exactness: the probed run start's FULL key limbs are gathered and
  compared against the probe row (a hash-only miss yields m = 0, never
  a wrong match), and a build-side adjacent-pair scan detects the one
  case that can't be repaired locally — two distinct live keys sharing
  a 64-bit hash — surfacing ``ok = False`` for the dispatcher's exact
  fallback (see hash_layout.hash_group_layout's argument for why
  adjacency detection is complete).

Bit-identity: within one hash run the stable sort keeps build rows in
original-index order — the same order the reference's key-sorted perm
gives inside a key group — so (m, lo, perm) drive exec.join._merge_join
to byte-identical materialized output.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.kernels import hash_layout as HL
from spark_rapids_tpu.ops import ordering as ORD

# numpy scalar: module import stays safe before jax_enable_x64 flips on
_TOP = np.uint64(1 << 63)


def match_fused(l_limbs: List[jnp.ndarray], r_limbs: List[jnp.ndarray],
                r_excl: jnp.ndarray, use_pallas: bool = False
                ) -> Optional[Tuple[jnp.ndarray, jnp.ndarray,
                                    jnp.ndarray, jnp.ndarray]]:
    """(m, lo, perm, ok) under exec.join._match_ranges' contract, or
    None when the key limbs are unhashable (raw-f64 limb — the caller
    stays on the exact reference; static per kernel instance).

    ``l_limbs``/``r_limbs`` are the fused key limbs WITHOUT the
    exclusion flag (it rides the hash limb's top bit here); left-side
    liveness masking stays with the caller, as in the reference.
    """
    if not HL.limbs_hashable(l_limbs + r_limbs):
        return None
    n = int(r_excl.shape[0])
    h_r = HL.hash_limbs(r_limbs, use_pallas=use_pallas) >> jnp.uint64(1)
    build_limb = jnp.where(r_excl, h_r | _TOP, h_r)
    sorted_hs, perm = ORD.sort_by_keys([build_limb])
    sorted_h = sorted_hs[0]
    rl_s = [jnp.take(l, perm) for l in r_limbs]

    # hash-run structure on the build side (run start + run length)
    run_start = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_h[1:] != sorted_h[:-1]])
    rlen = HL.run_lengths(run_start)

    # probe: one single-limb bisection, counts gathered at the run start
    h_q = HL.hash_limbs(l_limbs, use_pallas=use_pallas) >> jnp.uint64(1)
    lo = HL.lower_bound(sorted_h, h_q)
    loc = jnp.clip(lo, 0, n - 1)
    hit = (jnp.take(sorted_h, loc) == h_q) & (lo < n)
    # exact verification: run-start key must equal the probe key
    for rl, ll in zip(rl_s, l_limbs):
        hit = hit & (jnp.take(rl, loc) == ll)
    m = jnp.where(hit, jnp.take(rlen, loc), 0)

    # 64-bit collision between two distinct LIVE keys → exact fallback
    excl_s = jnp.take(r_excl, perm)
    key_neq = HL._adjacent_neq(rl_s)
    live_pair = jnp.concatenate(
        [jnp.zeros((1,), jnp.bool_), (~excl_s[1:]) & (~excl_s[:-1])])
    ok = ~jnp.any((~run_start) & key_neq & live_pair)
    return m, lo, perm, ok
