"""Fused multi-column hash aggregation — single-pass hash group layout.

The jnp reference (exec.aggregate.segment_groupby) stably sorts the
full fused key encoding (up to GROUP_HASH_LIMB_CAP limbs, or a 2-limb
128-bit murmur for wide tuples) and diffs adjacent sorted rows for
group boundaries.  The fused backend replaces that multi-operand sort
with hash_layout.hash_group_layout: ONE 64-bit hash limb sorted, full
keys compared only between ADJACENT sorted rows — the same downstream
segmented scans then reduce the values.  Group ORDER under the fused
layout is hash order, not key order; Spark leaves a hash aggregate's
output order undefined, and the engine's merge passes re-group by key,
so only the layout — never the group content — differs from the
reference.  A 64-bit collision (distinct keys, same hash) is detected
exactly and surfaces as ``ok = False`` for the dispatcher's fallback
to the sort-based reference.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp

from spark_rapids_tpu.kernels import hash_layout as HL


def group_layout_fused(key_limbs: List[jnp.ndarray],
                       use_pallas: bool = False
                       ) -> Optional[Tuple[jnp.ndarray, List[jnp.ndarray],
                                           jnp.ndarray, jnp.ndarray]]:
    """(perm, sorted_key_limbs, boundary, ok) for a grouped batch, or
    None when the key limbs are unhashable (raw-f64 limb: DoubleType
    grouping keys stay on the exact reference; static per instance).

    ``key_limbs`` is ops.ordering.group_sort_limbs' KEY limb set — the
    dead-row flag is fused into the first limb, so dead rows land in
    their own hash groups; the caller's live-row masking (num_groups,
    compaction rank) needs no change.
    """
    if not HL.limbs_hashable(key_limbs):
        return None
    perm, kl_s, boundary, _, ok = HL.hash_group_layout(
        key_limbs, use_pallas=use_pallas)
    return perm, kl_s, boundary, ok
