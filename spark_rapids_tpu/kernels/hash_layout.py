"""Shared device primitives of the kernel plane.

[REF: libcudf's join/groupby kernels share one hashing core
 (``cudf::hashing::detail``) so the build side of a join and the probe
 table of a group-by agree bit-for-bit; this module is the TPU analog.]

Everything here is DEVICE code traced inside ``cached_kernel`` builders:
no host materialization, no data-dependent Python control flow (the
``kernel-purity`` lint rule gates exactly that).  The core primitive is
the **hash-grouped layout**: instead of stably sorting the full
multi-limb key encoding (sort operand count is the dominant TPU compile
AND run cost — see ops/ordering.py), rows are stably sorted by ONE
64-bit hash limb and group boundaries are recovered by comparing the
full key limbs of adjacent sorted rows.  A 64-bit collision between
distinct keys in the same batch is detected exactly (any offending pair
is adjacent after the hash sort) and surfaces as ``ok = False`` so the
dispatcher can fall back to the exact sort-based reference — the fused
backends are *probabilistically fast, deterministically correct*.

The hash itself is computed entirely in uint32 arithmetic (two parallel
murmur3-finalizer lanes with cross-mixing): TPU has no native 64-bit
path, and keeping the mix 32-bit makes the Pallas variant in
pallas_backend.py a line-for-line transcription.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

# murmur3 fmix32 constants — the exact-fallback ladder makes hash
# quality a latency knob, not a correctness one
_C1 = 0x85EBCA6B
_C2 = 0xC2B2AE35
_SEED_HI = 0x9E3779B9
_SEED_LO = 0x85EBCA77


def _fmix32(x: jnp.ndarray) -> jnp.ndarray:
    """murmur3 32-bit finalizer (wrapping uint32 arithmetic)."""
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(_C1)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(_C2)
    x = x ^ (x >> jnp.uint32(16))
    return x


def mix_rounds(hi: jnp.ndarray, lo: jnp.ndarray,
               wh: jnp.ndarray, wl: jnp.ndarray
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fold one 64-bit word (as two u32 lanes) into the running state.

    Two fmix32 lanes with cross-feedback: each output bit depends on
    every input bit of both words after the two rounds.  Pure uint32
    ops — this is the function pallas_backend.hash_pairs transcribes.
    """
    hi = hi ^ wh
    lo = lo ^ wl
    hi = _fmix32(hi + lo + jnp.uint32(_SEED_HI))
    lo = _fmix32(lo + hi + jnp.uint32(_SEED_LO))
    return hi, lo


def limbs_hashable(limbs: List[jnp.ndarray]) -> bool:
    """Trace-time gate: the hash path needs unsigned-integer limbs.

    A raw float64 limb (DoubleType keys ride one — no 64-bit bitcast
    compiles on TPU, see ops/ordering.py) cannot be hashed without the
    bitcast the encoding exists to avoid, so such key sets stay on the
    exact sort-based reference.  Static per kernel instance: limb
    dtypes are schema-determined, so this never retraces.
    """
    return all(jnp.issubdtype(l.dtype, jnp.unsignedinteger)
               for l in limbs)


def split_u64(limb: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """uint64 limb → (hi, lo) uint32 lanes (shift+convert, no bitcast)."""
    l64 = limb.astype(jnp.uint64)
    return ((l64 >> jnp.uint64(32)).astype(jnp.uint32),
            l64.astype(jnp.uint32))


def hash_limbs(limbs: List[jnp.ndarray],
               use_pallas: bool = False) -> jnp.ndarray:
    """64-bit hash of a row's fused key limbs, as a uint64 array.

    ``use_pallas`` routes the mixing loop through the Pallas VPU kernel
    (TPU backends); the jnp form is the bit-identical reference.
    """
    if use_pallas:
        from spark_rapids_tpu.kernels import pallas_backend as PB
        his = jnp.stack([split_u64(l)[0] for l in limbs])
        los = jnp.stack([split_u64(l)[1] for l in limbs])
        hi, lo = PB.hash_pairs(his, los)
    else:
        n = limbs[0].shape[0]
        hi = jnp.zeros((n,), jnp.uint32)
        lo = jnp.zeros((n,), jnp.uint32)
        for l in limbs:
            wh, wl = split_u64(l)
            hi, lo = mix_rounds(hi, lo, wh, wl)
    return (hi.astype(jnp.uint64) << jnp.uint64(32)) | lo.astype(
        jnp.uint64)


def seg_scan(values: jnp.ndarray, boundary: jnp.ndarray,
             op) -> jnp.ndarray:
    """Inclusive segmented scan (same combiner shape as
    exec.aggregate.segmented_scan, local so the kernel plane stays a
    leaf below the exec layer)."""
    def comb(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, op(av, bv)), af | bf
    v, _ = jax.lax.associative_scan(comb, (values, boundary))
    return v


def run_lengths(boundary: jnp.ndarray) -> jnp.ndarray:
    """Per-row length of the row's run (``boundary`` marks run starts).

    Forward segmented count, then a reversed keep-first scan broadcasts
    each run's final count back over the whole run — scatter-free (XLA
    scatter lowers to a serial loop on TPU).
    """
    n = boundary.shape[0]
    rn = seg_scan(jnp.ones((n,), jnp.int32), boundary, jnp.add)
    is_end = jnp.concatenate([boundary[1:], jnp.ones((1,), jnp.bool_)])
    filled = seg_scan(rn[::-1], is_end[::-1], lambda a, b: a)
    return filled[::-1]


def _adjacent_neq(limbs: List[jnp.ndarray]) -> jnp.ndarray:
    """row i differs from row i-1 in any limb (row 0 → False)."""
    n = limbs[0].shape[0]
    neq = jnp.zeros((n,), jnp.bool_)
    for l in limbs:
        neq = neq | jnp.concatenate(
            [jnp.zeros((1,), jnp.bool_), l[1:] != l[:-1]])
    return neq


def lower_bound(sorted_limb: jnp.ndarray, queries: jnp.ndarray,
                le: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """First index whose entry is >= the query (or > when ``le[q]``).

    Fixed-step branchless bisection (same shape as exec.join._lex_search
    but over ONE limb — the whole point of the hash layout).  ``le`` is
    a per-query flag switching to upper-bound counting.
    """
    import math
    n = int(sorted_limb.shape[0])
    steps = max(1, int(math.ceil(math.log2(max(n, 2)))) + 1)
    lo = jnp.zeros(queries.shape, jnp.int32)
    hi = jnp.full(queries.shape, n, jnp.int32)
    for _ in range(steps):
        mid = (lo + hi) >> 1
        v = jnp.take(sorted_limb, jnp.clip(mid, 0, n - 1))
        go_right = v < queries
        if le is not None:
            go_right = go_right | (le & (v == queries))
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right, hi, mid)
    return lo


def hash_group_layout(key_limbs: List[jnp.ndarray],
                      use_pallas: bool = False):
    """Hash-grouped row layout: the fused group-by/build-side core.

    Returns ``(perm, sorted_key_limbs, boundary, sorted_hash, ok)``:
    rows stably ordered by the 64-bit key hash (``perm``), group starts
    under that order (``boundary``, from FULL-key adjacent comparison),
    and ``ok`` — False iff two adjacent sorted rows share the hash but
    not the key, i.e. a 64-bit collision made distinct keys
    non-contiguous.  Any such pair is adjacent after the hash sort, so
    the detection is exact; callers must fall back to the sort-based
    reference when ``ok`` is False (probability ~n²/2⁶⁴ per batch).

    Caller contract: ``limbs_hashable(key_limbs)`` is True, and the
    limbs encode the full grouping equivalence (nulls flagged, NaNs
    canonicalized, -0.0 normalized — ops/ordering.py does all three).
    """
    from spark_rapids_tpu.ops import ordering as ORD
    h = hash_limbs(key_limbs, use_pallas=use_pallas)
    (sorted_h,), perm = ORD.sort_by_keys([h])
    kl_s = [jnp.take(l, perm) for l in key_limbs]
    same_h = jnp.concatenate([jnp.zeros((1,), jnp.bool_),
                              sorted_h[1:] == sorted_h[:-1]])
    key_neq = _adjacent_neq(kl_s)
    boundary = key_neq.at[0].set(True)
    ok = ~jnp.any(same_h & key_neq)
    return perm, kl_s, boundary, sorted_h, ok
