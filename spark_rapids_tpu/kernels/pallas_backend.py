"""Pallas (Mosaic) kernels — the ``pallas`` rung of the backend ladder.

[REF: the reference's hot operators are hand-written CUDA in libcudf;
 PAPER.md's blueprint maps that slot to Pallas on TPU.]

What is Pallas today vs. the fused-XLA rung: the hash-grouped layout's
mixing loop runs as a hand-scheduled VPU kernel with the limb block
resident in VMEM (``hash_pairs``), while the stable sort and the
segmented scans around it stay XLA-HLO — Mosaic has no vectorized
VMEM gather on current chips, so a full open-addressing build+probe
kernel is the roadmap item, not this PR.  The kernel is pure uint32
arithmetic (element-wise shifts/mults/xors — exactly the VPU's lane
ops) and transcribes ``hash_layout.mix_rounds`` line for line, so the
``pallas`` and ``fused`` rungs are bit-identical by construction; the
interpret-mode test in tests/test_kernels.py pins that on CPU.

Never imported on the hot path off-TPU: the dispatcher resolves
``pallas → fused`` when ``jax.default_backend() != "tpu"``, and any
lowering failure on-TPU trips the PR 3 breaker and degrades.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

# rows per grid step: one VREG-friendly lane block, small enough that
# (limbs × block) stays far under VMEM even for wide key sets
BLOCK_ROWS = 4096


def available() -> bool:
    """Pallas rung usable here? (TPU only — CPU/GPU degrade to fused.)"""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def hash_pairs(his: jnp.ndarray, los: jnp.ndarray,
               interpret: bool = False
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Mix L 64-bit words (as [L, n] u32 lane pairs) per row → (hi, lo).

    Grid over row blocks; each step loads its [L, BLOCK] slab into VMEM
    and runs the static-L mixing loop entirely on the VPU.  Bit-equal
    to the jnp loop in ``hash_layout.hash_limbs`` (same u32 ops in the
    same order).  ``interpret=True`` runs the kernel on the host for
    the CPU bit-identity test.
    """
    from jax.experimental import pallas as pl
    limbs, n = his.shape
    blk = min(BLOCK_ROWS, n)
    if n % blk:
        # capacities are pow2 (or sums of pow2s ≥ 16) so this only
        # trips on tiny probe shapes — shrink to the exact size
        blk = n

    def kernel(hi_ref, lo_ref, oh_ref, ol_ref):
        from spark_rapids_tpu.kernels.hash_layout import mix_rounds
        h = jnp.zeros((blk,), jnp.uint32)
        l = jnp.zeros((blk,), jnp.uint32)
        for j in range(limbs):  # static: unrolled into straight VPU ops
            h, l = mix_rounds(h, l, hi_ref[j, :], lo_ref[j, :])
        oh_ref[:] = h
        ol_ref[:] = l

    oh, ol = pl.pallas_call(
        kernel,
        grid=(n // blk,),
        in_specs=[pl.BlockSpec((limbs, blk), lambda i: (0, i)),
                  pl.BlockSpec((limbs, blk), lambda i: (0, i))],
        out_specs=[pl.BlockSpec((blk,), lambda i: (i,)),
                   pl.BlockSpec((blk,), lambda i: (i,))],
        out_shape=[jax.ShapeDtypeStruct((n,), jnp.uint32),
                   jax.ShapeDtypeStruct((n,), jnp.uint32)],
        interpret=interpret,
    )(his, los)
    return oh, ol
