"""Segmented sort — bucket-local key ranking, merged by rank.

The jnp reference (``ops.ordering.sort_by_keys``) is ONE global
``lax.sort`` over (limbs…, iota): correct, but its compile and run cost
grow with operand count × full batch length.  The fused backend
exploits the shape plane's static row buckets: a bucket splits into a
fixed number of contiguous TILES, each tile sorts locally (one 2-D
``lax.sort`` along the tile axis — the per-tile sorts are one fused
device op, not a loop), and every row's GLOBAL rank is recovered by
counting, per foreign tile, how many of its rows precede this row —
a branchless single-limb-at-a-time bisection per tile, not a second
multi-operand global sort.  One final two-operand sort inverts the
rank permutation (scatter-free: XLA scatter serializes on TPU).

Stability (and therefore bit-identity with the reference) falls out of
the merge rule: tiles are contiguous ascending index ranges, so a tied
row in an earlier tile ALWAYS precedes one in a later tile — earlier
tiles count ties (upper bound), later tiles don't (lower bound), and
within a tile the local sort is iota-stabilized.  The resulting rank is
exactly the row's position under the reference's stable sort, so the
returned permutation is identical bit for bit.

f64 limbs (DoubleType sort keys ride a raw-float limb) are compared
with plain </==, which matches the ``lax.sort`` comparator for the
values the encoding admits (NaNs are canonicalized out of the raw limb
upstream; ±0.0 compare equal in both).
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp

# tiles per bucket: enough locality to shrink the per-sort problem,
# few enough that the t² rank-count passes stay a small static unroll
_TILES = 8
# below this the tiling bookkeeping costs more than the sort
_MIN_ROWS = 4 * _TILES


def _pick_tiles(n: int) -> int:
    """Largest power-of-two tile count ≤ _TILES dividing n (1 = don't
    tile).  Static: capacities are pow2 buckets or sums of them."""
    if n < _MIN_ROWS:
        return 1
    t = _TILES
    while t > 1 and n % t:
        t >>= 1
    return t


def _tile_count(table: List[jnp.ndarray], queries: List[jnp.ndarray],
                le: jnp.ndarray) -> jnp.ndarray:
    """Rows of one sorted tile preceding each query row.

    Lexicographic fixed-step bisection over the tile's limbs;
    ``le[q]`` switches that query to upper-bound counting (ties in
    earlier tiles precede — the stable-merge rule).
    """
    import math
    s = int(table[0].shape[0])
    steps = max(1, int(math.ceil(math.log2(max(s, 2)))) + 1)
    lo = jnp.zeros(queries[0].shape, jnp.int32)
    hi = jnp.full(queries[0].shape, s, jnp.int32)
    for _ in range(steps):
        mid = (lo + hi) >> 1
        midc = jnp.clip(mid, 0, s - 1)
        lt = jnp.zeros(queries[0].shape, jnp.bool_)
        eq = jnp.ones(queries[0].shape, jnp.bool_)
        for tl, ql in zip(table, queries):
            v = jnp.take(tl, midc)
            lt = lt | (eq & (v < ql))
            eq = eq & (v == ql)
        go_right = lt | (eq & le)
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right, hi, mid)
    return lo


def sort_perm(limbs: List[jnp.ndarray], backend: str = "jnp"
              ) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
    """Drop-in ``ops.ordering.sort_by_keys``: (sorted limbs, perm).

    ``backend``: "jnp" → the reference global sort; "fused"/"pallas" →
    tiled rank merge (pallas has no sort-specific kernel yet, so both
    name the tiled path).  The choice is static per kernel instance —
    no runtime fallback is needed because the tiled path is exact.
    """
    from spark_rapids_tpu.ops import ordering as ORD
    n = int(limbs[0].shape[0])
    t = _pick_tiles(n) if backend != "jnp" else 1
    if t == 1:
        return ORD.sort_by_keys(limbs)
    s = n // t
    gi = jnp.arange(n, dtype=jnp.int32).reshape(t, s)
    ops = tuple(l.reshape(t, s) for l in limbs) + (gi,)
    res = jax.lax.sort(ops, dimension=1, num_keys=len(limbs) + 1)
    tiled = [r for r in res[:-1]]          # [t, s] tile-sorted limbs
    gis = res[-1].reshape(-1)              # original index, tile order
    flat = [r.reshape(-1) for r in tiled]  # queries: every row, tile order
    qtile = jnp.arange(n, dtype=jnp.int32) // s
    rank = jnp.arange(n, dtype=jnp.int32) % s  # position in own tile
    for u in range(t):
        cnt = _tile_count([r[u] for r in tiled], flat, le=qtile > u)
        rank = rank + jnp.where(qtile == u, 0, cnt)
    # ranks are a bijection on [0, n): invert with one 2-operand sort
    _, perm = jax.lax.sort((rank, gis), num_keys=1)
    return [jnp.take(l, perm) for l in limbs], perm
