"""Result-cache plane — plan-signature query result caching.

PAPER.md's north-star workload (dashboard traffic from millions of
users) is overwhelmingly *repeated plans over slowly-changing data*.
This plane sits between the ``QueryServer``/``DataFrame.toArrow`` front
door and the ``QueryScheduler``: a query whose *result key* is already
resident is served host-side from an Arrow table — it never submits to
the scheduler and never acquires the device semaphore.

Three layers (docs/result_cache.md):

* **keying** (``cache/keys.py``) — result key = sha1(physical-plan
  fingerprint ⊕ result-affecting confs ⊕ input fingerprints).  The
  PR 7 plan signature is op+path+schema only; the result key folds in
  the expression detail (``node_string``), the confs that select a
  different compute path (``kernel.backend``, ``adaptive.*``,
  ``exchange.mode``, the shape-bucket ladder), and a fingerprint per
  input relation (content digest for in-memory tables, path+size+mtime
  for file scans).
* **fingerprints** (``cache/fingerprints.py``) — the registration /
  bump chokepoint for input fingerprints.  The ``cache-safety`` lint
  rule flags catalog or fingerprint mutation anywhere else.
* **store** (``cache/store.py``) — byte-budgeted LRU + TTL store of
  host/Arrow-resident entries with single-flight de-duplication,
  automatic supersede-invalidation when an input fingerprint changes,
  and a subplan mode that caches materialized exchange outputs under
  subtree signatures so partially-overlapping queries reuse shared
  stages.

Conf surface: ``spark.rapids.tpu.cache.{enabled,maxBytes,ttlMs,
minRuntimeMs,subplan.enabled}``.  Observability:
``tpuq_result_cache_*`` counters + the ``tpuq_result_cache_resident_
bytes`` gauge, ``entry["cache"]`` in the query event log,
``session.cache_stats()``, and ``profile top --cache``.
"""

from __future__ import annotations

import threading
from typing import Optional

from spark_rapids_tpu.cache.keys import ResultKey, result_key, subplan_key
from spark_rapids_tpu.cache.store import ResultCache
from spark_rapids_tpu.runtime.telemetry import REGISTRY

__all__ = ["ResultKey", "ResultCache", "result_key", "subplan_key",
           "configure", "get_cache", "peek_cache", "subplan_store",
           "reset"]

# process-telemetry family (docs/observability.md)
HITS = REGISTRY.counter(
    "tpuq_result_cache_hits_total",
    "queries served from the result cache (device never touched)")
MISSES = REGISTRY.counter(
    "tpuq_result_cache_misses_total",
    "cache-enabled queries that had to execute")
EVICTIONS = REGISTRY.counter(
    "tpuq_result_cache_evictions_total",
    "entries dropped by LRU byte pressure or TTL expiry")
INVALIDATIONS = REGISTRY.counter(
    "tpuq_result_cache_invalidations_total",
    "entries dropped because an input fingerprint changed or an "
    "explicit invalidate_cache() matched")
BYTES = REGISTRY.counter(
    "tpuq_result_cache_bytes_total",
    "Arrow bytes served from the result cache on hits")

_lock = threading.Lock()
_store: Optional[ResultCache] = None


def _resident_bytes() -> float:
    s = _store
    return float(s.resident_bytes()) if s is not None else 0.0


REGISTRY.gauge("tpuq_result_cache_resident_bytes",
               "Arrow bytes currently resident in the result cache",
               fn=_resident_bytes)


def configure(conf) -> Optional[ResultCache]:
    """Create (or retune) the process result cache from a conf
    snapshot.  Entries survive a retune — two sessions with different
    kernel backends share one store and key separately; only the
    byte/TTL budgets and the subplan conf fingerprint follow the most
    recent session."""
    from spark_rapids_tpu import conf as C
    from spark_rapids_tpu.cache import keys as K
    global _store
    if not conf.get(C.CACHE_ENABLED):
        return _store
    with _lock:
        if _store is None:
            _store = ResultCache(
                max_bytes=int(conf.get(C.CACHE_MAX_BYTES)),
                ttl_ms=float(conf.get(C.CACHE_TTL_MS)),
                min_runtime_ms=float(conf.get(C.CACHE_MIN_RUNTIME_MS)),
                subplan_enabled=bool(conf.get(C.CACHE_SUBPLAN_ENABLED)))
        else:
            _store.retune(
                max_bytes=int(conf.get(C.CACHE_MAX_BYTES)),
                ttl_ms=float(conf.get(C.CACHE_TTL_MS)),
                min_runtime_ms=float(conf.get(C.CACHE_MIN_RUNTIME_MS)),
                subplan_enabled=bool(conf.get(C.CACHE_SUBPLAN_ENABLED)))
        _store.subplan_conf_fp = K.conf_fingerprint(conf)
        return _store


def get_cache(conf) -> Optional[ResultCache]:
    """The store serving this conf snapshot — None when
    ``spark.rapids.tpu.cache.enabled`` is off."""
    from spark_rapids_tpu import conf as C
    if not conf.get(C.CACHE_ENABLED):
        return None
    return configure(conf)


def peek_cache() -> Optional[ResultCache]:
    """Observation only — never creates."""
    return _store


def subplan_store() -> Optional[ResultCache]:
    """The store, iff subplan (exchange-output) caching is on — the
    exchange execs' gate."""
    s = _store
    return s if s is not None and s.subplan_enabled else None


def reset() -> None:
    """Drop the process store and the fingerprint registry (tests)."""
    from spark_rapids_tpu.cache import fingerprints
    global _store
    with _lock:
        _store = None
    fingerprints.reset()
