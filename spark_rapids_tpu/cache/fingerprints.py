"""Input fingerprints — the bump chokepoint for the result cache.

A result key is only sound if every input relation contributes a
fingerprint that changes whenever its *contents* can have changed.
This module is the single place those fingerprints are minted and
bumped:

* in-memory tables — a content digest over the Arrow IPC stream,
  computed once per ``pa.Table`` *object* (id-keyed, weakref-cleaned)
  and re-minted when a table is re-registered under a catalog name;
* file scans — a digest over the sorted (path, size, mtime_ns) stat
  tuples, recomputed at every key derivation so an in-place rewrite
  (mtime bump) yields a fresh key without any registration step.

The ``cache-safety`` lint rule (utils/lint/cache_safety.py) flags any
code outside this module / the session catalog that mutates a catalog
entry or assigns a relation fingerprint — mutating a registered table
behind the registry's back is exactly the bug class that serves stale
results.
"""

from __future__ import annotations

import hashlib
import os
import threading
import weakref
from typing import Dict, Iterable, List, Optional, Set, Tuple

import pyarrow as pa

__all__ = ["table_fingerprint", "bump_table_fingerprint",
           "file_fingerprint", "relation_inputs", "physical_inputs",
           "reset"]

# id(table) -> (weakref to the table, fingerprint).  RLock: weakref
# cleanup callbacks can fire on this thread mid-update if a gc cycle
# collects a dead table while we hold the lock.
_lock = threading.RLock()
_table_fps: Dict[int, Tuple[weakref.ref, str]] = {}


def _content_fingerprint(table: pa.Table) -> str:
    """Digest of the canonical Arrow IPC serialization — stable across
    chunking/slicing layouts that a raw buffer walk would distinguish."""
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as writer:
        writer.write_table(table)
    h = hashlib.sha1()
    h.update(memoryview(sink.getvalue()))
    return "t" + h.hexdigest()[:15]


def _register(table: pa.Table, bump: bool) -> str:
    key = id(table)
    with _lock:
        ent = _table_fps.get(key)
        if ent is not None and ent[0]() is table and not bump:
            return ent[1]
    fp = _content_fingerprint(table)

    def _drop(ref, _key=key):
        with _lock:
            cur = _table_fps.get(_key)
            if cur is not None and cur[0] is ref:
                del _table_fps[_key]

    with _lock:
        _table_fps[key] = (weakref.ref(table, _drop), fp)
    return fp


def table_fingerprint(table: pa.Table) -> str:
    """Content fingerprint for a table, memoized per object identity."""
    return _register(table, bump=False)


def bump_table_fingerprint(table: pa.Table) -> str:
    """Re-mint the fingerprint (re-registration chokepoint).  Called by
    ``TpuSession.registerTable`` when a name is re-bound, so a mutated
    pandas→Arrow reimport under the same name can never alias the old
    digest even if the interpreter reuses the object id."""
    return _register(table, bump=True)


def file_fingerprint(paths: Iterable[str]) -> str:
    """Stat digest over (path, size, mtime_ns) — raises ``OSError`` for
    missing paths; callers treat that as an unkeyable plan."""
    h = hashlib.sha1()
    for p in sorted(paths):
        st = os.stat(p)
        h.update(f"{p}:{st.st_size}:{st.st_mtime_ns}".encode())
    return "f" + h.hexdigest()[:15]


def relation_inputs(plan) -> Tuple[List[str], Set[str]]:
    """(input fingerprints, catalog source names) for a *logical* plan.

    In-memory relations carry their fingerprint on the node (assigned
    here — the only assignment site outside tests); file relations are
    re-statted every call so staleness is caught at lookup time.
    """
    from spark_rapids_tpu.plan.logical import InMemoryRelation, ParquetRelation

    fps: List[str] = []
    sources: Set[str] = set()

    def walk(n) -> None:
        if isinstance(n, InMemoryRelation):
            fp = n.fingerprint
            if fp is None:
                fp = table_fingerprint(n.table)
                n.fingerprint = fp
            fps.append(fp)
            if n.source:
                sources.add(n.source)
        elif isinstance(n, ParquetRelation):
            fps.append(file_fingerprint(list(n.paths)))
        for c in n.children:
            walk(c)

    walk(plan)
    return fps, sources


def physical_inputs(node) -> List[str]:
    """Input fingerprints for a *physical* subtree (subplan keys): scan
    execs hold either a ``.table`` (in-memory) or ``.paths`` (files)."""
    fps: List[str] = []

    def walk(n) -> None:
        t = getattr(n, "table", None)
        if isinstance(t, pa.Table):
            fps.append(table_fingerprint(t))
        paths = getattr(n, "paths", None)
        if isinstance(paths, (list, tuple)) and paths and all(
                isinstance(p, str) for p in paths):
            fps.append(file_fingerprint(list(paths)))
        for c in n.children:
            walk(c)

    walk(node)
    return fps


def reset() -> None:
    """Clear the registry (tests)."""
    with _lock:
        _table_fps.clear()
