"""Result-key derivation.

The PR 7 plan signature (``stats.plan_signature``) is deliberately
coarse — op class + tree path + schema fields — so profile-store
records of the same plan *shape* compare across runs.  A cache key has
the opposite requirement: it must distinguish anything that can change
the answer.  Three components are folded together:

* **plan fingerprint** — pre-order walk of the *physical* plan using
  ``node_string()`` (which carries expression detail: ``Filter
  [ (x > 1) ]`` vs ``Filter [ (x > 2) ]``) plus schema fields and the
  CPU/TPU placement marker;
* **conf fingerprint** — the curated list of result-affecting entries
  (kernel backend, adaptive plane, exchange mode, shape-bucket ladder,
  ANSI, partitioning) plus any per-tenant raw overrides, so two
  backends or two tenants never share a slot;
* **input fingerprints** — one per leaf relation, minted by
  ``cache/fingerprints.py``.

``sha1(plan ⊕ conf)`` is also kept separately (``plan_conf``): when a
later store sees the same plan+conf with *different* input
fingerprints it supersedes — that is the automatic
fingerprint-change invalidation path.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import List, Optional, Tuple

from spark_rapids_tpu.cache import fingerprints
from spark_rapids_tpu.runtime import stats

__all__ = ["ResultKey", "result_key", "subplan_key", "conf_fingerprint",
           "plan_fingerprint"]


def _sha(s: str, n: int = 16) -> str:
    return hashlib.sha1(s.encode()).hexdigest()[:n]


@dataclasses.dataclass(frozen=True)
class ResultKey:
    """Everything the store needs to file and later invalidate a result."""

    key: str                    # full result key (plan ⊕ conf ⊕ inputs)
    plan_conf: str              # plan ⊕ conf only — supersede axis
    sig: str                    # PR 7 root signature — attribution axis
    inputs: Tuple[str, ...]     # input fingerprints (invalidation axis)
    sources: Tuple[str, ...]    # catalog names feeding the plan
    tenant: Optional[str]


def _result_conf_entries():
    """The curated result-affecting entry list (satellite bugfix: the
    raw PR 7 signature would alias results across these)."""
    from spark_rapids_tpu import conf as C
    return (
        C.SQL_ENABLED, C.ANSI_ENABLED, C.BATCH_ROWS, C.MIN_BUCKET_ROWS,
        C.SHUFFLE_PARTITIONS, C.SHUFFLE_MODE, C.EXCHANGE_MODE,
        C.KERNEL_BACKEND, C.KERNEL_BUCKETING, C.KERNEL_BUCKET_LADDER,
        C.KERNEL_MAX_PAD_FRACTION,
        C.ADAPTIVE_ENABLED, C.ADAPTIVE_PLANE_ENABLED,
        C.ADAPTIVE_JOIN_STRATEGY, C.ADAPTIVE_SKEW_SPLIT,
        C.ADAPTIVE_SKEW_THRESHOLD, C.ADAPTIVE_MAX_SPLITS,
        C.ADAPTIVE_BATCH_RETARGET,
    )


def conf_fingerprint(conf, tenant: Optional[str] = None) -> str:
    parts: List[str] = [
        f"{e.key}={conf.get(e)!r}" for e in _result_conf_entries()]
    if tenant:
        prefix = f"spark.rapids.tpu.scheduler.tenant.{tenant}."
        parts.append(f"tenant={tenant}")
        parts.extend(f"{k}={v!r}"
                     for k, v in sorted(conf.raw_prefix(prefix).items()))
    return _sha("|".join(parts), 12)


def plan_fingerprint(node) -> str:
    """Detailed pre-order fingerprint of a physical (sub)tree."""
    parts: List[str] = []

    def walk(n, path: str) -> None:
        try:
            fields = ",".join(n.schema.field_names())
        except Exception:
            fields = ""
        parts.append(f"{path}/{n.node_string()}({fields})")
        for i, c in enumerate(n.children):
            walk(c, f"{path}.{i}")

    walk(node, "0")
    return _sha("|".join(parts), 16)


def result_key(logical_plan, physical_plan, conf,
               tenant: Optional[str] = None) -> ResultKey:
    """Derive the full result key for a query about to execute.

    Raises (``OSError`` from a stat, anything from an exotic plan) if
    any input cannot be fingerprinted — callers treat that as
    uncacheable and execute normally.
    """
    pfp = plan_fingerprint(physical_plan)
    cfp = conf_fingerprint(conf, tenant)
    fps, sources = fingerprints.relation_inputs(logical_plan)
    plan_conf = _sha(f"{pfp}|{cfp}", 16)
    key = _sha(f"{plan_conf}|{'|'.join(fps)}", 16)
    sig = stats.plan_signature(physical_plan.name, "0",
                               physical_plan.schema)
    return ResultKey(key=key, plan_conf=plan_conf, sig=sig,
                     inputs=tuple(fps), sources=tuple(sorted(sources)),
                     tenant=tenant)


def subplan_key(exchange_node, conf_fp: str) -> ResultKey:
    """Key for a materialized exchange output: detailed subtree
    fingerprint ⊕ the owning session's conf fingerprint ⊕ the physical
    leaves' input fingerprints.  Prefixed so result and subplan entries
    can never collide in the shared store."""
    pfp = plan_fingerprint(exchange_node)
    fps = fingerprints.physical_inputs(exchange_node)
    plan_conf = "sub:" + _sha(f"{pfp}|{conf_fp}", 16)
    key = "sub:" + _sha(f"{plan_conf}|{'|'.join(fps)}", 16)
    from spark_rapids_tpu.adaptive.cost_model import subtree_signature
    sig = subtree_signature(exchange_node)
    return ResultKey(key=key, plan_conf=plan_conf, sig=sig,
                     inputs=tuple(fps), sources=(), tenant=None)
