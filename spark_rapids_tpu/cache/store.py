"""Byte-budgeted LRU + TTL store for cached query results.

Entries are host/Arrow-resident — serving a hit is a dictionary move
plus a table handoff, never a device transfer, so a hit acquires
nothing device-side.  One store holds both full results (``pa.Table``
values) and subplan/exchange payloads (keys prefixed ``sub:``) under a
single byte budget.

Invalidation surfaces, most to least specific:

* **supersede** — ``put`` drops any entry with the same ``plan_conf``
  (plan ⊕ conf) but a different full key: the inputs changed under the
  same query, so the old answer is stale (the *automatic* invalidation
  path for bumped fingerprints);
* **explicit** — ``invalidate(source=... / fingerprint=... /
  signature=... / everything=True)`` from
  ``session.invalidate_cache``;
* **TTL** — an expired entry found at lookup counts as an eviction;
* **LRU** — byte pressure evicts from the cold end.

Single-flight: concurrent executions of the same key elect one leader
via ``join_flight``; followers wait on its Event and re-lookup, so N
identical submissions compute once.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["CacheEntry", "Flight", "ResultCache"]


class CacheEntry:
    __slots__ = ("key", "value", "nbytes", "sig", "plan_conf", "inputs",
                 "sources", "tenant", "runtime_s", "created",
                 "last_used", "hits", "kind")

    def __init__(self, key: str, value: Any, nbytes: int, *, sig: str,
                 plan_conf: str, inputs: Tuple[str, ...],
                 sources: Tuple[str, ...], tenant: Optional[str],
                 runtime_s: float, kind: str):
        self.key = key
        self.value = value
        self.nbytes = int(nbytes)
        self.sig = sig
        self.plan_conf = plan_conf
        self.inputs = inputs
        self.sources = sources
        self.tenant = tenant
        self.runtime_s = float(runtime_s)
        self.created = time.monotonic()
        self.last_used = self.created
        self.hits = 0
        self.kind = kind


class Flight:
    """One in-progress computation of a key (single-flight election).

    ``leader_qid`` is stamped by the winning query so followers can
    tell when the leader has been preempted and break away instead of
    holding their run slots hostage to a suspended computation."""

    __slots__ = ("key", "done", "leader_qid")

    def __init__(self, key: str):
        self.key = key
        self.done = threading.Event()
        self.leader_qid: Optional[int] = None


class ResultCache:
    def __init__(self, max_bytes: int, ttl_ms: float,
                 min_runtime_ms: float, subplan_enabled: bool):
        self._lock = threading.RLock()
        self.max_bytes = int(max_bytes)
        self.ttl_ms = float(ttl_ms)
        self.min_runtime_ms = float(min_runtime_ms)
        self.subplan_enabled = bool(subplan_enabled)
        # conf fingerprint of the most recently configured session —
        # the conf axis for subplan keys (exchanges have no conf).
        self.subplan_conf_fp = ""
        self._entries: "OrderedDict[str, CacheEntry]" = OrderedDict()
        self._flights: Dict[str, Flight] = {}
        self._bytes = 0
        self._counts = {
            "hits": 0, "misses": 0, "stored": 0, "evictions": 0,
            "invalidations": 0, "bytes_served": 0,
            "device_seconds_avoided": 0.0,
            "sub_hits": 0, "sub_misses": 0, "sub_stored": 0,
        }

    def retune(self, max_bytes: int, ttl_ms: float, min_runtime_ms: float,
               subplan_enabled: bool) -> None:
        with self._lock:
            self.max_bytes = int(max_bytes)
            self.ttl_ms = float(ttl_ms)
            self.min_runtime_ms = float(min_runtime_ms)
            self.subplan_enabled = bool(subplan_enabled)
            self._evict_to(self.max_bytes)

    # -- internal (lock held) -------------------------------------------

    def _remove(self, key: str) -> Optional[CacheEntry]:
        ent = self._entries.pop(key, None)
        if ent is not None:
            self._bytes -= ent.nbytes
        return ent

    def _evict_to(self, budget: int) -> int:
        n = 0
        while self._entries and self._bytes > budget:
            k = next(iter(self._entries))
            self._remove(k)
            n += 1
        if n:
            self._counts["evictions"] += n
            self._count_evictions(n)
        return n

    def _count_evictions(self, n: int) -> None:
        from spark_rapids_tpu import cache as cache_mod
        cache_mod.EVICTIONS.inc(n)

    def _expired(self, ent: CacheEntry) -> bool:
        return (self.ttl_ms > 0
                and (time.monotonic() - ent.created) * 1000.0
                > self.ttl_ms)

    # -- lookup / store -------------------------------------------------

    def lookup(self, key: str) -> Optional[CacheEntry]:
        """Hit-counting lookup: a live entry is a hit (LRU-refreshed);
        an expired entry counts as an eviction.  Misses are NOT counted
        here — a single-flight follower probes twice but a query is one
        hit or one miss, so the caller reports the miss exactly once
        via ``note_miss`` when it actually computes."""
        from spark_rapids_tpu import cache as cache_mod
        sub = key.startswith("sub:")
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None and self._expired(ent):
                self._remove(key)
                self._counts["evictions"] += 1
                self._count_evictions(1)
                ent = None
            if ent is not None:
                self._entries.move_to_end(key)
                ent.last_used = time.monotonic()
                ent.hits += 1
                self._counts["sub_hits" if sub else "hits"] += 1
                self._counts["bytes_served"] += ent.nbytes
                self._counts["device_seconds_avoided"] += ent.runtime_s
        if ent is not None and not sub:
            cache_mod.HITS.inc()
            cache_mod.BYTES.inc(ent.nbytes)
        return ent

    def note_miss(self, sub: bool = False) -> None:
        """One computed (non-served) cache-enabled query."""
        from spark_rapids_tpu import cache as cache_mod
        with self._lock:
            self._counts["sub_misses" if sub else "misses"] += 1
        if not sub:
            cache_mod.MISSES.inc()

    def peek(self, key: str) -> Optional[CacheEntry]:
        """Non-counting, non-refreshing probe (server admission check)."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None and self._expired(ent):
                return None
            return ent

    def put(self, rk, value: Any, nbytes: int, runtime_s: float,
            kind: str = "result") -> Dict[str, Any]:
        """File a computed result under its ResultKey.  Returns a
        status dict destined for the query log's ``entry["cache"]``."""
        from spark_rapids_tpu import cache as cache_mod
        nbytes = int(nbytes)
        if kind == "result" and runtime_s * 1000.0 < self.min_runtime_ms:
            return {"status": "uncached", "reason": "below_min_runtime"}
        if nbytes > self.max_bytes:
            return {"status": "uncached", "reason": "over_budget"}
        superseded = 0
        with self._lock:
            stale = [k for k, e in self._entries.items()
                     if e.plan_conf == rk.plan_conf and k != rk.key]
            for k in stale:
                self._remove(k)
            superseded = len(stale)
            if superseded:
                self._counts["invalidations"] += superseded
            self._remove(rk.key)
            self._evict_to(self.max_bytes - nbytes)
            ent = CacheEntry(rk.key, value, nbytes, sig=rk.sig,
                             plan_conf=rk.plan_conf, inputs=rk.inputs,
                             sources=rk.sources, tenant=rk.tenant,
                             runtime_s=runtime_s, kind=kind)
            self._entries[rk.key] = ent
            self._bytes += nbytes
            self._counts["sub_stored" if kind == "subplan"
                         else "stored"] += 1
        if superseded:
            cache_mod.INVALIDATIONS.inc(superseded)
        return {"status": "stored", "superseded": superseded}

    # -- single-flight --------------------------------------------------

    def join_flight(self, key: str) -> Tuple[str, Flight]:
        """('leader', flight) for the first caller of a key; everyone
        else gets ('follower', the leader's flight) to wait on."""
        with self._lock:
            fl = self._flights.get(key)
            if fl is None:
                fl = Flight(key)
                self._flights[key] = fl
                return "leader", fl
            return "follower", fl

    def finish_flight(self, key: str, flight: Flight) -> None:
        """Leader's finally-block: wake followers whether or not the
        computation stored (they re-lookup and fall back to computing)."""
        with self._lock:
            if self._flights.get(key) is flight:
                del self._flights[key]
        flight.done.set()

    # -- invalidation ---------------------------------------------------

    def invalidate(self, *, key: Optional[str] = None,
                   source: Optional[str] = None,
                   fingerprint: Optional[str] = None,
                   signature: Optional[str] = None,
                   everything: bool = False) -> int:
        from spark_rapids_tpu import cache as cache_mod
        with self._lock:
            if everything:
                doomed = list(self._entries)
            else:
                doomed = [
                    k for k, e in self._entries.items()
                    if (key is not None and k == key)
                    or (source is not None and source in e.sources)
                    or (fingerprint is not None
                        and fingerprint in e.inputs)
                    or (signature is not None and e.sig == signature)]
            for k in doomed:
                self._remove(k)
            n = len(doomed)
            if n:
                self._counts["invalidations"] += n
        if n:
            cache_mod.INVALIDATIONS.inc(n)
        return n

    # -- observation ----------------------------------------------------

    def resident_bytes(self) -> int:
        return self._bytes

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            hits = self._counts["hits"]
            misses = self._counts["misses"]
            by_sig: Dict[str, Dict[str, Any]] = {}
            for e in self._entries.values():
                d = by_sig.setdefault(
                    e.sig, {"entries": 0, "bytes": 0, "hits": 0})
                d["entries"] += 1
                d["bytes"] += e.nbytes
                d["hits"] += e.hits
            return {
                "entries": len(self._entries),
                "resident_bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "ttl_ms": self.ttl_ms,
                "hit_rate": (hits / (hits + misses)
                             if hits + misses else 0.0),
                "by_signature": by_sig,
                **dict(self._counts),
            }

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._entries)
