"""QueryServer — the multi-tenant serving front door over a session.

``TpuSession`` executes one query per caller thread; the server turns
that into a *service*: many concurrent ``submit`` calls across named
tenants, each admission-checked and fairness-scheduled by
``runtime/scheduler.py`` before it may touch the device.  The flow per
submission:

1. ``submit`` mints the query id and its ``CancelToken`` (deadline
   ticking from SUBMIT time — queue time counts against it) and
   registers the token, so ``session.cancel(qid)`` and per-tenant
   ``active_queries`` work while the query is still QUEUED.
2. The scheduler admits (or raises ``QueryRejected(reason=...)`` —
   quota breach or load shed; nothing was started, retry/back off).
3. A worker thread blocks in ``scheduler.acquire`` until the fairness
   dispatcher grants a run slot, then runs ``DataFrame.toArrow`` which
   adopts the server's query id and token.
4. ``poll``/``result`` observe completion; ``release`` in the worker's
   ``finally`` hands the slot to the next waiter no matter how the
   query ended.

See docs/serving.md for the admission-state walkthrough and tuning
guide.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Union

from spark_rapids_tpu.runtime.scheduler import (  # re-exported API
    QueryRejected, get_scheduler, peek_scheduler)

#: handle states reported by ``poll``
QUEUED = "QUEUED"
RUNNING = "RUNNING"
OK = "OK"
CANCELLED = "CANCELLED"
ERROR = "ERROR"


class QueryHandle:
    """One submission's future.  ``done`` is set exactly once, after
    the run slot has been released and the token unregistered — a
    ``result()`` returner can immediately submit a follow-up without
    racing the slot it just freed."""

    __slots__ = ("query_id", "tenant", "priority", "token", "ticket",
                 "done", "result", "error", "state", "submitted_at",
                 "queue_wait_s", "wall_s")

    def __init__(self, query_id: int, tenant: str, priority: int,
                 token, ticket):
        self.query_id = query_id
        self.tenant = tenant
        self.priority = priority
        self.token = token
        self.ticket = ticket
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.state = QUEUED
        self.submitted_at = time.monotonic()
        self.queue_wait_s: Optional[float] = None
        self.wall_s: Optional[float] = None


class QueryServer:
    """Accepts concurrent query submissions for one ``TpuSession``.

    A submission is either a ``DataFrame`` or a zero-arg callable
    returning one.  Prefer the callable for concurrent load: it is
    invoked on the admitted worker thread, so plan construction happens
    per-execution and per-DataFrame caches (``_last_plan`` etc.) are
    not raced by overlapping runs of the SAME DataFrame object.

    ``warmup_plans`` (DataFrames, or callables taking the session)
    name the shapes the server expects to serve; when
    ``spark.rapids.tpu.kernel.warmupOnStart`` is on (default) they run
    through ``session.warmup`` at construction — so the op x bucket
    matrix compiles BEFORE the first tenant submission, outside any
    query's telemetry window, and (with kernel.cacheDir set) the
    executables persist for the next server process.
    """

    def __init__(self, session, warmup_plans=None, scheduler=None):
        from spark_rapids_tpu import conf as C
        self.session = session
        # an explicit scheduler pins this server to it (the cluster
        # tenancy soak hosts several executors in one process, each
        # with its own non-singleton scheduler); None = the process
        # singleton, as before
        self._scheduler = scheduler
        self._lock = threading.Lock()
        self._handles: Dict[int, QueryHandle] = {}
        self._threads: List[threading.Thread] = []
        self._closed = False
        self.warmup_report: Optional[dict] = None
        conf = session.rapids_conf()
        if warmup_plans and conf.get(C.KERNEL_WARMUP_ON_START):
            self.warmup_report = session.warmup(warmup_plans)

    # -- submission --------------------------------------------------------

    def submit(self, query: Union[Callable, object],
               tenant: str = "default", priority: int = 0,
               timeout_ms: Optional[float] = None) -> QueryHandle:
        """Admit one query for ``tenant``.  Returns a ``QueryHandle``
        immediately (the query is queued or already running) or raises
        ``QueryRejected(reason=...)`` without side effects.  Higher
        ``priority`` drains first within the tenant; ``timeout_ms``
        deadlines the query from NOW — time spent queued counts, so a
        deadline can expire a query that was never admitted.

        An out-of-range ``priority`` is rejected here with
        ``QueryRejected(reason='bad_priority')`` — at the door, before
        any token is minted or scheduler state touched."""
        from spark_rapids_tpu import conf as C
        from spark_rapids_tpu.runtime import cancel
        from spark_rapids_tpu.runtime import scheduler as sched_mod
        from spark_rapids_tpu.runtime import trace
        with self._lock:
            if self._closed:
                raise QueryRejected("server_shutdown", tenant=tenant,
                                    detail="QueryServer.shutdown() ran")
        priority = sched_mod.check_priority(priority, tenant)
        conf = self.session.rapids_conf()
        qid = trace.next_query_id()
        eff = (timeout_ms if timeout_ms is not None
               else float(conf.get(C.QUERY_TIMEOUT_MS)))
        if eff is not None and eff <= 0:
            eff = None
        token = cancel.CancelToken(
            qid, timeout_ms=eff,
            poll_ms=float(conf.get(C.CANCEL_POLL_MS)))
        token.tenant = tenant   # HBM arbiter charges this tenant
        cancel.register(token)
        # result-cache admission check: a DataFrame submission whose
        # result key is already resident is served on THIS thread —
        # it never enters the scheduler, holds no run slot, and
        # touches no device state.  (Callable submissions build their
        # plan on the worker, so their cache probe happens inside
        # toArrow instead — a hit still releases the run slot in
        # microseconds.)
        if not callable(query):
            hit = self._try_serve_cached(query, qid, token, tenant,
                                         priority, conf)
            if hit is not None:
                return hit
        sched = (self._scheduler if self._scheduler is not None
                 else get_scheduler(conf))
        try:
            ticket = sched.submit(qid, tenant=tenant, priority=priority,
                                  token=token)
        except BaseException:
            cancel.unregister(token)
            raise
        handle = QueryHandle(qid, tenant, priority, token, ticket)
        with self._lock:
            self._handles[qid] = handle
        worker = threading.Thread(target=self._run, args=(handle, query),
                                  name=f"tpuq-serve-{qid}", daemon=True)
        with self._lock:
            self._threads.append(worker)
            self._threads = [t for t in self._threads if t.is_alive()
                             or t is worker]
        worker.start()
        return handle

    def _try_serve_cached(self, df, qid: int, token, tenant: str,
                          priority: int, conf) -> Optional[QueryHandle]:
        """Serve a submission from the result cache without admission.

        Probes non-destructively (``peek``); on a resident key, runs
        ``toArrow`` synchronously — the probe guarantees it resolves as
        a hit short of a racing eviction, in which case the query
        computes here without a run slot but still under the device
        semaphore.  Returns None on miss (normal admission proceeds).
        """
        from spark_rapids_tpu import cache as cache_mod
        from spark_rapids_tpu import conf as C
        from spark_rapids_tpu.runtime import cancel
        if not conf.get(C.CACHE_ENABLED):
            return None
        store = cache_mod.get_cache(conf)
        try:
            plan = df._execute_plan()
            ckey = cache_mod.result_key(df._plan, plan, conf,
                                        tenant=tenant)
        except Exception:
            return None
        if store.peek(ckey.key) is None:
            return None
        handle = QueryHandle(qid, tenant, priority, token, ticket=None)
        try:
            handle.state = RUNNING
            handle.result = df.toArrow(query_id=qid, cancel_token=token,
                                       tenant=tenant)
            handle.state = OK
        except cancel.QueryCancelled as e:
            handle.error = e
            handle.state = CANCELLED
        except BaseException as e:
            handle.error = e
            handle.state = ERROR
        finally:
            handle.queue_wait_s = 0.0
            handle.wall_s = time.monotonic() - handle.submitted_at
            cancel.unregister(token)
            handle.done.set()
        return handle

    def _run(self, handle: QueryHandle, query) -> None:
        from spark_rapids_tpu.runtime import cancel
        sched = (self._scheduler if self._scheduler is not None
                 else peek_scheduler())
        t0 = time.monotonic()
        df = None
        try:
            handle.queue_wait_s = sched.acquire(handle.ticket)
            handle.state = RUNNING
            df = query() if callable(query) else query
            handle.result = df.toArrow(query_id=handle.query_id,
                                       cancel_token=handle.token,
                                       tenant=handle.tenant)
            handle.state = OK
        except cancel.QueryCancelled as e:
            handle.error = e
            if handle.state == QUEUED:
                # died while still queued for a run slot — toArrow
                # never ran, so the dataframe-side black-box hook never
                # fired; leave a queue-side box where the entire wall
                # is queue wait
                self._dump_queued_blackbox(handle, e, t0)
            handle.state = CANCELLED
        except BaseException as e:
            handle.error = e
            handle.state = ERROR
        finally:
            handle.wall_s = time.monotonic() - t0
            sched.release(handle.ticket)
            cancel.unregister(handle.token)
            with self._lock:
                self._handles.pop(handle.query_id, None)
            if handle.state == OK:
                self._record_latency(sched, handle, df)
            handle.done.set()

    def _record_latency(self, sched, handle: QueryHandle, df) -> None:
        """Feed a completed query's submit-to-done wall into the
        tenant's SLO estimator; on the un-breached -> breached
        transition the scheduler returns a breach record and the
        server leaves an ``slo``-triggered black box naming the
        offending dominant bucket."""
        entry = getattr(df, "_last_query_entry", None) or {}
        att = entry.get("attribution") or {}
        try:
            breach = sched.record_latency(
                handle.tenant, handle.wall_s,
                buckets=att.get("buckets"),
                query_id=handle.query_id)
        except Exception:
            return
        if not breach:
            return
        from spark_rapids_tpu import conf as C
        from spark_rapids_tpu.runtime import attribution
        conf = self.session.rapids_conf()
        if not conf.get(C.ATTRIBUTION_ENABLED):
            return
        bb_dir = str(conf.get(C.ATTRIBUTION_BLACKBOX_PATH))
        if not bb_dir:
            return
        attribution.dump_blackbox(
            bb_dir, handle.query_id, "slo",
            attribution=att or None,
            extra={"status": "ok", "tenant": handle.tenant,
                   "slo_breach": breach},
            max_dumps=int(conf.get(C.ATTRIBUTION_BLACKBOX_MAX)))

    def _dump_queued_blackbox(self, handle: QueryHandle, exc,
                              t0: float) -> None:
        """Black box for a query killed before admission (deadline or
        cancel fired while QUEUED): no tracer ever ran, so the ledger
        is built from the one fact the server owns — the whole wall
        was queue wait."""
        from spark_rapids_tpu import conf as C
        from spark_rapids_tpu.runtime import attribution
        conf = self.session.rapids_conf()
        if not conf.get(C.ATTRIBUTION_ENABLED):
            return
        bb_dir = str(conf.get(C.ATTRIBUTION_BLACKBOX_PATH))
        if not bb_dir:
            return
        waited = time.monotonic() - t0
        att = attribution.attribute(
            spans=(), e2e_s=0.0,
            tolerance=float(conf.get(C.ATTRIBUTION_CLOSE_TOLERANCE)),
            extras={"queue_wait": waited})
        trigger = ("timeout" if getattr(exc, "reason", "") == "deadline"
                   else "cancel")
        attribution.dump_blackbox(
            bb_dir, handle.query_id, trigger, attribution=att,
            extra={"status": "cancelled", "tenant": handle.tenant,
                   "cancel": {"reason": getattr(exc, "reason", "user"),
                              "while": "QUEUED"}},
            max_dumps=int(conf.get(C.ATTRIBUTION_BLACKBOX_MAX)))

    # -- observation -------------------------------------------------------

    def poll(self, handle: QueryHandle) -> dict:
        """Non-blocking status snapshot."""
        return {"query_id": handle.query_id,
                "tenant": handle.tenant,
                "state": handle.state,
                "done": handle.done.is_set(),
                "queue_wait_s": handle.queue_wait_s,
                "wall_s": handle.wall_s}

    def result(self, handle: QueryHandle,
               timeout_s: Optional[float] = None):
        """Block until the query finishes and return its Arrow table;
        re-raises the query's ``QueryCancelled``/error.  ``timeout_s``
        bounds the wait (``TimeoutError``) without affecting the query
        itself."""
        if not handle.done.wait(timeout=timeout_s):
            raise TimeoutError(
                f"query {handle.query_id} still {handle.state} after "
                f"{timeout_s}s")
        if handle.error is not None:
            raise handle.error
        return handle.result

    def cancel(self, query_id: int, reason: str = "user") -> bool:
        """Cancel a submitted query — queued or running.  A queued
        query surfaces ``QueryCancelled`` within ~one poll interval
        WITHOUT ever being admitted; its queue entry is removed and the
        dispatcher moves on."""
        from spark_rapids_tpu.runtime import cancel
        return cancel.cancel_query(query_id, reason=reason)

    def active_queries(self, tenant: Optional[str] = None) -> List[int]:
        """Queued + running query ids, optionally one tenant's."""
        sched = (self._scheduler if self._scheduler is not None
                 else peek_scheduler())
        if sched is None:
            return []
        return sched.active_queries(tenant)

    def stats(self) -> Dict[str, dict]:
        """Per-tenant scheduler accounting (see
        ``QueryScheduler.stats``)."""
        sched = (self._scheduler if self._scheduler is not None
                 else peek_scheduler())
        return sched.stats() if sched is not None else {}

    # -- lifecycle ---------------------------------------------------------

    def shutdown(self, timeout_s: float = 30.0,
                 cancel_pending: bool = True) -> None:
        """Stop accepting submissions; optionally cancel everything
        outstanding; join workers.  Idempotent."""
        with self._lock:
            self._closed = True
            handles = list(self._handles.values())
            threads = list(self._threads)
            self._threads = []
        if cancel_pending:
            from spark_rapids_tpu.runtime import cancel
            for h in handles:
                cancel.cancel_query(h.query_id, reason="user",
                                    detail="server shutdown")
        deadline = time.monotonic() + timeout_s
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
