"""TpuSession — the SparkSession-analog entry point.

The reference is a plugin into an existing engine; this framework carries a
minimal session so the plugin machinery (conf, plan rewrite, fallback
reporting) has an engine to plug into.  Conf surface and behavior mirror
``spark.rapids.*`` [REF: sql-plugin/../RapidsConf.scala].
"""

from __future__ import annotations

import datetime
import decimal
from typing import Any, Dict, Iterable, List, Optional, Sequence, Union

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.runtime.device import ensure_initialized


class RuntimeConf:
    """Mutable session conf view (spark.conf analog)."""

    def __init__(self, raw: Dict[str, Any]):
        self._raw = dict(raw)

    def set(self, key: str, value) -> None:
        self._raw[str(key)] = value

    def get(self, key: str, default=None):
        return self._raw.get(key, default)

    def unset(self, key: str) -> None:
        self._raw.pop(key, None)

    def snapshot(self) -> RapidsConf:
        return RapidsConf(self._raw)


class TpuSessionBuilder:
    def __init__(self):
        self._conf: Dict[str, Any] = {}

    def config(self, key=None, value=None, conf: Optional[Dict] = None
               ) -> "TpuSessionBuilder":
        if conf:
            self._conf.update(conf)
        if key is not None:
            self._conf[key] = value
        return self

    def getOrCreate(self) -> "TpuSession":
        return TpuSession(self._conf)


def _decompose_structs(table: pa.Table):
    """Flatten arrow STRUCT columns into per-field physical columns
    ('s.a', 's.b' [+ 's#null' when the struct has nulls]) — the engine's
    struct-of-arrays data model; the DataFrame layer keeps the logical
    view and toArrow reassembles [REF: cuDF struct columns /
    complexTypeCreator — here structs never reach a kernel at all]."""
    if not any(pa.types.is_struct(f.type) for f in table.schema):
        return table, {}
    from spark_rapids_tpu.sql.dataframe import StructSpec
    arrays, names = [], []
    structs: Dict[str, object] = {}
    for name in table.column_names:
        col = table.column(name)
        t = col.type
        if not pa.types.is_struct(t):
            arrays.append(col)
            names.append(name)
            continue
        if any(pa.types.is_struct(t.field(i).type)
               or pa.types.is_map(t.field(i).type)
               for i in range(t.num_fields)):
            raise NotImplementedError(
                f"struct column {name!r}: nested struct/map fields are "
                "not supported yet (one level of struct nesting)")
        arr = col.combine_chunks()
        null_col = None
        if arr.null_count > 0:
            null_col = f"{name}#null"
            arrays.append(pa.chunked_array([arr.is_null()]))
            names.append(null_col)
        flat = arr.flatten()  # parent nulls applied to children
        fields = []
        for i in range(t.num_fields):
            f = t.field(i)
            pname = f"{name}.{f.name}"
            arrays.append(flat[i])
            names.append(pname)
            fields.append((f.name, pname))
        structs[name] = StructSpec(fields, null_col)
    return pa.table(dict(zip(names, arrays))), structs


def _infer_arrow_type(values: List[Any]) -> pa.DataType:
    """Scan ALL values (pyspark-style): int → int64 (LongType), numeric
    int/float mixes promote to float64."""
    saw_int = saw_float = False
    for v in values:
        if v is None:
            continue
        if isinstance(v, bool):
            return pa.bool_()
        if isinstance(v, int):
            saw_int = True
            continue
        if isinstance(v, float):
            saw_float = True
            continue
        if isinstance(v, str):
            return pa.string()
        if isinstance(v, bytes):
            return pa.binary()
        if isinstance(v, decimal.Decimal):
            return pa.decimal128(18, max(0, -v.as_tuple().exponent))
        if isinstance(v, datetime.datetime):
            return pa.timestamp("us", tz="UTC")
        if isinstance(v, datetime.date):
            return pa.date32()
    if saw_float:
        return pa.float64()
    if saw_int:
        return pa.int64()
    return pa.int32()


class _BuilderDescriptor:
    """Class-level ``TpuSession.builder`` (SparkSession.builder idiom)."""

    def __get__(self, obj, objtype=None) -> TpuSessionBuilder:
        return TpuSessionBuilder()


class TpuSession:
    builder = _BuilderDescriptor()

    # session-held query records beyond this cap evict oldest-first;
    # the JSONL query log keeps the full record
    QUERY_HISTORY_CAP = 256

    def __init__(self, conf: Optional[Dict[str, Any]] = None):
        self.conf = RuntimeConf(conf or {})
        self._query_history: List[Dict[str, Any]] = []
        # multi-executor mode joins the global mesh FIRST:
        # jax.distributed.initialize must run before anything touches
        # the XLA backend [REF: RapidsExecutorPlugin.init]
        from spark_rapids_tpu.parallel.executor import init_executor
        init_executor(self.conf.snapshot())
        ensure_initialized()
        # continuous telemetry: starts the background sampler when
        # spark.rapids.tpu.telemetry.enabled (registry updates always)
        from spark_rapids_tpu.runtime import telemetry
        telemetry.configure_sampler(self.conf.snapshot())
        # conf-gated lock-order watchdog (spark.rapids.tpu.lockdep.*)
        from spark_rapids_tpu.runtime import lockdep
        lockdep.configure(self.conf.snapshot())
        # shape plane: batch-shape bucketing policy for every exec pump
        # (spark.rapids.tpu.kernel.bucketing/bucketLadder/maxPadFraction)
        from spark_rapids_tpu.runtime import shapes
        shapes.configure(self.conf.snapshot())
        # kernel plane: fused-kernel backend + double-buffered pump
        # (spark.rapids.tpu.kernel.backend, spark.rapids.tpu.exec.pumpDepth)
        from spark_rapids_tpu import kernels
        kernels.configure(self.conf.snapshot())
        # persistent (on-disk) XLA compilation cache
        # (spark.rapids.tpu.kernel.cacheDir; no-op on the CPU backend)
        from spark_rapids_tpu.runtime import kernel_cache
        kernel_cache.configure_persistent_cache(self.conf.snapshot())
        # result-cache plane (spark.rapids.tpu.cache.*): host-resident
        # plan-signature result cache served ahead of the scheduler
        from spark_rapids_tpu import cache as cache_mod
        cache_mod.configure(self.conf.snapshot())
        # name -> (table, fingerprint): registered-table catalog backing
        # registerTable()/table(); mutated only through registerTable —
        # the cache-safety lint rule flags writes anywhere else
        self._catalog: Dict[str, Any] = {}

    # -- observability ------------------------------------------------------
    def _record_query(self, entry: Dict[str, Any]) -> None:
        self._query_history.append(entry)
        if len(self._query_history) > self.QUERY_HISTORY_CAP:
            del self._query_history[:-self.QUERY_HISTORY_CAP]

    def query_history(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        """Event-log entries for queries this session has executed,
        oldest first (same schema as the ``spark.rapids.sql.queryLog``
        JSONL records).  ``n`` limits to the most recent n."""
        h = self._query_history
        return list(h[-n:] if n else h)

    def last_query_profile(self) -> Optional[Dict[str, Any]]:
        """Structured stats-plane profile of the most recent query run
        with ``spark.rapids.tpu.stats.enabled``: the same record
        ``df.explain("analyze")`` renders and the profile store persists
        — per-operator rows/batches/bytes, batch-shape histograms,
        per-partition exchange counts with skew factors, and traced
        self/total time when tracing was on.  None until a query has
        executed with stats collection."""
        return getattr(self, "_last_profile", None)

    # -- query lifecycle ----------------------------------------------------
    def active_queries(self, tenant: Optional[str] = None) -> List[int]:
        """Ids of queries currently executing or queued (cancellable).
        ``tenant`` filters to one tenant's queries via the scheduler
        (queries submitted through a ``QueryServer``); without it,
        every registered cancellable query is listed — including
        server-submitted queries still waiting for a run slot, whose
        tokens are registered at submit time."""
        from spark_rapids_tpu.runtime import cancel
        if tenant is not None:
            from spark_rapids_tpu.runtime import scheduler
            sched = scheduler.peek_scheduler()
            return sched.active_queries(tenant) if sched is not None else []
        return cancel.active_queries()

    def cancel(self, query_id: Optional[int] = None,
               reason: str = "user") -> bool:
        """Cancel an in-flight query: every blocking boundary of its
        execution raises ``QueryCancelled`` within ~2x
        ``spark.rapids.tpu.query.cancelPollMs`` and the engine reclaims
        the query's resources.  With no ``query_id``, cancels the
        oldest active query.  Returns False when nothing matched."""
        from spark_rapids_tpu.runtime import cancel
        if query_id is None:
            active = cancel.active_queries()
            if not active:
                return False
            query_id = active[0]
        return cancel.cancel_query(query_id, reason=reason)

    def warmup(self, plans: Iterable[Any]) -> Dict[str, Any]:
        """Pre-compile the kernels a set of plans will need.

        ``plans`` is an iterable of DataFrames (or callables taking this
        session and returning one — handy for conf-parameterized plan
        builders).  Each plan is planned and every partition drained
        through the full exec pipeline, so the op x schema x bucket
        matrix the plan touches compiles NOW — and, with
        ``spark.rapids.tpu.kernel.cacheDir`` set, lands in the on-disk
        cache for future processes.

        Deliberately OUTSIDE the query-window machinery ``toArrow``
        runs: compiles triggered here never enter any query's telemetry
        delta, so the compile-storm health check (which diffs per-query
        counter windows) sees a clean hot path afterwards — warming up
        is not a storm.  Results are discarded; only compilation state
        survives.

        Returns ``{"plans", "compiles", "compile_seconds", "wall_s"}``.
        """
        import time as _time
        from spark_rapids_tpu.runtime import kernel_cache
        t0 = _time.perf_counter()
        c0, s0 = kernel_cache.compile_snapshot()
        count = 0
        for p in plans:
            df = p(self) if callable(p) else p
            plan = df._execute_plan()
            for part in range(plan.num_partitions()):
                for _ in plan.execute(part):
                    pass
            count += 1
        c1, s1 = kernel_cache.compile_snapshot()
        return {"plans": count,
                "compiles": c1 - c0,
                "compile_seconds": round(s1 - s0, 6),
                "wall_s": round(_time.perf_counter() - t0, 6)}

    def metrics_report(self) -> Dict[str, Any]:
        """Point-in-time process telemetry: every registry counter/gauge
        value and histogram summary (the same values the JSONL sink and
        Prometheus dump export) plus recent health WARN events."""
        import time as _time
        from spark_rapids_tpu.runtime import telemetry
        telemetry.ensure_producers()
        return {"ts": _time.strftime("%Y-%m-%dT%H:%M:%S%z"),
                "metrics": telemetry.REGISTRY.snapshot(),
                "health": telemetry.REGISTRY.recent_health()}

    # -- data ingestion -----------------------------------------------------
    def createDataFrame(self, data, schema=None) -> "DataFrame":
        from spark_rapids_tpu.plan.logical import InMemoryRelation
        from spark_rapids_tpu.sql.dataframe import DataFrame

        table = self._to_arrow(data, schema)
        table, structs = _decompose_structs(table)
        st = T.StructType(tuple(
            T.StructField(n, T.from_arrow(table.schema.field(n).type))
            for n in table.column_names))
        nparts = int(self.conf.get("spark.default.parallelism", 1))
        return DataFrame(self, InMemoryRelation(table, st, nparts),
                         structs)

    # -- catalog + result cache ---------------------------------------------
    def registerTable(self, name: str, data, schema=None) -> "DataFrame":
        """Register (or re-register) a named table in the session
        catalog.  This is the fingerprint-bump chokepoint for in-memory
        inputs: re-registering a name re-mints the content digest and
        drops every cached result that read the old version, so a
        refreshed table can never serve stale hits."""
        from spark_rapids_tpu import cache as cache_mod
        from spark_rapids_tpu.cache import fingerprints

        table = self._to_arrow(data, schema)
        table, structs = _decompose_structs(table)
        rebind = name in self._catalog
        fp = (fingerprints.bump_table_fingerprint(table) if rebind
              else fingerprints.table_fingerprint(table))
        self._catalog[name] = (table, structs, fp)
        if rebind:
            store = cache_mod.peek_cache()
            if store is not None:
                store.invalidate(source=name)
        return self.table(name)

    def table(self, name: str) -> "DataFrame":
        """A DataFrame over a catalog table registered with
        ``registerTable`` — its relation carries the content
        fingerprint, so results derived from it are cache-keyed."""
        from spark_rapids_tpu.plan.logical import InMemoryRelation
        from spark_rapids_tpu.sql.dataframe import DataFrame
        if name not in self._catalog:
            raise KeyError(f"table {name!r} is not registered")
        table, structs, fp = self._catalog[name]
        st = T.StructType(tuple(
            T.StructField(n, T.from_arrow(table.schema.field(n).type))
            for n in table.column_names))
        nparts = int(self.conf.get("spark.default.parallelism", 1))
        rel = InMemoryRelation(table, st, nparts,
                               fingerprint=fp, source=name)
        return DataFrame(self, rel, structs)

    def invalidate_cache(self, name: Optional[str] = None, *,
                         signature: Optional[str] = None,
                         fingerprint: Optional[str] = None) -> int:
        """Explicitly drop cached results: by catalog ``name``, plan
        ``signature``, input ``fingerprint``, or — with no arguments —
        everything.  Returns the number of entries dropped."""
        from spark_rapids_tpu import cache as cache_mod
        store = cache_mod.peek_cache()
        if store is None:
            return 0
        if name is None and signature is None and fingerprint is None:
            return store.invalidate(everything=True)
        return store.invalidate(source=name, signature=signature,
                                fingerprint=fingerprint)

    def cache_stats(self) -> Dict[str, Any]:
        """Result-cache observability: counts, hit rate, resident
        bytes, device-seconds avoided, and a per-signature breakdown
        (the same numbers ``profile top --cache`` reports)."""
        from spark_rapids_tpu import cache as cache_mod
        from spark_rapids_tpu import conf as C
        store = cache_mod.peek_cache()
        # the store is a process singleton — THIS session's conf decides
        # whether its queries participate, so a cache-off session must
        # not report a co-resident session's store as its own
        if store is None or not self.rapids_conf().get(C.CACHE_ENABLED):
            return {"enabled": False}
        return {"enabled": True, **store.stats()}

    def _to_arrow(self, data, schema) -> pa.Table:
        if isinstance(data, pa.Table):
            return data
        if hasattr(data, "to_arrow"):  # pandas-ish escape hatch
            return data.to_arrow()
        if hasattr(data, "__dataframe__") or str(type(data)).endswith(
                "DataFrame'>"):
            return pa.Table.from_pandas(data)
        rows = list(data)
        if schema is not None and isinstance(schema, (list, tuple)) and rows:
            names = list(schema)
            cols = list(zip(*rows)) if rows else [[] for _ in names]
            arrays = [pa.array(list(c), type=_infer_arrow_type(list(c)))
                      for c in cols]
            return pa.table(arrays, names=names)
        if isinstance(schema, T.StructType):
            names = schema.field_names()
            cols = list(zip(*rows)) if rows else [[] for _ in names]
            arrays = [
                pa.array(list(c), type=T.to_arrow(f.dtype))
                for c, f in zip(cols, schema.fields)
            ]
            return pa.table(arrays, names=names)
        raise TypeError(
            "createDataFrame expects a pyarrow.Table, pandas DataFrame, or "
            "list of tuples with a schema (list of names or StructType)")

    def range(self, start: int, end: Optional[int] = None,
              step: int = 1, numPartitions: Optional[int] = None
              ) -> "DataFrame":
        """Generated id column — lands as a device iota, no host data
        [REF: basicPhysicalOperators.scala :: GpuRangeExec]."""
        from spark_rapids_tpu.plan.logical import Range
        from spark_rapids_tpu.sql.dataframe import DataFrame
        if end is None:
            start, end = 0, start
        nparts = numPartitions or int(
            self.conf.get("spark.default.parallelism", 1))
        schema = T.StructType((T.StructField("id", T.LongT, False),))
        return DataFrame(self, Range(int(start), int(end), int(step),
                                     schema, nparts))

    @property
    def read(self):
        from spark_rapids_tpu.io.readers import DataFrameReader
        return DataFrameReader(self)

    def rapids_conf(self) -> RapidsConf:
        return self.conf.snapshot()

    def stop(self):
        pass
