"""pyspark.sql.window-compatible WindowSpec surface.

[REF: sql-plugin/../GpuWindowExec.scala — plan surface; the spec object
itself mirrors pyspark.sql.Window]
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Tuple

from spark_rapids_tpu.sql.column import Column, UExpr, _to_uexpr


@dataclasses.dataclass(frozen=True)
class WindowSpec:
    partition_by: Tuple[UExpr, ...] = ()
    order_by: Tuple[UExpr, ...] = ()
    # frame: None = Spark default (RANGE unbounded-preceding..current when
    # ordered, whole partition otherwise); or ("rows", lo, hi)
    frame: object = None

    def partitionBy(self, *cols) -> "WindowSpec":
        return dataclasses.replace(
            self, partition_by=self.partition_by + tuple(
                _col_u(c) for c in cols))

    def orderBy(self, *cols) -> "WindowSpec":
        return dataclasses.replace(
            self, order_by=self.order_by + tuple(
                _col_u(c) for c in cols))

    def rowsBetween(self, start: int, end: int) -> "WindowSpec":
        return dataclasses.replace(self, frame=("rows", start, end))

    def rangeBetween(self, start: int, end: int) -> "WindowSpec":
        return dataclasses.replace(self, frame=("range", start, end))


def _col_u(c) -> UExpr:
    if isinstance(c, str):
        return UExpr("attr", c)
    return _to_uexpr(c)


class Window:
    """pyspark.sql.Window entry points."""

    unboundedPreceding = -sys.maxsize
    unboundedFollowing = sys.maxsize
    currentRow = 0

    @staticmethod
    def partitionBy(*cols) -> WindowSpec:
        return WindowSpec().partitionBy(*cols)

    @staticmethod
    def orderBy(*cols) -> WindowSpec:
        return WindowSpec().orderBy(*cols)
