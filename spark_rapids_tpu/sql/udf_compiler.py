"""Python UDF → device expression compiler.

[REF: udf-compiler/src/main/scala/com/nvidia/spark/udf ::
 CatalystExpressionBuilder, LambdaReflection; SURVEY §2.1 #27] — the
reference decompiles JVM bytecode of simple Scala lambdas into Catalyst
expressions so "UDFs" run as native GPU kernels.  The engine here is
Python, so the analog inspects the *source AST* of a Python lambda/def
and lowers it onto the engine's Expression tree — a compiled UDF never
crosses the arrow bridge at all; it fuses into the surrounding XLA
program like any built-in expression.

Supported subset (same spirit as the reference's opcode whitelist):
* arithmetic  + - * / % ** on arguments/constants
* comparisons  == != < <= > >=, boolean and/or/not
* conditional expressions  ``a if cond else b``
* calls to math functions  abs, min, max (2-arg)
* string methods  .upper() .lower() .strip()
* None-checks  ``x is None`` / ``x is not None``

Anything outside the subset raises ``UdfCompileError`` and the caller
falls back to the arrow-bridge UDF — opt-in via
``spark.rapids.sql.udfCompiler.enabled`` exactly like the reference.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from typing import Callable, Dict, List

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.ops import expressions as E
from spark_rapids_tpu.ops import strings as S


class UdfCompileError(Exception):
    pass


def _fn_ast(fn: Callable):
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError) as e:
        raise UdfCompileError(f"no source available: {e}")
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        # getsource returns a fragment for lambdas defined mid-expression
        raise UdfCompileError(f"source fragment does not parse: {e}")
    lambdas = [n for n in ast.walk(tree) if isinstance(n, ast.Lambda)]
    if getattr(fn, "__name__", "") == "<lambda>":
        if len(lambdas) != 1:
            # two lambdas on one source line: no way to tell which one
            # this function object is — compiling the wrong body would
            # be silent wrong results
            raise UdfCompileError(
                f"{len(lambdas)} lambdas share the source line; "
                "cannot disambiguate")
        return lambdas[0].args, lambdas[0].body
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            body = [st for st in node.body
                    if not isinstance(st, (ast.Expr,))
                    or not isinstance(st.value, ast.Constant)]
            if len(body) != 1 or not isinstance(body[0], ast.Return):
                raise UdfCompileError(
                    "only single-return functions compile")
            return node.args, body[0].value
    raise UdfCompileError("no lambda or def found in source")


_BINOPS = {
    ast.Add: E.Add, ast.Sub: E.Subtract, ast.Mult: E.Multiply,
}
_CMPOPS = {
    ast.Eq: E.EqualTo, ast.Lt: E.LessThan,
    ast.LtE: E.LessThanOrEqual, ast.Gt: E.GreaterThan,
    ast.GtE: E.GreaterThanOrEqual,
}


class _Lowerer:
    def __init__(self, params: Dict[str, E.Expression]):
        self.params = params

    def lower(self, node) -> E.Expression:
        from spark_rapids_tpu.plan.analysis import (
            cast_to, common_type, literal)
        if isinstance(node, ast.Name):
            if node.id not in self.params:
                raise UdfCompileError(f"free variable {node.id!r}")
            return self.params[node.id]
        if isinstance(node, ast.Constant):
            if node.value is None:
                return E.Literal(None, T.NullT)
            return literal(node.value)
        if isinstance(node, ast.BinOp):
            l, r = self.lower(node.left), self.lower(node.right)
            if isinstance(node.op, ast.Div):
                return E.Divide(cast_to(l, T.DoubleT),
                                cast_to(r, T.DoubleT))
            if isinstance(node.op, ast.Pow):
                return E.Pow(cast_to(l, T.DoubleT),
                             cast_to(r, T.DoubleT))
            if isinstance(node.op, ast.Mod):
                return self._py_mod(l, r)
            cls = _BINOPS.get(type(node.op))
            if cls is None:
                raise UdfCompileError(
                    f"operator {type(node.op).__name__} not supported")
            ct = common_type(l.dtype, r.dtype)
            return cls(cast_to(l, ct), cast_to(r, ct))
        if isinstance(node, ast.UnaryOp):
            if isinstance(node.op, ast.USub):
                return E.UnaryMinus(self.lower(node.operand))
            if isinstance(node.op, ast.Not):
                return E.Not(self._require_bool(
                    self.lower(node.operand), "not"))
            raise UdfCompileError("unary operator not supported")
        if isinstance(node, ast.Compare):
            if len(node.ops) != 1:
                raise UdfCompileError("chained comparisons")
            op, right = node.ops[0], node.comparators[0]
            if isinstance(op, (ast.Is, ast.IsNot)):
                if not (isinstance(right, ast.Constant)
                        and right.value is None):
                    raise UdfCompileError("'is' only against None")
                inner = E.IsNull(self.lower(node.left))
                return E.Not(inner) if isinstance(op, ast.IsNot) \
                    else inner
            l, r = self.lower(node.left), self.lower(right)
            ct = common_type(l.dtype, r.dtype)
            l, r = cast_to(l, ct), cast_to(r, ct)
            if isinstance(l.dtype, T.StringType):
                if isinstance(op, ast.NotEq):
                    return E.Not(S.string_comparison("eq", l, r))
                kinds = {ast.Eq: "eq", ast.Lt: "lt", ast.LtE: "le",
                         ast.Gt: "gt", ast.GtE: "ge"}
                return S.string_comparison(kinds[type(op)], l, r)
            if isinstance(op, ast.NotEq):
                return E.Not(E.EqualTo(l, r))
            cls = _CMPOPS.get(type(op))
            if cls is None:
                raise UdfCompileError(
                    f"comparison {type(op).__name__} not supported")
            return cls(l, r)
        if isinstance(node, ast.BoolOp):
            parts = [self._require_bool(self.lower(v), "and/or")
                     for v in node.values]
            cls = E.And if isinstance(node.op, ast.And) else E.Or
            out = parts[0]
            for p in parts[1:]:
                out = cls(out, p)
            return out
        if isinstance(node, ast.IfExp):
            cond = self._require_bool(self.lower(node.test), "if/else")
            t, f = self.lower(node.body), self.lower(node.orelse)
            ct = common_type(t.dtype, f.dtype)
            return E.CaseWhen([(cond, cast_to(t, ct))], cast_to(f, ct))
        if isinstance(node, ast.Call):
            return self._call(node)
        raise UdfCompileError(
            f"AST node {type(node).__name__} not supported")

    @staticmethod
    def _require_bool(e: E.Expression, where: str) -> E.Expression:
        """Python truthiness over non-booleans (`1 if x else 0` with int
        x) has no columnar equivalent — the device And/CaseWhen are
        bitwise.  Outside booleans → fall back to the bridge."""
        if not isinstance(e.dtype, (T.BooleanType, T.NullType)):
            raise UdfCompileError(
                f"non-boolean condition in {where} (python truthiness "
                "does not compile)")
        return e

    def _py_mod(self, l: E.Expression, r: E.Expression) -> E.Expression:
        """Python % (sign follows divisor) from the engine's Java-sign
        Remainder: rem + divisor when signs disagree and rem != 0.
        (x % 0: python raises, the compiled form is null — same
        error-vs-null caveat as null inputs, see module docstring.)"""
        from spark_rapids_tpu.plan.analysis import (
            cast_to, common_type, literal)
        ct = common_type(l.dtype, r.dtype)
        l, r = cast_to(l, ct), cast_to(r, ct)
        rem = E.Remainder(l, r)
        zero = cast_to(literal(0), ct)
        signs_differ = E.Or(
            E.And(E.LessThan(rem, zero), E.GreaterThan(r, zero)),
            E.And(E.GreaterThan(rem, zero), E.LessThan(r, zero)))
        return E.CaseWhen([(signs_differ, E.Add(rem, r))], rem)

    def _call(self, node: ast.Call) -> E.Expression:
        from spark_rapids_tpu.plan.analysis import cast_to, common_type
        if isinstance(node.func, ast.Attribute):
            target = self.lower(node.func.value)
            meth = node.func.attr
            if not isinstance(target.dtype, T.StringType):
                raise UdfCompileError(
                    f"method .{meth}() on non-string")
            if node.args or node.keywords:
                raise UdfCompileError(f".{meth}() with arguments")
            if meth == "upper":
                return S.Upper(target)
            if meth == "lower":
                return S.Lower(target)
            if meth == "strip":
                return S.Trim(target, "both")
            raise UdfCompileError(f"string method .{meth}()")
        if isinstance(node.func, ast.Name):
            name = node.func.id
            args = [self.lower(a) for a in node.args]
            if name == "abs" and len(args) == 1:
                return E.Abs(args[0])
            if name in ("min", "max") and len(args) == 2:
                ct = common_type(args[0].dtype, args[1].dtype)
                a, b = cast_to(args[0], ct), cast_to(args[1], ct)
                cond = (E.LessThanOrEqual(a, b) if name == "min"
                        else E.GreaterThanOrEqual(a, b))
                return E.CaseWhen([(cond, a)], b)
            if name in ("int", "float") and len(args) == 1:
                dt = T.LongT if name == "int" else T.DoubleT
                return cast_to(args[0], dt) if args[0].dtype != dt \
                    else args[0]
            raise UdfCompileError(f"call to {name}() not supported")
        raise UdfCompileError("unsupported call form")


def compile_udf(fn: Callable, args: List[E.Expression],
                result_dtype: T.DataType) -> E.Expression:
    """Lower fn(*args) onto the expression tree, cast to the declared
    return type.  Raises UdfCompileError when outside the subset."""
    from spark_rapids_tpu.plan.analysis import cast_to
    params, body = _fn_ast(fn)
    names = [a.arg for a in params.args]
    if params.vararg or params.kwonlyargs or params.kwarg:
        raise UdfCompileError("only plain positional parameters")
    if len(names) != len(args):
        raise UdfCompileError(
            f"UDF takes {len(names)} args, called with {len(args)}")
    expr = _Lowerer(dict(zip(names, args))).lower(body)
    if expr.dtype != result_dtype:
        # cast_to constant-folds Literal(None) onto the declared type,
        # so NullType results also land with the right column dtype
        expr = cast_to(expr, result_dtype)
    return expr
