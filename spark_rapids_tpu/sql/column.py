"""User-facing Column API — unresolved expression trees.

Mirrors pyspark's ``Column``: operator overloading builds an unresolved
tree; resolution against a schema (plan/analysis.py) produces bound, typed
``ops.expressions`` nodes with Spark's implicit-cast coercion applied.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple


@dataclasses.dataclass
class UExpr:
    """Unresolved expression node: (op, payload, children)."""

    op: str
    payload: Any = None
    children: Tuple["UExpr", ...] = ()

    def __str__(self):
        if self.op == "attr":
            return str(self.payload)
        if self.op == "lit":
            return repr(self.payload)
        return f"{self.op}({', '.join(str(c) for c in self.children)})"


def _to_uexpr(v) -> UExpr:
    if isinstance(v, Column):
        return v._u
    if isinstance(v, UExpr):
        return v
    return UExpr("lit", v)


class Column:
    def __init__(self, u: UExpr):
        self._u = u

    # arithmetic ----------------------------------------------------------
    def _bin(self, op, other, reverse=False):
        l, r = self._u, _to_uexpr(other)
        if reverse:
            l, r = r, l
        return Column(UExpr(op, None, (l, r)))

    def __add__(self, o):
        return self._bin("add", o)

    def __radd__(self, o):
        return self._bin("add", o, True)

    def __sub__(self, o):
        return self._bin("sub", o)

    def __rsub__(self, o):
        return self._bin("sub", o, True)

    def __mul__(self, o):
        return self._bin("mul", o)

    def __rmul__(self, o):
        return self._bin("mul", o, True)

    def __truediv__(self, o):
        return self._bin("div", o)

    def __rtruediv__(self, o):
        return self._bin("div", o, True)

    def __mod__(self, o):
        return self._bin("mod", o)

    def __neg__(self):
        return Column(UExpr("neg", None, (self._u,)))

    # comparisons ---------------------------------------------------------
    def __eq__(self, o):  # type: ignore[override]
        return self._bin("eq", o)

    def __ne__(self, o):  # type: ignore[override]
        return Column(UExpr("not", None, (self._bin("eq", o)._u,)))

    def __lt__(self, o):
        return self._bin("lt", o)

    def __le__(self, o):
        return self._bin("le", o)

    def __gt__(self, o):
        return self._bin("gt", o)

    def __ge__(self, o):
        return self._bin("ge", o)

    def eqNullSafe(self, o):
        return self._bin("eqns", o)

    # logic ---------------------------------------------------------------
    def __and__(self, o):
        return self._bin("and", o)

    def __rand__(self, o):
        return self._bin("and", o, True)

    def __or__(self, o):
        return self._bin("or", o)

    def __ror__(self, o):
        return self._bin("or", o, True)

    def __invert__(self):
        return Column(UExpr("not", None, (self._u,)))

    # misc ----------------------------------------------------------------
    def alias(self, name: str) -> "Column":
        return Column(UExpr("alias", name, (self._u,)))

    def cast(self, dtype) -> "Column":
        return Column(UExpr("cast", dtype, (self._u,)))

    def isNull(self) -> "Column":
        return Column(UExpr("isnull", None, (self._u,)))

    def isNotNull(self) -> "Column":
        return Column(UExpr("isnotnull", None, (self._u,)))

    def isNaN(self) -> "Column":
        return Column(UExpr("isnan", None, (self._u,)))

    def between(self, low, high) -> "Column":
        return (self >= low) & (self <= high)

    def getField(self, name: str) -> "Column":
        """Struct field access — rewritten to the flattened physical
        column by the DataFrame layer (structs are stored
        struct-of-arrays)."""
        return Column(UExpr("getfield", name, (self._u,)))

    def isin(self, *values) -> "Column":
        """Membership test [REF: Spark Column.isin / catalyst In] —
        lowered as an OR chain of equalities, which XLA fuses into one
        elementwise program (the device needs no dedicated In kernel)."""
        if len(values) == 1 and isinstance(values[0], (list, tuple, set)):
            values = tuple(values[0])
        if not values:
            from spark_rapids_tpu.sql.column import lit
            return lit(False)
        out = self == values[0]
        for v in values[1:]:
            out = out | (self == v)
        return out

    def when(self, cond: "Column", value) -> "Column":
        u = self._u
        if u.op != "casewhen" or u.payload == "closed":
            raise TypeError("when() only chains after functions.when(...) "
                            "and before otherwise()")
        return Column(UExpr("casewhen", u.payload,
                            u.children + (_to_uexpr(cond),
                                          _to_uexpr(value))))

    def otherwise(self, value) -> "Column":
        u = self._u
        if u.op != "casewhen" or u.payload == "closed":
            raise TypeError("otherwise() only follows when() and may "
                            "appear once")
        return Column(UExpr("casewhen", "closed",
                            u.children + (_to_uexpr(value),)))

    def over(self, window) -> "Column":
        """Attach a WindowSpec: F.row_number().over(w), F.sum(c).over(w)."""
        return Column(UExpr("window", window, (self._u,)))

    def asc(self) -> "Column":
        return Column(UExpr("sortorder", ("asc", "nulls_first"), (self._u,)))

    def desc(self) -> "Column":
        return Column(UExpr("sortorder", ("desc", "nulls_last"), (self._u,)))

    def asc_nulls_first(self) -> "Column":
        return self.asc()

    def asc_nulls_last(self) -> "Column":
        return Column(UExpr("sortorder", ("asc", "nulls_last"), (self._u,)))

    def desc_nulls_first(self) -> "Column":
        return Column(UExpr("sortorder", ("desc", "nulls_first"),
                            (self._u,)))

    def desc_nulls_last(self) -> "Column":
        return self.desc()

    def substr(self, start, length) -> "Column":
        return Column(UExpr("substring", (start, length), (self._u,)))

    def startswith(self, o) -> "Column":
        return self._bin("startswith", o)

    def endswith(self, o) -> "Column":
        return self._bin("endswith", o)

    def contains(self, o) -> "Column":
        return self._bin("contains", o)

    def like(self, pattern: str) -> "Column":
        """SQL LIKE ('%', '_', backslash escape), literal pattern."""
        return Column(UExpr("like", pattern, (self._u,)))

    def rlike(self, pattern: str) -> "Column":
        """Regex match (simple patterns run on device; the rest host)."""
        return Column(UExpr("rlike", pattern, (self._u,)))

    def __str__(self):
        return str(self._u)

    def __repr__(self):
        return f"Column<{self._u}>"

    def __hash__(self):
        return id(self)

    def __bool__(self):
        raise ValueError(
            "Cannot convert Column to bool: use '&' for 'and', '|' for "
            "'or', '~' for 'not' in DataFrame filter expressions.")


def col(name: str) -> Column:
    return Column(UExpr("attr", name))


def lit(value) -> Column:
    return Column(UExpr("lit", value))
